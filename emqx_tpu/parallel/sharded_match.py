"""Multi-chip match + table update over a (dp, sub) mesh.

Two styles, both idiomatic:

* The *match* path relies on XLA SPMD auto-partitioning: the dense
  predicate is elementwise over the [B, N] plane, so sharded inputs
  ([B]→'dp', [N]→'sub') partition it with zero communication; count
  reductions become one psum over 'sub' that XLA inserts on its own.
  (This replaces the reference's full-table replication + local match,
  emqx_router.erl:133-162 — ICI is fast enough to partition instead.)

* The *update* path (route add/delete deltas) uses shard_map because
  each 'sub' shard must translate global row ids into its local slice:
  every shard receives the same delta batch (deltas are tiny — ≤1024
  rows, mirroring emqx_router_syncer batches) and applies the rows it
  owns with a masked scatter; rows outside the shard drop out. This is
  the mria-rlog analog: one write stream, applied shard-locally.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports it at top level; 0.4.x under experimental
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map as _shard_map


def _shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """shard_map with the replication check disabled: the combine
    kernels' all_gather -> nonzero recompaction IS replicated over
    'sub' (every member computes from the identical gathered vector),
    but the static rep-inference can't see through the fixed-size
    nonzero. The kwarg spelling differs across jax versions."""
    try:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
    except TypeError:  # pragma: no cover - jax >= 0.7 spelling
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )

from ..obs.profiler import STAGE_MARK
from ..ops.match import EncodedTopics, _match_block, _pack_bits
from ..ops.table import EncodedFilters
from .mesh import DP_AXIS, SUB_AXIS, filter_sharding, topic_sharding


def make_sharded_kernels(mesh: Mesh):
    """Compile the mesh-partitioned kernels. Returns
    (match_counts, match_packed, apply_delta)."""

    f_shard = filter_sharding(mesh)
    t_shard = topic_sharding(mesh)
    counts_out = NamedSharding(mesh, P(DP_AXIS))
    packed_out = NamedSharding(mesh, P(DP_AXIS, SUB_AXIS))
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=counts_out,
    )
    def match_counts(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return ok.sum(axis=1, dtype=jnp.int32)  # XLA: psum over 'sub'

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=packed_out,
    )
    def match_packed(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return _pack_bits(ok)

    n_sub = mesh.shape[SUB_AXIS]

    def _apply_delta_local(dev: EncodedFilters, rows, words, plen, hh, rw, act):
        # dev leaves are the LOCAL shard [N/n_sub, ...]; rows are
        # GLOBAL ids with a leading delta-batch axis [n_b, K, ...] —
        # all batches apply inside ONE dispatch via scan (chained
        # dispatches do not pipeline through the device relay,
        # PERF_NOTES.md; same rule as the single-device _scatter_rows).
        local_n = dev.words.shape[0]
        offset = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32) * local_n

        def step(d, xs):
            r, w, p, h, rw_, a = xs
            local = r - offset
            # rows outside this shard scatter out of range -> dropped
            oob = (local < 0) | (local >= local_n)
            local = jnp.where(oob, local_n, local)
            return (
                EncodedFilters(
                    d.words.at[local].set(w, mode="drop"),
                    d.prefix_len.at[local].set(p, mode="drop"),
                    d.has_hash.at[local].set(h, mode="drop"),
                    d.root_wild.at[local].set(rw_, mode="drop"),
                    d.active.at[local].set(a, mode="drop"),
                ),
                None,
            )

        out, _ = jax.lax.scan(step, dev, (rows, words, plen, hh, rw, act))
        return out

    dev_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    # rows, words, plen, hh, rw, act — all replicated to every shard
    delta_specs = (
        P(None, None), P(None, None, None), P(None, None),
        P(None, None), P(None, None), P(None, None),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def apply_delta(
        dev: EncodedFilters,
        rows: jnp.ndarray,  # int32 [n_b, K] global row ids
        words: jnp.ndarray,  # int32 [n_b, K, L]
        plen: jnp.ndarray,
        hh: jnp.ndarray,
        rw: jnp.ndarray,
        act: jnp.ndarray,
    ) -> EncodedFilters:
        return _shard_map(
            _apply_delta_local,
            mesh=mesh,
            in_specs=(dev_specs,) + delta_specs,
            out_specs=dev_specs,
        )(dev, rows, words, plen, hh, rw, act)

    return match_counts, match_packed, apply_delta


def _combine_pairs(a, b, valid_key, mh):
    """Device-side cross-shard reduction: gather every shard's
    compacted [mh] buffers over 'sub' (tiled — one [n_sub*mh] vector,
    replicated across the axis by the collective) and recompact the
    valid entries into ONE [mh] result. This is the combine that used
    to run on host: the finish leg now fetches N-independent bytes and
    merges nothing. Safe under the same escalation contract — if the
    psum'd total fits mh then every per-shard count fit mh too, so the
    per-shard compaction upstream dropped nothing."""
    a_all = jax.lax.all_gather(a, SUB_AXIS, tiled=True)
    b_all = jax.lax.all_gather(b, SUB_AXIS, tiled=True)
    pos = jnp.nonzero(valid_key(a_all), size=mh, fill_value=-1)[0]
    pv = pos >= 0
    ps = jnp.maximum(pos, 0)
    ca = jnp.where(pv, a_all[ps], -1).astype(jnp.int32)
    cb = jnp.where(pv, b_all[ps], -1).astype(jnp.int32)
    return ca, cb


def make_combine_probe_kernel(mesh: Mesh, mh: int):
    """Combine-only probe for the mesh microscope (obs/mesh_scope.py):
    EXACTLY the cross-shard reduction of the match kernels
    (`_combine_pairs` over 'sub' plus the psum'd total) on synthetic
    per-shard buffers built on-device, so its device span isolates the
    `combine_collective` leg of a real dispatch without duplicating
    either match kernel — the reduction cost depends only on (n_sub,
    mh), which this probe shares with both the dense and hash paths.
    The salted scalar input keeps the gathered buffers from being
    constant-folded and defeats the relay's identical-computation
    memoization — every probe pays the real collective."""

    def _local(salt):
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        iot = jnp.arange(mh, dtype=jnp.int32)
        # one salted valid entry per shard — occupancy does not change
        # the gather cost (the buffers are flat [n_sub*mh] either way)
        a = jnp.where(iot == 0, salt + sub_i + 1, -1)
        b = jnp.where(iot == 0, salt * 2 + 1, -1)
        ca, cb = _combine_pairs(a, b, lambda t: t >= 0, mh)
        total = jax.lax.psum((a >= 0).sum(dtype=jnp.int32), SUB_AXIS)
        return ca[None, :], cb[None, :], total.reshape(1, 1)

    @jax.jit
    def probe(salt):
        return _shard_map_unchecked(
            _local,
            mesh=mesh,
            in_specs=(P(),),
            out_specs=(
                P(DP_AXIS, None), P(DP_AXIS, None), P(DP_AXIS, None),
            ),
        )(salt)

    return probe


def make_match_ids_kernel(mesh: Mesh, max_hits_per_block: int):
    """Sharded compaction kernel with DEVICE-SIDE combine: every
    (dp, sub) block matches its LOCAL [B/dp, N/sub] tile, compacts its
    hits to fixed-size (topic, row) id buffers with GLOBAL indices
    (axis_index offsets), then the shards reduce over 'sub' on-device
    (all_gather + recompaction, totals via psum) so ONE dispatch
    returns ONE combined buffer whose transfer size is independent of
    the shard count — the multi-chip version of ops.match.match_ids
    without the per-shard host merge that inverted the scaling curve
    (PERF_NOTES.md r15). Returns (ti [dp, mh], ri [dp, mh],
    totals [dp, 1]); slots are -1 beyond each dp block's true count,
    and a block whose total exceeds max_hits_per_block overflowed
    (caller escalates)."""

    f_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    t_specs = EncodedTopics(P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS))
    mh = max_hits_per_block

    def _local(ids, lens, dollar, words, plen, hh, rw, act):
        dp_i = jax.lax.axis_index(DP_AXIS).astype(jnp.int32)
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        ok = _match_block(ids, lens, dollar, words, plen, hh, rw, act)
        b_loc, n_loc = ok.shape
        cnt = ok.sum(dtype=jnp.int32)
        idx = jnp.nonzero(ok.reshape(-1), size=mh, fill_value=-1)[0]
        valid = idx >= 0
        ti = jnp.where(valid, idx // n_loc + dp_i * b_loc, -1).astype(jnp.int32)
        ri = jnp.where(valid, idx % n_loc + sub_i * n_loc, -1).astype(jnp.int32)
        cti, cri = _combine_pairs(ti, ri, lambda t: t >= 0, mh)
        total = jax.lax.psum(cnt, SUB_AXIS)
        return cti[None, :], cri[None, :], total.reshape(1, 1)

    @jax.jit
    def match_ids(filters: EncodedFilters, topics: EncodedTopics):
        return _shard_map_unchecked(
            _local,
            mesh=mesh,
            in_specs=(
                t_specs.ids, t_specs.lens, t_specs.dollar,
                f_specs.words, f_specs.prefix_len, f_specs.has_hash,
                f_specs.root_wild, f_specs.active,
            ),
            out_specs=(
                P(DP_AXIS, None),
                P(DP_AXIS, None),
                P(DP_AXIS, None),
            ),
        )(
            topics.ids, topics.lens, topics.dollar,
            filters.words, filters.prefix_len, filters.has_hash,
            filters.root_wild, filters.active,
        )

    return match_ids


def make_sharded_hash_kernel(
    mesh: Mesh, max_hits_per_block: int, n_buckets: Optional[int] = None
):
    """The PRODUCTION pattern-class cuckoo kernel, bucket-partitioned
    over the 'sub' axis (VERDICT r2 #2: the mesh must run the 67x hash
    path, not the dense demo). Each shard owns a contiguous bucket
    range of the global table; it probes only the candidate buckets
    that fall inside its slice, so a pair whose b1/b2 land on
    different shards is served by both — each emits its own candidate
    with the GLOBAL bucket id, and the host union (plus its oracle
    verify) merges them. Meta and the per-(topic,class) hash mixing
    are replicated (B×C u32 ops — cheap); the O(table) state is what
    partitions, exactly the HBM-capacity reason to go multi-chip.

    Returns kernel(meta, slots, topics) ->
    (ti [dp, mh], bi [dp, mh], totals [dp, 1], amb [1,1]): the
    candidates are combined ON-DEVICE over 'sub' (all_gather +
    recompaction, same reduction as make_match_ids_kernel) so the
    fetch is one shard-count-independent buffer; totals are the
    psum'd flagged-pair counts for escalation, amb the mesh-wide
    ambiguity (see ops.hash_index.match_ids_hash).

    `n_buckets` is the LOGICAL global bucket count (pow2 — the host
    index's n_buckets). It must be passed whenever the per-shard slice
    carries trailing pad buckets (an N-1 survivor mesh, where n_sub no
    longer divides the pow2 count): the hash mask is `n_buckets - 1`,
    NOT `nb_loc * n_sub - 1`, and pad buckets are simply never probed
    because every b1/b2 lands below n_buckets. None keeps the
    divisible-layout default (nb_loc * n_sub)."""
    from ..ops.hash_index import BUCKET_W, _ALT_MUL, _FP_CLS, _FP_MUL
    from ..ops.hash_index import _FP_SEED, _FP_XOR, _H1_CLS, _H1_MUL, _H1_SEED

    mh = max_hits_per_block
    meta_specs = (P(None),) * 5
    slot_specs = (P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS))
    t_specs = (P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS))
    n_sub = mesh.shape[SUB_AXIS]  # static (jax.lax.axis_size is >=0.5)

    def _local(plen, has_hash, root_wild, plus, active, sfp, sbkt, probe,
               ids, lens, dollar):
        dp_i = jax.lax.axis_index(DP_AXIS).astype(jnp.int32)
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        b_loc, max_levels = ids.shape
        c = plen.shape[0]
        nb_loc = probe.shape[0]
        nb_global = n_buckets if n_buckets is not None else nb_loc * n_sub
        tl = lens[:, None]
        pl = plen[None, :]
        len_ok = jnp.where(has_hash[None, :], tl >= pl, tl == pl)
        elig = len_ok & active[None, :] & ~(
            dollar[:, None] & root_wild[None, :]
        )
        cids = jnp.arange(c, dtype=jnp.uint32)
        h1 = jnp.broadcast_to(
            jnp.uint32(_H1_SEED) ^ (cids * jnp.uint32(_H1_CLS)), (b_loc, c)
        )
        fp = jnp.broadcast_to(
            jnp.uint32(_FP_SEED) + (cids * jnp.uint32(_FP_CLS)), (b_loc, c)
        )
        for i in range(max_levels):
            lit = (i < plen) & (((plus >> i) & 1) == 0)
            x = jnp.where(
                lit[None, :],
                ids[:, i : i + 1].astype(jnp.uint32) + 1,
                jnp.uint32(0),
            )
            h1 = (h1 ^ x) * jnp.uint32(_H1_MUL)
            fp = (fp ^ (x * jnp.uint32(_FP_XOR))) * jnp.uint32(_FP_MUL)
        mask = jnp.uint32(nb_global - 1)
        b1 = h1 & mask
        b2 = b1 ^ (((fp | jnp.uint32(1)) * jnp.uint32(_ALT_MUL)) & mask)
        off = (sub_i * nb_loc).astype(jnp.int32)
        p8 = jnp.maximum(fp >> jnp.uint32(24), jnp.uint32(1))
        rep = p8 * jnp.uint32(0x01010101)

        def local_hit(b):
            lb = b.astype(jnp.int32) - off
            inside = (lb >= 0) & (lb < nb_loc)
            w = probe[jnp.clip(lb, 0, nb_loc - 1)]
            x = w ^ rep
            hz = ((x - jnp.uint32(0x01010101)) & ~x
                  & jnp.uint32(0x80808080)) != 0
            return inside & hz, lb, w

        hit1, l1, wp1 = local_hit(b1)
        hit2, l2, wp2 = local_hit(b2)
        pairhit = elig & (hit1 | hit2)
        total = pairhit.sum(dtype=jnp.int32)
        pflat = jnp.nonzero(
            pairhit.reshape(-1), size=mh, fill_value=-1
        )[0]
        pvalid = pflat >= 0
        psafe = jnp.maximum(pflat, 0)
        ph1 = hit1.reshape(-1)[psafe]
        ph2 = hit2.reshape(-1)[psafe]
        pl1 = l1.reshape(-1)[psafe]
        pl2 = l2.reshape(-1)[psafe]
        pfp = fp.reshape(-1)[psafe]
        pw1 = wp1.reshape(-1)[psafe]
        pw2 = wp2.reshape(-1)[psafe]
        # two-lane sparse verify (mirrors match_ids_hash phase 2): the
        # probe words pin the candidate lanes exactly; verify the
        # first two LOCAL byte-matching lanes, route >2 to amb. Lane
        # validity folds the shard-ownership mask per bucket.
        pp8 = jnp.maximum(pfp >> jnp.uint32(24), jnp.uint32(1))
        lid = jnp.arange(2 * BUCKET_W, dtype=jnp.int32)
        use1 = lid < BUCKET_W
        lvalid = jnp.where(use1[None, :], ph1[:, None], ph2[:, None])
        lane_byte = jnp.where(
            use1[None, :],
            pw1[:, None] >> (jnp.uint32(8) * (lid[None, :].astype(jnp.uint32) & jnp.uint32(3))),
            pw2[:, None] >> (jnp.uint32(8) * (lid[None, :].astype(jnp.uint32) & jnp.uint32(3))),
        ) & jnp.uint32(0xFF)
        bm = (lane_byte == pp8[:, None]) & lvalid & pvalid[:, None]
        nbm = bm.sum(axis=1, dtype=jnp.int32)
        ln1 = jnp.argmax(bm, axis=1)
        bm2 = bm & (lid[None, :] != ln1[:, None])
        ln2 = jnp.argmax(bm2, axis=1)

        def lslot_of(ln):
            s = (
                jnp.where(ln < BUCKET_W, pl1, pl2) * BUCKET_W
                + (ln % BUCKET_W)
            )
            return jnp.clip(s, 0, sfp.shape[0] - 1)

        s1 = lslot_of(ln1)
        s2 = lslot_of(ln2)
        f1 = sfp[s1]
        f2 = sfp[s2]
        ok1 = (nbm >= 1) & (f1 == pfp)
        ok2 = (nbm >= 2) & (f2 == pfp)
        nmatch = ok1.astype(jnp.int32) + ok2.astype(jnp.int32)
        found = nmatch > 0
        win = jnp.where(ok1, s1, s2)
        g_bkt = sbkt[win]
        ok = found & (g_bkt >= 0)
        ti = jnp.where(
            ok, psafe // c + dp_i * b_loc, -1
        ).astype(jnp.int32)
        bi = jnp.where(ok, g_bkt, -1).astype(jnp.int32)
        amb = jax.lax.psum(
            jax.lax.psum(
                ((nmatch > 1) | (pvalid & (nbm > 2))).sum(dtype=jnp.int32),
                SUB_AXIS,
            ),
            DP_AXIS,
        )
        # device-side combine over 'sub': valid candidates <= flagged
        # pairs, so the psum'd flagged total remains a sound overflow
        # trigger for the combined buffer
        cti, cbi = _combine_pairs(ti, bi, lambda t: t >= 0, mh)
        total = jax.lax.psum(total, SUB_AXIS)
        return (
            cti[None, :], cbi[None, :], total.reshape(1, 1),
            amb.reshape(1, 1),
        )

    @jax.jit
    def kernel(meta, slots, topics):
        return _shard_map_unchecked(
            _local,
            mesh=mesh,
            in_specs=meta_specs + slot_specs + t_specs,
            out_specs=(
                P(DP_AXIS, None),
                P(DP_AXIS, None),
                P(DP_AXIS, None),
                P(None, None),
            ),
        )(
            meta.plen, meta.has_hash, meta.root_wild, meta.plus, meta.active,
            slots.fp, slots.bucket, slots.probe,
            topics.ids, topics.lens, topics.dollar,
        )

    return kernel


def make_slot_delta_kernel(mesh: Mesh):
    """shard_map scatter for incremental cuckoo-slot sync: every shard
    receives the same (global slot idx, fp, bucket, probe word) delta
    batches and applies the slots/probe words it owns (mode='drop'
    discards out-of-slice rows) — one write stream, applied
    shard-locally, the same mria-rlog shape as the filter-row delta."""
    from ..ops.hash_index import BUCKET_W

    def _local(sfp, sbkt, probe, idx, fpv, bktv, pwv):
        n_loc = sfp.shape[0]
        nb_loc = probe.shape[0]
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        s_off = sub_i * n_loc
        b_off = sub_i * nb_loc

        def step(carry, xs):
            cfp, cbkt, cpw = carry
            i, f, b, pw = xs
            # clamp negatives to one-past-end: jnp negative indices WRAP
            # (they'd corrupt the tail of lower shards); only >= n is
            # dropped by mode='drop' (same guard as _apply_delta_local)
            ls = i - s_off
            ls = jnp.where((ls < 0) | (ls >= n_loc), n_loc, ls)
            lb = i // BUCKET_W - b_off
            lb = jnp.where((lb < 0) | (lb >= nb_loc), nb_loc, lb)
            return (
                (
                    cfp.at[ls].set(f, mode="drop"),
                    cbkt.at[ls].set(b, mode="drop"),
                    cpw.at[lb].set(pw, mode="drop"),
                ),
                None,
            )

        (sfp, sbkt, probe), _ = jax.lax.scan(
            step, (sfp, sbkt, probe), (idx, fpv, bktv, pwv)
        )
        return sfp, sbkt, probe

    specs = (P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS))
    dspecs = ((P(None, None),) * 4)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def apply(sfp, sbkt, probe, idx, fpv, bktv, pwv):
        return _shard_map(
            _local,
            mesh=mesh,
            in_specs=specs + dspecs,
            out_specs=specs,
        )(sfp, sbkt, probe, idx, fpv, bktv, pwv)

    return apply


def make_mesh_sync_kernel(mesh: Mesh):
    """FUSED churn sync: apply a filter-row delta batch AND a
    cuckoo-slot delta batch in ONE shard_map dispatch with every
    device buffer donated. The steady-state churn loop used to pay two
    launches per sync (row scatter, then slot scatter) — chained
    dispatches do not pipeline through the device relay
    (PERF_NOTES.md), so at mesh scale the second launch was pure
    serial overhead. Delta streams are replicated (tiny — syncer
    batches); each shard applies the rows/slots it owns via the same
    masked mode='drop' scatters as the split kernels."""
    from ..ops.hash_index import BUCKET_W

    def _local(dev, sfp, sbkt, probe,
               rows, words, plen, hh, rw, act,
               sidx, sfpv, sbktv, spwv):
        local_n = dev.words.shape[0]
        n_loc = sfp.shape[0]
        nb_loc = probe.shape[0]
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        r_off = sub_i * local_n
        s_off = sub_i * n_loc
        b_off = sub_i * nb_loc

        def rstep(d, xs):
            r, w, p, h, rw_, a = xs
            local = r - r_off
            oob = (local < 0) | (local >= local_n)
            local = jnp.where(oob, local_n, local)
            return (
                EncodedFilters(
                    d.words.at[local].set(w, mode="drop"),
                    d.prefix_len.at[local].set(p, mode="drop"),
                    d.has_hash.at[local].set(h, mode="drop"),
                    d.root_wild.at[local].set(rw_, mode="drop"),
                    d.active.at[local].set(a, mode="drop"),
                ),
                None,
            )

        dev, _ = jax.lax.scan(rstep, dev, (rows, words, plen, hh, rw, act))

        def sstep(carry, xs):
            cfp, cbkt, cpw = carry
            i, f, b, pw = xs
            ls = i - s_off
            ls = jnp.where((ls < 0) | (ls >= n_loc), n_loc, ls)
            lb = i // BUCKET_W - b_off
            lb = jnp.where((lb < 0) | (lb >= nb_loc), nb_loc, lb)
            return (
                (
                    cfp.at[ls].set(f, mode="drop"),
                    cbkt.at[ls].set(b, mode="drop"),
                    cpw.at[lb].set(pw, mode="drop"),
                ),
                None,
            )

        (sfp, sbkt, probe), _ = jax.lax.scan(
            sstep, (sfp, sbkt, probe), (sidx, sfpv, sbktv, spwv)
        )
        return dev, sfp, sbkt, probe

    dev_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    slot_specs = (P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS))
    row_dspecs = (
        P(None, None), P(None, None, None), P(None, None),
        P(None, None), P(None, None), P(None, None),
    )
    slot_dspecs = (P(None, None),) * 4

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def apply(dev, sfp, sbkt, probe,
              rows, words, plen, hh, rw, act,
              sidx, sfpv, sbktv, spwv):
        return _shard_map(
            _local,
            mesh=mesh,
            in_specs=(dev_specs,) + slot_specs + row_dspecs + slot_dspecs,
            out_specs=(dev_specs,) + slot_specs,
        )(dev, sfp, sbkt, probe, rows, words, plen, hh, rw, act,
          sidx, sfpv, sbktv, spwv)

    return apply


class ShardedDeviceTable:
    """Mesh-resident mirror of a FilterTable: rows sub-sharded across
    the mesh, topics dp-sharded, batched delta sync through the
    shard_map scatter. The multi-device counterpart of
    models.router.DeviceTable behind the same sync()/match surface —
    replication-as-partitioning instead of the reference's full
    per-node table replica (emqx_router.erl:133-162). With `index`,
    the pattern-class cuckoo table is ALSO mesh-resident (buckets
    sub-sharded) and match_hash runs the production kernel; the dense
    kernel then serves only residual (unclassed) rows."""

    DELTA_BATCH = 1024  # rows per apply_delta call (syncer batch size)

    def __init__(
        self,
        table,
        mesh: Mesh,
        max_hits_per_block: int = 2048,
        index=None,
        telemetry=None,
    ):
        from . import mesh as mesh_mod
        from ..obs.kernel_telemetry import NULL as _null_tel

        self.table = table
        self.mesh = mesh
        self.index = index
        self.telemetry = telemetry if telemetry is not None else _null_tel
        self._mesh_mod = mesh_mod
        # shard failure domain: `_mesh0` is the full N-chip layout;
        # `lost_shards` holds ORIGINAL sub-axis columns evacuated off
        # the mesh (chip loss); `shard_gen` bumps on every re-shard so
        # in-flight handles/caches can detect a layout change
        self._mesh0 = mesh
        self.lost_shards: set = set()
        self.shard_gen = 0
        self._dev: Optional[EncodedFilters] = None
        self._synced_capacity = 0
        _mc, _mp, self._apply_delta = make_sharded_kernels(mesh)
        self._match_ids_cache: dict = {}
        self._hash_cache: dict = {}
        self.default_mh = max_hits_per_block
        # sticky escalation floor: the combined result buffer budgets
        # the SUM of per-shard hits, so once a batch overflows, every
        # later batch of the same workload would too — re-dispatching
        # each time is exactly the N-x overhead this path removes. The
        # floor persists for the life of the layout.
        self._mh_floor = 0
        self._dev_meta = None
        self._dev_slots = None
        self._dev_residual = None
        self._apply_slot_delta = (
            make_slot_delta_kernel(mesh) if index is not None else None
        )
        self._mesh_sync = (
            make_mesh_sync_kernel(mesh) if index is not None else None
        )
        # degrade-to-single-device admission (tpu_mesh_min_rows_per_shard
        # knob): below this many table rows per shard the mesh
        # launch+combine overhead exceeds the kernel work it spreads,
        # so serving falls back to a plain DeviceTable on the mesh's
        # first chip. 0 (the direct-construction default) never
        # degrades.
        self.min_rows_per_shard = 0
        self.degraded = False
        self._single = None
        self.fanout = None
        # chaos fault seam (emqx_tpu/chaos/faults.py) — same contract
        # as the single-device DeviceTable: one attribute read per sync
        self.fault_injector = None
        # transfer chunk cap (ops/transfer.chunk_hits) — same contract
        # as DeviceTable.transfer_chunk_hits
        self.transfer_chunk_hits = None
        # mesh microscope seam (obs/mesh_scope.MeshScope): None keeps
        # the served path at one attribute read per dispatch — the
        # tpu_mesh_scope_enable=false contract
        self.scope = None
        self._probe_cache: dict = {}

    def attach_fanout(self, store) -> None:
        """Mirror a CSR destination store on the mesh (replicated: the
        fan tables are small next to the sub-sharded filter state, and
        every shard needs every segment) — the same resolve begin/
        finish surface as the single-device DeviceTable."""
        from ..ops.fanout import FanoutDeviceState

        self.fanout = FanoutDeviceState(
            store, mesh=self.mesh, telemetry=self.telemetry
        )

    # --- shard failure domain (chip loss / evacuation / rebalance) --------

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[SUB_AXIS]

    def shard_of_row(self, row: int) -> int:
        """The sub-axis column serving a table row under the CURRENT
        mesh (trailing-pad slices: ceil(capacity / n_sub) rows each)."""
        return row // self._mesh_mod.shard_rows(self.table.capacity, self.mesh)

    def shard_of_slot(self, slot: int) -> int:
        """The sub-axis column serving a cuckoo slot position under the
        current mesh (slot slices stay bucket-aligned)."""
        from ..ops.hash_index import BUCKET_W

        n_sub = self.mesh.shape[SUB_AXIS]
        nb = self.index.n_buckets
        nb_loc = -(-nb // n_sub)
        return slot // (nb_loc * BUCKET_W)

    def _survivor_mesh(self) -> Mesh:
        import numpy as np

        arr = np.asarray(self._mesh0.devices)  # [n_dp, n_sub0]
        keep = [
            i for i in range(arr.shape[1]) if i not in self.lost_shards
        ]
        return self._mesh_mod.make_mesh(
            n_dp=arr.shape[0],
            n_sub=len(keep),
            devices=arr[:, keep].reshape(-1).tolist(),
        )

    def evacuate_shard(self, shard: int) -> bool:
        """Drop one ORIGINAL sub-axis column from the mesh and re-shard
        the table over the survivors (N-1 serving). The caller owns the
        follow-up `sync()` that re-uploads every slice from host truth
        through the normal full-resync machinery. Returns True when the
        mesh changed. Adding to `lost_shards` FIRST matters: the fault
        injector consults it, so the evacuation resync already runs
        without touching the lost chip while its fault is still live."""
        n_sub0 = self._mesh0.shape[SUB_AXIS]
        if shard < 0 or shard >= n_sub0 or shard in self.lost_shards:
            return False
        if len(self.lost_shards) + 1 >= n_sub0:
            raise RuntimeError(
                f"cannot evacuate shard {shard}: no survivor would remain"
            )
        self.lost_shards.add(shard)
        self._rebuild_mesh(self._survivor_mesh())
        return True

    def restore_shard(self, shard: int) -> bool:
        """Rebalance a recovered chip back in: restore the full layout
        (or the wider survivor layout while other chips are still
        lost). Caller owns the follow-up full `sync()`."""
        if shard not in self.lost_shards:
            return False
        self.lost_shards.discard(shard)
        self._rebuild_mesh(
            self._mesh0 if not self.lost_shards else self._survivor_mesh()
        )
        return True

    def _rebuild_mesh(self, mesh: Mesh) -> None:
        """Swap the serving mesh: recompile the shard_map kernels for
        the new layout, drop every device-resident array so the next
        sync() is a full re-upload from host truth, and re-mirror the
        fanout store."""
        self.mesh = mesh
        _mc, _mp, self._apply_delta = make_sharded_kernels(mesh)
        self._match_ids_cache.clear()
        self._hash_cache.clear()
        self._probe_cache.clear()
        self._apply_slot_delta = (
            make_slot_delta_kernel(mesh) if self.index is not None else None
        )
        self._mesh_sync = (
            make_mesh_sync_kernel(mesh) if self.index is not None else None
        )
        self._dev = None
        self._dev_meta = None
        self._dev_slots = None
        self._dev_residual = None
        self._synced_capacity = 0
        if self.fanout is not None:
            self.attach_fanout(self.fanout.store)
        self.shard_gen += 1
        tel = self.telemetry
        if tel.enabled:
            tel.set_gauge("mesh_shards", self.mesh.shape[SUB_AXIS])
            tel.set_gauge("shards_lost", len(self.lost_shards))

    # --- degrade-to-single-device admission (small tables) ----------------

    def _decide_mode(self) -> None:
        """Flip between mesh serving and the single-device fallback
        when the per-shard row count crosses `min_rows_per_shard`.
        Capacity is grow-only, so a workload flips at most once each
        way; each flip forces a full re-upload on the new path (the
        other path's device state is dropped, not kept coherent)."""
        thr = self.min_rows_per_shard
        want = bool(thr) and (
            self.table.capacity // max(1, self.n_shards) < thr
        )
        if want == self.degraded:
            return
        tel = self.telemetry
        if want:
            from ..models.router import DeviceTable

            single = DeviceTable(
                self.table,
                device=self._mesh_mod.primary_device(self.mesh),
                index=self.index,
                telemetry=self.telemetry,
            )
            single.transfer_chunk_hits = self.transfer_chunk_hits
            self._single = single
            if tel.enabled:
                tel.count("mesh_degraded_single_device_total")
        else:
            self._single = None
            self._dev = None
            self._dev_meta = None
            self._dev_slots = None
            self._dev_residual = None
            self._synced_capacity = 0
        self.degraded = want
        if tel.enabled:
            tel.set_gauge("mesh_degraded_single_device", int(want))

    def _match_kernel(self, mh: int):
        k = self._match_ids_cache.get(mh)
        if k is None:
            k = make_match_ids_kernel(self.mesh, mh)
            self._match_ids_cache[mh] = k
        return k

    def _hash_kernel(self, mh: int):
        # keyed on (mh, logical bucket count): capacity growth changes
        # the hash mask, and on an N-1 mesh the mask can no longer be
        # derived from the padded per-shard slice width
        nb = self.index.n_buckets
        k = self._hash_cache.get((mh, nb))
        if k is None:
            k = make_sharded_hash_kernel(self.mesh, mh, n_buckets=nb)
            self._hash_cache[(mh, nb)] = k
        return k

    def _nchips(self) -> int:
        return int(self.mesh.devices.size)

    def _combine_probe(self, mh: int):
        """Cached combine-only probe kernel for the CURRENT layout
        (mesh microscope sampled splits; see
        make_combine_probe_kernel). Cleared on every re-shard."""
        k = self._probe_cache.get(mh)
        if k is None:
            k = make_combine_probe_kernel(self.mesh, mh)
            self._probe_cache[mh] = k
        return k

    def _put_repl(self, a):
        return jax.device_put(a, NamedSharding(self.mesh, P()))

    def _put_sub(self, a, pad_value=0):
        """Sub-shard a host array, ceil-padding the leading axis to a
        multiple of n_sub with `pad_value` (trailing pad — logical ids
        keep their positions; see mesh.shard_rows)."""
        import numpy as np

        pad = (-a.shape[0]) % self.mesh.shape[SUB_AXIS]
        if pad:
            width = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
            a = np.pad(a, width, constant_values=pad_value)
        self._count_shard_rows(
            np.full(self.mesh.shape[SUB_AXIS],
                    a.shape[0] // self.mesh.shape[SUB_AXIS], np.int64)
        )
        return jax.device_put(a, NamedSharding(self.mesh, P(SUB_AXIS)))

    def _count_shard_rows(self, per_shard) -> None:
        """Per-shard host->device transfer accounting
        (emqx_xla_mesh_shard_transfer_rows_total{shard=...}): the
        combined fetch is shard-count-independent, so the upload side
        is where per-shard skew shows up."""
        tel = self.telemetry
        if not tel.enabled:
            return
        for s, n in enumerate(per_shard):
            if n:
                tel.count_labeled(
                    "mesh_shard_transfer_rows_total",
                    {"shard": str(s)},
                    int(n),
                )

    def _sync_index(self) -> None:
        import numpy as np

        from ..ops.hash_index import BUCKET_W, ClassMeta, SlotArrays

        ix = self.index
        assert ix is not None
        n_sub = self.mesh.shape[SUB_AXIS]
        # buckets per shard is ceil(n_buckets / n_sub): when n_sub does
        # not divide the pow2 count (an N-1 survivor mesh) the trailing
        # pad buckets are inert — fp=0 can never byte-match (p8 >= 1),
        # bucket=-1 is rejected by the kernel's g_bkt >= 0 check, and
        # the logical hash mask (n_buckets - 1) never probes them.
        nb_pad = (-ix.n_buckets) % n_sub
        if ix.meta_dirty or self._dev_meta is None:
            self._dev_meta = ClassMeta(
                *(self._put_repl(np.array(a)) for a in ix.packed_meta())
            )
            ix.meta_dirty = False
        if ix.rebuilt or self._dev_slots is None:
            ix.dirty_slots.clear()
            fp = np.array(ix.slots.fp)
            bkt = np.array(ix.slots.bucket)
            if nb_pad:
                # slot pad must stay bucket-aligned (per-shard slots ==
                # buckets-per-shard * BUCKET_W), which _put_sub's plain
                # ceil-pad would not produce
                sp = nb_pad * BUCKET_W
                fp = np.pad(fp, (0, sp))
                bkt = np.pad(bkt, (0, sp), constant_values=-1)
            self._dev_slots = SlotArrays(
                self._put_sub(fp),
                self._put_sub(bkt),
                self._put_sub(np.array(ix.slots.probe)),
            )
            ix.rebuilt = False
        elif ix.dirty_slots:
            dirty = np.unique(np.asarray(ix.dirty_slots, np.int32))
            ix.dirty_slots.clear()
            total = len(dirty)
            k = self.DELTA_BATCH
            n_b = 1 << max(0, -(-total // k) - 1).bit_length()
            idx = np.full(n_b * k, dirty[-1], np.int32)
            idx[:total] = dirty
            shape2 = (n_b, k)
            self.telemetry.record_shape(
                "mesh_slot_delta", (n_b, len(ix.slots.fp))
            )
            out = self._apply_slot_delta(
                self._dev_slots.fp,
                self._dev_slots.bucket,
                self._dev_slots.probe,
                jnp.asarray(idx.reshape(shape2)),
                jnp.asarray(ix.slots.fp[idx].reshape(shape2)),
                jnp.asarray(ix.slots.bucket[idx].reshape(shape2)),
                jnp.asarray(
                    ix.slots.probe[idx // BUCKET_W].reshape(shape2)
                ),
            )
            self._dev_slots = SlotArrays(*out)
        cap_padded = (
            self.table.capacity + (-self.table.capacity) % n_sub
        )
        if ix.residual_dirty or self._dev_residual is None or (
            self._dev_residual.shape[0] != cap_padded
        ):
            mask = np.zeros(self.table.capacity, bool)
            if ix.residual_rows:
                mask[list(ix.residual_rows)] = True
            self._dev_residual = self._put_sub(mask)
            ix.residual_dirty = False

    def sync(self) -> int:
        fi = self.fault_injector
        if fi is not None:
            fi.check("sync")
        self._decide_mode()
        if self.degraded:
            # single-device fallback owns its own sync telemetry; the
            # fault check stays at this wrapper (the injector reasons
            # about mesh shards, not the fallback device)
            self._single.transfer_chunk_hits = self.transfer_chunk_hits
            return self._single.sync()
        tel = self.telemetry
        t0 = tel.clock()
        pending = len(self.table.dirty)
        n, full = self._sync_impl()
        if tel.enabled and (n or full):
            tel.record_sync(
                rows=n, seconds=tel.clock() - t0, pending=pending, full=full
            )
            tel.observe_device_table(self)
        return n

    def _sync_impl(self):
        t = self.table
        sc = self.scope
        if self._dev is None or t.grew or t.capacity != self._synced_capacity:
            rec = sc.begin("sync", self._nchips()) if sc is not None else None
            n = len(t.dirty)
            t.drain_dirty()
            snap = t.snapshot()
            if rec is not None:
                sc.lap(rec, "host_encode")
            self._dev = self._mesh_mod.put_filters(snap, self.mesh)
            self._synced_capacity = t.capacity
            if rec is not None:
                sc.lap(rec, "h2d_stage")
                sc.finish_sync(rec)
            if self.index is not None:
                self._sync_index()
            return n, True
        dirty = t.drain_dirty()  # ndarray: row id 0 alone is falsy —
        if len(dirty) == 0:      # test LENGTH, never truthiness
            if self.index is not None:
                self._sync_index()
            return 0, False
        import numpy as np

        rec = sc.begin("sync", self._nchips()) if sc is not None else None
        total = len(dirty)
        arr = np.asarray(dirty, np.int32)
        # ONE dispatch for the whole churn: pad to [n_b, K] (n_b pow2
        # so recompiles stay log-bounded) and scan inside the kernel
        k = self.DELTA_BATCH
        n_b = 1 << max(0, -(-total // k) - 1).bit_length()  # pow2 ceil-div
        idx = np.full(n_b * k, arr[-1], np.int32)
        idx[:total] = arr
        shape2 = (n_b, k)
        tel = self.telemetry
        if tel.enabled:
            n_sub = self.mesh.shape[SUB_AXIS]
            rs = self._mesh_mod.shard_rows(t.capacity, self.mesh)
            self._count_shard_rows(
                np.bincount(
                    np.clip(arr // rs, 0, n_sub - 1), minlength=n_sub
                )
            )
        ix = self.index
        if (
            ix is not None
            and ix.dirty_slots
            and not ix.rebuilt
            and self._dev_slots is not None
            and self._mesh_sync is not None
        ):
            # steady-state churn touches rows AND cuckoo slots: apply
            # both delta streams in ONE fused dispatch (the split
            # kernels pay two serial launches per sync)
            from ..ops.hash_index import BUCKET_W, SlotArrays

            sdirty = np.unique(np.asarray(ix.dirty_slots, np.int32))
            ix.dirty_slots.clear()
            s_total = len(sdirty)
            s_nb = 1 << max(0, -(-s_total // k) - 1).bit_length()
            sidx = np.full(s_nb * k, sdirty[-1], np.int32)
            sidx[:s_total] = sdirty
            s_shape2 = (s_nb, k)
            tel.record_shape(
                "mesh_sync",
                (n_b, s_nb, t.capacity, t.max_levels, len(ix.slots.fp)),
            )
            if tel.enabled:
                tel.set_gauge("mesh_sync_batch_rows", total + s_total)
            if rec is not None:
                sc.lap(rec, "host_encode")
            # staged args hoisted so the microscope can lap the host
            # gather + device placement (h2d_stage) apart from the
            # fused kernel dispatch (program_launch)
            staged = (
                jnp.asarray(idx.reshape(shape2)),
                jnp.asarray(t.words[idx].reshape(shape2 + (t.max_levels,))),
                jnp.asarray(t.prefix_len[idx].reshape(shape2)),
                jnp.asarray(t.has_hash[idx].reshape(shape2)),
                jnp.asarray(t.root_wild[idx].reshape(shape2)),
                jnp.asarray(t.active[idx].reshape(shape2)),
                jnp.asarray(sidx.reshape(s_shape2)),
                jnp.asarray(ix.slots.fp[sidx].reshape(s_shape2)),
                jnp.asarray(ix.slots.bucket[sidx].reshape(s_shape2)),
                jnp.asarray(
                    ix.slots.probe[sidx // BUCKET_W].reshape(s_shape2)
                ),
            )
            if rec is not None:
                sc.lap(rec, "h2d_stage")
            out = self._mesh_sync(
                self._dev,
                self._dev_slots.fp,
                self._dev_slots.bucket,
                self._dev_slots.probe,
                *staged,
            )
            if rec is not None:
                sc.lap(rec, "program_launch")
                sc.finish_sync(rec)
            self._dev = out[0]
            self._dev_slots = SlotArrays(*out[1:])
            self._sync_index()  # meta/residual legs only — slots done
            return total, False
        tel.record_shape(
            "apply_delta", (n_b, t.capacity, t.max_levels)
        )
        if tel.enabled:
            tel.set_gauge("mesh_sync_batch_rows", total)
        if rec is not None:
            sc.lap(rec, "host_encode")
        staged = (
            jnp.asarray(idx.reshape(shape2)),
            jnp.asarray(t.words[idx].reshape(shape2 + (t.max_levels,))),
            jnp.asarray(t.prefix_len[idx].reshape(shape2)),
            jnp.asarray(t.has_hash[idx].reshape(shape2)),
            jnp.asarray(t.root_wild[idx].reshape(shape2)),
            jnp.asarray(t.active[idx].reshape(shape2)),
        )
        if rec is not None:
            sc.lap(rec, "h2d_stage")
        self._dev = self._apply_delta(self._dev, *staged)
        if rec is not None:
            sc.lap(rec, "program_launch")
            sc.finish_sync(rec)
        if self.index is not None:
            self._sync_index()
        return total, False

    def _block_mh(self) -> int:
        """Per-block hit capacity, bounded by the transfer chunk when
        one is set (ops/transfer.chunk_hits semantics — oversize
        results escalate through the exact-size retry, so the bound
        costs a counted re-dispatch, never correctness), then raised
        to the sticky escalation floor: the combined buffer budgets
        the dp-block TOTAL across shards, so a workload that
        overflowed once would overflow every batch — the floor trades
        one-time extra transfer width for never re-dispatching."""
        mh = self.default_mh
        cap = self.transfer_chunk_hits
        if cap is not None and mh > cap >= 1024:
            mh = 1 << (cap.bit_length() - 1)
        return max(mh, self._mh_floor)

    def match_ids_begin(self, enc: EncodedTopics, residual: bool = False):
        """Launch the sharded dense compaction kernel WITHOUT forcing
        any device->host transfer AND begin the result copy
        (ops/transfer.FetchTicket, handle's last element — the same
        begin contract as the single-device DeviceTable): the
        pipelined publish path overlaps this batch's mesh execution +
        device->host transfer with the next batch's host-side encode.
        Returns an opaque handle for match_ids_finish."""
        if self.degraded:
            return ("1dev",) + self._single.match_ids_begin(enc, residual)
        assert self._dev is not None, "sync() before matching"
        dev = self._dev
        if residual:
            assert self._dev_residual is not None
            dev = dev._replace(active=self._dev_residual)
        sc = self.scope
        rec = None
        if sc is not None:
            rec = sc.begin("ids", self._nchips())
            enc = self._mesh_mod.pad_topics(enc, self.mesh)
            sc.lap(rec, "host_encode")
        t_dev = self._mesh_mod.put_topics(enc, self.mesh)
        if rec is not None:
            sc.lap(rec, "h2d_stage")
        mh = self._block_mh()
        self.telemetry.record_shape(
            "mesh_match_ids", (int(t_dev.ids.shape[0]), mh)
        )
        from ..ops import transfer as transfer_ops

        out = self._match_kernel(mh)(dev, t_dev)
        if rec is not None:
            sc.lap(rec, "program_launch")
        STAGE_MARK.stage = "ticket_start"
        ticket = transfer_ops.start_fetch(out, self.telemetry)
        if rec is not None:
            sc.attach(rec, ticket)
        return (dev, t_dev, mh, rec, ticket)

    def match_ids_finish(self, pending):
        """Force the transfers for a begun dense match, escalating
        per-block capacity on overflow (sticky: the new capacity
        becomes the floor for later begins). Returns (ti 1d, ri 1d)
        host arrays of equal length (valid pairs only)."""
        import numpy as np

        if pending[0] == "1dev":
            return self._single.match_ids_finish(pending[1:])
        dev, t_dev, mh, rec, ticket = pending
        tel = self.telemetry
        t0 = tel.clock()
        ti, ri, totals = ticket.wait()
        totals = np.asarray(totals)
        mh0 = mh
        while int(totals.max(initial=0)) > mh:
            tel.count("escalations_total")
            mh = max(mh * 2, 1 << int(totals.max()).bit_length())
            tel.record_shape(
                "mesh_match_ids", (int(t_dev.ids.shape[0]), mh)
            )
            self._mh_floor = max(self._mh_floor, mh)
            ti, ri, totals = self._match_kernel(mh)(dev, t_dev)
            totals = np.asarray(totals)
        ti = np.asarray(ti).reshape(-1)
        ri = np.asarray(ri).reshape(-1)
        keep = ti >= 0
        if tel.enabled:
            tel.observe_family("mesh_combine_seconds", tel.clock() - t0)
        sc = self.scope
        if sc is not None and rec is not None and mh == mh0:
            # escalated dispatches re-ran synchronously — their clock
            # pairs no longer describe one dispatch, so they are
            # dropped (the escalation is already counted above)
            shards = None
            if rec.sampled:
                rs = self._mesh_mod.shard_rows(
                    self.table.capacity, self.mesh
                )
                shards = ri[keep] // rs
            sc.finish(
                rec, self, ticket, mh,
                hits=int(keep.sum()), shard_ids=shards,
            )
        return ti[keep], ri[keep]

    def match_ids(self, enc: EncodedTopics, residual: bool = False):
        """All (topic, row) hit pairs for an encoded topic batch via
        the dense kernel. With residual=True the active mask narrows
        to the class index's residual rows (the unclassed fallback).
        Returns (ti 1d, ri 1d) host arrays of equal length (valid
        pairs only), escalating per-block capacity on overflow.
        Composed from the begin/finish pipeline halves."""
        return self.match_ids_finish(self.match_ids_begin(enc, residual))

    def match_hash_begin(self, enc: EncodedTopics):
        """Launch the mesh-sharded production hash kernel without a
        host fetch AND begin the result transfer (ticket last, same
        contract as DeviceTable.match_hash_begin). Returns an opaque
        handle for match_hash_finish."""
        if self.degraded:
            return ("1dev",) + self._single.match_hash_begin(enc)
        assert self._dev_slots is not None, "sync() before matching"
        sc = self.scope
        rec = None
        if sc is not None:
            rec = sc.begin("hash", self._nchips())
            enc = self._mesh_mod.pad_topics(enc, self.mesh)
            sc.lap(rec, "host_encode")
        t_dev = self._mesh_mod.put_topics(enc, self.mesh)
        if rec is not None:
            sc.lap(rec, "h2d_stage")
        mh = self._block_mh()
        self.telemetry.record_shape(
            "mesh_match_ids_hash", (int(t_dev.ids.shape[0]), mh)
        )
        from ..ops import transfer as transfer_ops

        out = self._hash_kernel(mh)(self._dev_meta, self._dev_slots, t_dev)
        if rec is not None:
            sc.lap(rec, "program_launch")
        STAGE_MARK.stage = "ticket_start"
        ticket = transfer_ops.start_fetch(out, self.telemetry)
        if rec is not None:
            sc.attach(rec, ticket)
        return (t_dev, mh, rec, ticket)

    def match_hash_finish(self, pending):
        """Force the transfers for a begun hash match, escalating
        per-block capacity on overflow (sticky floor, same policy as
        match_ids_finish). Same result contract as match_hash."""
        import numpy as np

        if pending[0] == "1dev":
            return self._single.match_hash_finish(pending[1:])
        t_dev, mh, rec, ticket = pending
        tel = self.telemetry
        t0 = tel.clock()
        ti, bi, totals, amb = ticket.wait()
        totals = np.asarray(totals)
        mh0 = mh
        while int(totals.max(initial=0)) > mh:
            tel.count("hash_overflow_retries_total")
            mh = max(mh * 2, 1 << int(totals.max()).bit_length())
            tel.record_shape(
                "mesh_match_ids_hash", (int(t_dev.ids.shape[0]), mh)
            )
            self._mh_floor = max(self._mh_floor, mh)
            ti, bi, totals, amb = self._hash_kernel(mh)(
                self._dev_meta, self._dev_slots, t_dev
            )
            totals = np.asarray(totals)
        ti = np.asarray(ti).reshape(-1)
        bi = np.asarray(bi).reshape(-1)
        keep = ti >= 0
        if tel.enabled:
            tel.observe_family("mesh_combine_seconds", tel.clock() - t0)
        sc = self.scope
        if sc is not None and rec is not None and mh == mh0:
            shards = None
            if rec.sampled:
                n_sub = self.mesh.shape[SUB_AXIS]
                nb_loc = -(-self.index.n_buckets // n_sub)
                shards = bi[keep] // nb_loc
            sc.finish(
                rec, self, ticket, mh,
                hits=int(keep.sum()), shard_ids=shards,
            )
        return ti[keep], bi[keep], int(np.asarray(amb).reshape(-1)[0])

    def match_hash(self, enc: EncodedTopics):
        """(topic, bucket) candidates via the mesh-sharded production
        hash kernel. Returns (ti 1d, bi 1d, amb int): global topic
        indices (may include dp-padding rows — callers drop
        t_idx >= batch), global bucket ids, and the mesh-wide
        ambiguity count (amb > 0 -> caller re-matches on a host path,
        see ops.hash_index.match_ids_hash)."""
        return self.match_hash_finish(self.match_hash_begin(enc))

    # --- mesh AOT warmup (recompiles_at_serve_total == 0 discipline) ------

    def warmup_deltas(self) -> int:
        """Pre-trace the churn sync kernels (row delta, slot delta,
        fused row+slot) at their small pow2 batch shapes so the first
        serve-time churn wave hits a warm compile cache — the mesh
        counterpart of Router.warmup_shapes' match-kernel ladder.
        Re-applies row/slot 0's CURRENT host truth, so every warm
        dispatch is semantically a no-op. Requires a completed full
        sync(); returns the number of kernels warmed."""
        if self.degraded or self._dev is None:
            return 0
        import numpy as np

        t = self.table
        k = self.DELTA_BATCH
        tel = self.telemetry
        warmed = 0
        for n_b in (1, 2):
            shape2 = (n_b, k)
            idx = np.zeros(n_b * k, np.int32)
            row_args = (
                jnp.asarray(idx.reshape(shape2)),
                jnp.asarray(t.words[idx].reshape(shape2 + (t.max_levels,))),
                jnp.asarray(t.prefix_len[idx].reshape(shape2)),
                jnp.asarray(t.has_hash[idx].reshape(shape2)),
                jnp.asarray(t.root_wild[idx].reshape(shape2)),
                jnp.asarray(t.active[idx].reshape(shape2)),
            )
            tel.record_shape("apply_delta", (n_b, t.capacity, t.max_levels))
            self._dev = self._apply_delta(self._dev, *row_args)
            warmed += 1
            ix = self.index
            if ix is None or self._dev_slots is None:
                continue
            from ..ops.hash_index import BUCKET_W, SlotArrays

            slot_args = (
                jnp.asarray(idx.reshape(shape2)),
                jnp.asarray(ix.slots.fp[idx].reshape(shape2)),
                jnp.asarray(ix.slots.bucket[idx].reshape(shape2)),
                jnp.asarray(ix.slots.probe[idx // BUCKET_W].reshape(shape2)),
            )
            tel.record_shape("mesh_slot_delta", (n_b, len(ix.slots.fp)))
            out = self._apply_slot_delta(
                self._dev_slots.fp, self._dev_slots.bucket,
                self._dev_slots.probe, *slot_args,
            )
            self._dev_slots = SlotArrays(*out)
            warmed += 1
            if self._mesh_sync is None:
                continue
            tel.record_shape(
                "mesh_sync",
                (n_b, n_b, t.capacity, t.max_levels, len(ix.slots.fp)),
            )
            out = self._mesh_sync(
                self._dev,
                self._dev_slots.fp, self._dev_slots.bucket,
                self._dev_slots.probe, *row_args, *slot_args,
            )
            self._dev = out[0]
            self._dev_slots = SlotArrays(*out[1:])
            warmed += 1
        return warmed

    def warmup_escalated(self, enc: EncodedTopics) -> int:
        """Pre-build the first escalation step (2x the current block
        capacity) for both match kernels at this batch shape: a
        serve-time overflow then re-dispatches against a warm cache
        and the shape key is already recorded, keeping
        recompiles_at_serve_total at 0. Dispatch-only — results are
        dropped unfetched (compilation happens at call time; no
        blocking fetch on this path)."""
        if self.degraded or self._dev is None:
            return 0
        t_dev = self._mesh_mod.put_topics(enc, self.mesh)
        b = int(t_dev.ids.shape[0])
        mh2 = self._block_mh() * 2
        warmed = 0
        self.telemetry.record_shape("mesh_match_ids", (b, mh2))
        self._match_kernel(mh2)(self._dev, t_dev)
        warmed += 1
        if self._dev_slots is not None:
            self.telemetry.record_shape("mesh_match_ids_hash", (b, mh2))
            self._hash_kernel(mh2)(self._dev_meta, self._dev_slots, t_dev)
            warmed += 1
        sc = self.scope
        if sc is not None:
            # pre-warm the microscope's combine-only probe at the
            # current block capacity and its first escalation so
            # serve-time sampled splits never compile
            warmed += sc.warm_probe(self, self._block_mh())
            warmed += sc.warm_probe(self, mh2)
        return warmed
