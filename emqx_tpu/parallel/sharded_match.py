"""Multi-chip match + table update over a (dp, sub) mesh.

Two styles, both idiomatic:

* The *match* path relies on XLA SPMD auto-partitioning: the dense
  predicate is elementwise over the [B, N] plane, so sharded inputs
  ([B]→'dp', [N]→'sub') partition it with zero communication; count
  reductions become one psum over 'sub' that XLA inserts on its own.
  (This replaces the reference's full-table replication + local match,
  emqx_router.erl:133-162 — ICI is fast enough to partition instead.)

* The *update* path (route add/delete deltas) uses shard_map because
  each 'sub' shard must translate global row ids into its local slice:
  every shard receives the same delta batch (deltas are tiny — ≤1024
  rows, mirroring emqx_router_syncer batches) and applies the rows it
  owns with a masked scatter; rows outside the shard drop out. This is
  the mria-rlog analog: one write stream, applied shard-locally.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match import EncodedTopics, _match_block, _pack_bits
from ..ops.table import EncodedFilters
from .mesh import DP_AXIS, SUB_AXIS, filter_sharding, topic_sharding


def make_sharded_kernels(mesh: Mesh):
    """Compile the mesh-partitioned kernels. Returns
    (match_counts, match_packed, apply_delta)."""

    f_shard = filter_sharding(mesh)
    t_shard = topic_sharding(mesh)
    counts_out = NamedSharding(mesh, P(DP_AXIS))
    packed_out = NamedSharding(mesh, P(DP_AXIS, SUB_AXIS))
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=counts_out,
    )
    def match_counts(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return ok.sum(axis=1, dtype=jnp.int32)  # XLA: psum over 'sub'

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=packed_out,
    )
    def match_packed(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return _pack_bits(ok)

    n_sub = mesh.shape[SUB_AXIS]

    def _apply_delta_local(dev: EncodedFilters, rows, words, plen, hh, rw, act):
        # dev leaves are the LOCAL shard [N/n_sub, ...]; rows are global.
        local_n = dev.words.shape[0]
        offset = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32) * local_n
        local = rows - offset
        # rows outside this shard scatter out of range -> dropped
        oob = (local < 0) | (local >= local_n)
        local = jnp.where(oob, local_n, local)
        return EncodedFilters(
            dev.words.at[local].set(words, mode="drop"),
            dev.prefix_len.at[local].set(plen, mode="drop"),
            dev.has_hash.at[local].set(hh, mode="drop"),
            dev.root_wild.at[local].set(rw, mode="drop"),
            dev.active.at[local].set(act, mode="drop"),
        )

    dev_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    # rows, words, plen, hh, rw, act — all replicated to every shard
    delta_specs = (P(None), P(None, None), P(None), P(None), P(None), P(None))

    @functools.partial(jax.jit, donate_argnums=0)
    def apply_delta(
        dev: EncodedFilters,
        rows: jnp.ndarray,  # int32 [K] global row ids
        words: jnp.ndarray,  # int32 [K, L]
        plen: jnp.ndarray,
        hh: jnp.ndarray,
        rw: jnp.ndarray,
        act: jnp.ndarray,
    ) -> EncodedFilters:
        return jax.shard_map(
            _apply_delta_local,
            mesh=mesh,
            in_specs=(dev_specs,) + delta_specs,
            out_specs=dev_specs,
        )(dev, rows, words, plen, hh, rw, act)

    return match_counts, match_packed, apply_delta
