"""Multi-chip match + table update over a (dp, sub) mesh.

Two styles, both idiomatic:

* The *match* path relies on XLA SPMD auto-partitioning: the dense
  predicate is elementwise over the [B, N] plane, so sharded inputs
  ([B]→'dp', [N]→'sub') partition it with zero communication; count
  reductions become one psum over 'sub' that XLA inserts on its own.
  (This replaces the reference's full-table replication + local match,
  emqx_router.erl:133-162 — ICI is fast enough to partition instead.)

* The *update* path (route add/delete deltas) uses shard_map because
  each 'sub' shard must translate global row ids into its local slice:
  every shard receives the same delta batch (deltas are tiny — ≤1024
  rows, mirroring emqx_router_syncer batches) and applies the rows it
  owns with a masked scatter; rows outside the shard drop out. This is
  the mria-rlog analog: one write stream, applied shard-locally.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match import EncodedTopics, _match_block, _pack_bits
from ..ops.table import EncodedFilters
from .mesh import DP_AXIS, SUB_AXIS, filter_sharding, topic_sharding


def make_sharded_kernels(mesh: Mesh):
    """Compile the mesh-partitioned kernels. Returns
    (match_counts, match_packed, apply_delta)."""

    f_shard = filter_sharding(mesh)
    t_shard = topic_sharding(mesh)
    counts_out = NamedSharding(mesh, P(DP_AXIS))
    packed_out = NamedSharding(mesh, P(DP_AXIS, SUB_AXIS))
    repl = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=counts_out,
    )
    def match_counts(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return ok.sum(axis=1, dtype=jnp.int32)  # XLA: psum over 'sub'

    @functools.partial(
        jax.jit,
        in_shardings=(f_shard, t_shard),
        out_shardings=packed_out,
    )
    def match_packed(filters: EncodedFilters, topics: EncodedTopics):
        ok = _match_block(topics.ids, topics.lens, topics.dollar, *filters)
        return _pack_bits(ok)

    n_sub = mesh.shape[SUB_AXIS]

    def _apply_delta_local(dev: EncodedFilters, rows, words, plen, hh, rw, act):
        # dev leaves are the LOCAL shard [N/n_sub, ...]; rows are
        # GLOBAL ids with a leading delta-batch axis [n_b, K, ...] —
        # all batches apply inside ONE dispatch via scan (chained
        # dispatches do not pipeline through the device relay,
        # PERF_NOTES.md; same rule as the single-device _scatter_rows).
        local_n = dev.words.shape[0]
        offset = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32) * local_n

        def step(d, xs):
            r, w, p, h, rw_, a = xs
            local = r - offset
            # rows outside this shard scatter out of range -> dropped
            oob = (local < 0) | (local >= local_n)
            local = jnp.where(oob, local_n, local)
            return (
                EncodedFilters(
                    d.words.at[local].set(w, mode="drop"),
                    d.prefix_len.at[local].set(p, mode="drop"),
                    d.has_hash.at[local].set(h, mode="drop"),
                    d.root_wild.at[local].set(rw_, mode="drop"),
                    d.active.at[local].set(a, mode="drop"),
                ),
                None,
            )

        out, _ = jax.lax.scan(step, dev, (rows, words, plen, hh, rw, act))
        return out

    dev_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    # rows, words, plen, hh, rw, act — all replicated to every shard
    delta_specs = (
        P(None, None), P(None, None, None), P(None, None),
        P(None, None), P(None, None), P(None, None),
    )

    @functools.partial(jax.jit, donate_argnums=0)
    def apply_delta(
        dev: EncodedFilters,
        rows: jnp.ndarray,  # int32 [n_b, K] global row ids
        words: jnp.ndarray,  # int32 [n_b, K, L]
        plen: jnp.ndarray,
        hh: jnp.ndarray,
        rw: jnp.ndarray,
        act: jnp.ndarray,
    ) -> EncodedFilters:
        return jax.shard_map(
            _apply_delta_local,
            mesh=mesh,
            in_specs=(dev_specs,) + delta_specs,
            out_specs=dev_specs,
        )(dev, rows, words, plen, hh, rw, act)

    return match_counts, match_packed, apply_delta


def make_match_ids_kernel(mesh: Mesh, max_hits_per_block: int):
    """Sharded compaction kernel: every (dp, sub) block matches its
    LOCAL [B/dp, N/sub] tile and compacts its hits to fixed-size
    (topic, row) id buffers with GLOBAL indices (axis_index offsets) —
    the device→host transfer stays proportional to matches per block,
    the multi-chip version of ops.match.match_ids. Returns
    (ti [dp, sub*mh], ri [dp, sub*mh], totals [dp, sub]); slots are -1
    beyond each block's true count, and a block whose total exceeds
    max_hits_per_block overflowed (caller escalates)."""

    f_specs = EncodedFilters(
        P(SUB_AXIS, None), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS), P(SUB_AXIS)
    )
    t_specs = EncodedTopics(P(DP_AXIS, None), P(DP_AXIS), P(DP_AXIS))
    mh = max_hits_per_block

    def _local(ids, lens, dollar, words, plen, hh, rw, act):
        dp_i = jax.lax.axis_index(DP_AXIS).astype(jnp.int32)
        sub_i = jax.lax.axis_index(SUB_AXIS).astype(jnp.int32)
        ok = _match_block(ids, lens, dollar, words, plen, hh, rw, act)
        b_loc, n_loc = ok.shape
        cnt = ok.sum(dtype=jnp.int32)
        idx = jnp.nonzero(ok.reshape(-1), size=mh, fill_value=-1)[0]
        valid = idx >= 0
        ti = jnp.where(valid, idx // n_loc + dp_i * b_loc, -1).astype(jnp.int32)
        ri = jnp.where(valid, idx % n_loc + sub_i * n_loc, -1).astype(jnp.int32)
        return ti[None, :], ri[None, :], cnt.reshape(1, 1)

    @jax.jit
    def match_ids(filters: EncodedFilters, topics: EncodedTopics):
        return jax.shard_map(
            _local,
            mesh=mesh,
            in_specs=(
                t_specs.ids, t_specs.lens, t_specs.dollar,
                f_specs.words, f_specs.prefix_len, f_specs.has_hash,
                f_specs.root_wild, f_specs.active,
            ),
            out_specs=(
                P(DP_AXIS, SUB_AXIS),
                P(DP_AXIS, SUB_AXIS),
                P(DP_AXIS, SUB_AXIS),
            ),
        )(
            topics.ids, topics.lens, topics.dollar,
            filters.words, filters.prefix_len, filters.has_hash,
            filters.root_wild, filters.active,
        )

    return match_ids


class ShardedDeviceTable:
    """Mesh-resident mirror of a FilterTable: rows sub-sharded across
    the mesh, topics dp-sharded, batched delta sync through the
    shard_map scatter. The multi-device counterpart of
    models.router.DeviceTable behind the same sync()/match surface —
    replication-as-partitioning instead of the reference's full
    per-node table replica (emqx_router.erl:133-162)."""

    DELTA_BATCH = 1024  # rows per apply_delta call (syncer batch size)

    def __init__(self, table, mesh: Mesh, max_hits_per_block: int = 2048):
        from . import mesh as mesh_mod

        self.table = table
        self.mesh = mesh
        self._mesh_mod = mesh_mod
        self._dev: Optional[EncodedFilters] = None
        self._synced_capacity = 0
        _mc, _mp, self._apply_delta = make_sharded_kernels(mesh)
        self._match_ids_cache: dict = {}
        self.default_mh = max_hits_per_block

    def _match_kernel(self, mh: int):
        k = self._match_ids_cache.get(mh)
        if k is None:
            k = make_match_ids_kernel(self.mesh, mh)
            self._match_ids_cache[mh] = k
        return k

    def sync(self) -> int:
        t = self.table
        if self._dev is None or t.grew or t.capacity != self._synced_capacity:
            n = len(t.dirty)
            t.drain_dirty()
            self._dev = self._mesh_mod.put_filters(t.snapshot(), self.mesh)
            self._synced_capacity = t.capacity
            return n
        dirty = t.drain_dirty()  # ndarray: row id 0 alone is falsy —
        if len(dirty) == 0:      # test LENGTH, never truthiness
            return 0
        import numpy as np

        total = len(dirty)
        arr = np.asarray(dirty, np.int32)
        # ONE dispatch for the whole churn: pad to [n_b, K] (n_b pow2
        # so recompiles stay log-bounded) and scan inside the kernel
        k = self.DELTA_BATCH
        n_b = 1 << max(0, -(-total // k) - 1).bit_length()  # pow2 ceil-div
        idx = np.full(n_b * k, arr[-1], np.int32)
        idx[:total] = arr
        shape2 = (n_b, k)
        self._dev = self._apply_delta(
            self._dev,
            jnp.asarray(idx.reshape(shape2)),
            jnp.asarray(t.words[idx].reshape(shape2 + (t.max_levels,))),
            jnp.asarray(t.prefix_len[idx].reshape(shape2)),
            jnp.asarray(t.has_hash[idx].reshape(shape2)),
            jnp.asarray(t.root_wild[idx].reshape(shape2)),
            jnp.asarray(t.active[idx].reshape(shape2)),
        )
        return total

    def match_ids(self, enc: EncodedTopics):
        """All (topic, row) hit pairs for an encoded topic batch.
        Returns (ti 1d, ri 1d) host arrays of equal length (valid pairs
        only), escalating per-block capacity on overflow."""
        import numpy as np

        assert self._dev is not None, "sync() before matching"
        t_dev = self._mesh_mod.put_topics(enc, self.mesh)
        mh = self.default_mh
        while True:
            ti, ri, totals = self._match_kernel(mh)(self._dev, t_dev)
            totals = np.asarray(totals)
            if int(totals.max(initial=0)) <= mh:
                break
            mh = max(mh * 2, 1 << int(totals.max()).bit_length())
        ti = np.asarray(ti).reshape(-1)
        ri = np.asarray(ri).reshape(-1)
        keep = ti >= 0
        return ti[keep], ri[keep]
