"""Device mesh + sharding layout for the distributed matcher.

The reference scales by (a) fully replicating route tables per node
(mria ram_copies, emqx_router.erl:133-162) and (b) sharding fanout
work across pools (SURVEY.md §2.5). On a TPU pod the idiomatic layout
is the opposite of replication: *partition* the subscription table
across chips and let ICI collectives reassemble per-topic results —
the moral equivalent of context parallelism over the subscription
axis:

  mesh axes:
    dp   — topic-batch data parallelism (inbound publish stream split)
    sub  — subscription-table model parallelism (filter rows split)

  shardings:
    filter table arrays  [N, ...]  -> P('sub')         (rows split)
    topic batch arrays   [B, ...]  -> P('dp')          (batch split)
    match matrix         [B, N]    -> P('dp', 'sub')   (2-D tiles)
    per-topic counts     [B]       -> P('dp')          (psum over sub)

XLA's SPMD partitioner inserts the all-reduce over 'sub' for count
reductions; packed bitmaps stay tiled and are fetched shard-wise.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match import EncodedTopics
from ..ops.table import EncodedFilters

DP_AXIS = "dp"
SUB_AXIS = "sub"


def make_mesh(
    n_dp: Optional[int] = None,
    n_sub: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (dp, sub) mesh over the given (default: all) devices.
    With neither count given, prefers sharding the subscription axis
    (n_dp=1): table HBM capacity is the scaling reason to go
    multi-chip at all (10M+ filter rows)."""
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n_dp is None and n_sub is None:
        n_dp, n_sub = 1, n
    elif n_dp is None:
        assert n % n_sub == 0, (n, n_sub)
        n_dp = n // n_sub
    elif n_sub is None:
        assert n % n_dp == 0, (n, n_dp)
        n_sub = n // n_dp
    assert n_dp * n_sub == n, (n_dp, n_sub, n)
    arr = np.asarray(devs).reshape(n_dp, n_sub)
    return Mesh(arr, (DP_AXIS, SUB_AXIS))


def primary_device(mesh: Mesh):
    """The mesh's first device — where small tables serve when the
    tpu_mesh_min_rows_per_shard admission knob degrades sharded
    serving to a single chip (the mesh overhead exceeds the kernel
    work it would spread; see ShardedDeviceTable.min_rows_per_shard).
    The EMQX analog is the core/replicant role split: not every node
    holds (or should hold) a table shard."""
    return np.asarray(mesh.devices).reshape(-1)[0]


def filter_sharding(mesh: Mesh) -> EncodedFilters:
    """Shardings for each EncodedFilters leaf (rows over 'sub')."""
    row = NamedSharding(mesh, P(SUB_AXIS))
    return EncodedFilters(
        NamedSharding(mesh, P(SUB_AXIS, None)), row, row, row, row
    )


def topic_sharding(mesh: Mesh) -> EncodedTopics:
    """Shardings for each EncodedTopics leaf (batch over 'dp')."""
    row = NamedSharding(mesh, P(DP_AXIS))
    return EncodedTopics(NamedSharding(mesh, P(DP_AXIS, None)), row, row)


def shard_rows(n: int, mesh: Mesh) -> int:
    """Rows per 'sub' shard for an n-row table: ceil(n / n_sub). When
    n_sub divides n this is the exact slice; otherwise the trailing
    `shard_rows*n_sub - n` positions are inert padding (active=False),
    which is what lets an N-1 survivor mesh keep serving a pow2
    capacity after a chip is evacuated. Because the pad sits at the
    END of the flat array, padded-global position == logical row id
    for every real row, so axis_index offset arithmetic in the
    shard_map kernels is unchanged."""
    n_sub = mesh.shape[SUB_AXIS]
    return -(-n // n_sub)


def put_filters(filters: EncodedFilters, mesh: Mesh) -> EncodedFilters:
    """Place a host filter-table snapshot onto the mesh, rows split
    over 'sub'. Row counts that don't divide the sub axis (an N-1
    survivor mesh serving a pow2 capacity) get trailing inert pad rows
    (zeros, active=False — they can never match)."""
    n = filters.words.shape[0]
    pad = shard_rows(n, mesh) * mesh.shape[SUB_AXIS] - n
    if pad:
        filters = EncodedFilters(
            np.pad(filters.words, ((0, pad), (0, 0))),
            np.pad(filters.prefix_len, (0, pad)),
            np.pad(filters.has_hash, (0, pad)),
            np.pad(filters.root_wild, (0, pad)),
            np.pad(filters.active, (0, pad)),
        )
    shs = filter_sharding(mesh)
    return EncodedFilters(
        *(jax.device_put(a, s) for a, s in zip(filters, shs))
    )


def pad_topics(enc: EncodedTopics, mesh: Mesh) -> EncodedTopics:
    """Host half of `put_topics`: pad the batch up to a multiple of the
    dp axis size. Split out so the mesh microscope can lap the host pad
    (`host_encode`) separately from the H2D placement (`h2d_stage`);
    idempotent — a pre-padded batch passes through unchanged."""
    n_dp = mesh.shape[DP_AXIS]
    b = enc.ids.shape[0]
    pad = (-b) % n_dp
    if pad:
        # dollar=True pad rows are inert (match nothing): they must
        # not burn per-block hit slots against '#'-class filters
        enc = EncodedTopics(
            np.pad(enc.ids, ((0, pad), (0, 0))),
            np.pad(enc.lens, (0, pad)),
            np.pad(enc.dollar, (0, pad), constant_values=True),
        )
    return enc


def put_topics(enc: EncodedTopics, mesh: Mesh) -> EncodedTopics:
    """Place an encoded topic batch onto the mesh, batch over 'dp'.
    Pads the batch up to a multiple of the dp axis size."""
    enc = pad_topics(enc, mesh)
    shs = topic_sharding(mesh)
    return EncodedTopics(*(jax.device_put(a, s) for a, s in zip(enc, shs)))
