"""Avro binary codec, written from the Avro 1.11 specification.

The reference's schema registry compiles avro schemas through erlavro
(apps/emqx_schema_registry/src/emqx_schema_registry.erl, serde type
`avro`); this is the same wire format from first principles:

    int/long    zigzag varint            float/double  IEEE LE
    bytes/str   long-prefixed            boolean       1 byte
    record      fields in order          enum          int index
    array/map   blocked (count, items, 0 terminator; negative count =
                block byte size follows — accepted on decode)
    union       long index + value      fixed          raw bytes

Schemas are the standard JSON shape (dict / list for unions / name
strings for primitives). Named-type references resolve against the
schema's own definitions (one level of recursion is enough for
self-referential records)."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string",
}


class AvroError(ValueError):
    pass


def _zz_enc(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zz_dec(data: bytes, off: int) -> Tuple[int, int]:
    u, shift = 0, 0
    while True:
        if off >= len(data):
            raise AvroError("truncated varint")
        b = data[off]
        off += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (u >> 1) ^ -(u & 1), off


class AvroSchema:
    """Parsed schema + named-type table; encode/decode entry points."""

    def __init__(self, schema: Any) -> None:
        self.named: Dict[str, Any] = {}
        self.schema = self._index(schema)

    def _index(self, s: Any) -> Any:
        if isinstance(s, dict):
            t = s.get("type")
            if t in ("record", "enum", "fixed"):
                name = s.get("name")
                if not name:
                    raise AvroError(f"{t} needs a name")
                self.named[name] = s
                ns = s.get("namespace")
                if ns:
                    self.named[f"{ns}.{name}"] = s
            if t == "record":
                for f in s.get("fields", []):
                    self._index(f.get("type"))
            elif t == "array":
                self._index(s.get("items"))
            elif t == "map":
                self._index(s.get("values"))
        elif isinstance(s, list):
            for b in s:
                self._index(b)
        return s

    def _resolve(self, s: Any) -> Any:
        if isinstance(s, str) and s not in PRIMITIVES:
            r = self.named.get(s)
            if r is None:
                raise AvroError(f"unknown type {s!r}")
            return r
        if isinstance(s, dict) and isinstance(s.get("type"), str) and (
            s["type"] not in PRIMITIVES
            and s["type"] not in ("record", "enum", "fixed", "array", "map")
        ):
            return self._resolve(s["type"])
        return s

    # --- encode -----------------------------------------------------------

    def encode(self, value: Any) -> bytes:
        return self._enc(self.schema, value)

    def _enc(self, s: Any, v: Any) -> bytes:
        s = self._resolve(s)
        if isinstance(s, list):  # union
            for i, branch in enumerate(s):
                if self._matches(branch, v):
                    return _zz_enc(i) + self._enc(branch, v)
            raise AvroError(f"no union branch for {type(v).__name__}")
        t = s["type"] if isinstance(s, dict) else s
        if t == "null":
            if v is not None:
                raise AvroError("null expects None")
            return b""
        if t == "boolean":
            return b"\x01" if v else b"\x00"
        if t in ("int", "long"):
            return _zz_enc(int(v))
        if t == "float":
            return struct.pack("<f", float(v))
        if t == "double":
            return struct.pack("<d", float(v))
        if t in ("bytes", "string"):
            b = v.encode() if isinstance(v, str) else bytes(v)
            return _zz_enc(len(b)) + b
        if t == "fixed":
            b = bytes(v)
            if len(b) != s["size"]:
                raise AvroError(f"fixed size {s['size']} != {len(b)}")
            return b
        if t == "enum":
            syms = s["symbols"]
            if v not in syms:
                raise AvroError(f"{v!r} not in enum {s.get('name')}")
            return _zz_enc(syms.index(v))
        if t == "array":
            out = b""
            if v:
                out += _zz_enc(len(v))
                for item in v:
                    out += self._enc(s["items"], item)
            return out + _zz_enc(0)
        if t == "map":
            out = b""
            if v:
                out += _zz_enc(len(v))
                for k, item in v.items():
                    out += self._enc("string", k) + self._enc(s["values"], item)
            return out + _zz_enc(0)
        if t == "record":
            out = b""
            for f in s["fields"]:
                name = f["name"]
                if name in v:
                    fv = v[name]
                elif "default" in f:
                    fv = f["default"]
                else:
                    raise AvroError(f"missing record field {name!r}")
                out += self._enc(f["type"], fv)
            return out
        raise AvroError(f"unsupported type {t!r}")

    def _matches(self, s: Any, v: Any) -> bool:
        s = self._resolve(s)
        t = s["type"] if isinstance(s, dict) else s
        if t == "null":
            return v is None
        if t == "boolean":
            return isinstance(v, bool)
        if t in ("int", "long"):
            return isinstance(v, int) and not isinstance(v, bool)
        if t in ("float", "double"):
            return isinstance(v, (int, float)) and not isinstance(v, bool)
        if t == "string":
            return isinstance(v, str)
        if t in ("bytes", "fixed"):
            return isinstance(v, (bytes, bytearray))
        if t == "enum":
            return isinstance(v, str) and v in s.get("symbols", [])
        if t == "array":
            return isinstance(v, list)
        if t in ("map", "record"):
            return isinstance(v, dict)
        return False

    # --- decode -----------------------------------------------------------

    def decode(self, data: bytes) -> Any:
        v, off = self._dec(self.schema, data, 0)
        if off != len(data):
            raise AvroError(f"{len(data) - off} trailing bytes")
        return v

    def _dec(self, s: Any, data: bytes, off: int) -> Tuple[Any, int]:
        s = self._resolve(s)
        if isinstance(s, list):
            idx, off = _zz_dec(data, off)
            if not 0 <= idx < len(s):
                raise AvroError(f"union index {idx} out of range")
            return self._dec(s[idx], data, off)
        t = s["type"] if isinstance(s, dict) else s
        if t == "null":
            return None, off
        if t == "boolean":
            return data[off] != 0, off + 1
        if t in ("int", "long"):
            return _zz_dec(data, off)
        if t == "float":
            return struct.unpack_from("<f", data, off)[0], off + 4
        if t == "double":
            return struct.unpack_from("<d", data, off)[0], off + 8
        if t in ("bytes", "string"):
            n, off = _zz_dec(data, off)
            if n < 0 or off + n > len(data):
                raise AvroError("bad bytes length")
            raw = data[off : off + n]
            off += n
            if t == "string":
                return raw.decode("utf-8"), off
            return bytes(raw), off
        if t == "fixed":
            n = s["size"]
            return bytes(data[off : off + n]), off + n
        if t == "enum":
            idx, off = _zz_dec(data, off)
            syms = s["symbols"]
            if not 0 <= idx < len(syms):
                raise AvroError(f"enum index {idx} out of range")
            return syms[idx], off
        if t == "array":
            out: List[Any] = []
            while True:
                cnt, off = _zz_dec(data, off)
                if cnt == 0:
                    return out, off
                if cnt < 0:  # block size prefix variant
                    cnt = -cnt
                    _sz, off = _zz_dec(data, off)
                for _ in range(cnt):
                    v, off = self._dec(s["items"], data, off)
                    out.append(v)
        if t == "map":
            m: Dict[str, Any] = {}
            while True:
                cnt, off = _zz_dec(data, off)
                if cnt == 0:
                    return m, off
                if cnt < 0:
                    cnt = -cnt
                    _sz, off = _zz_dec(data, off)
                for _ in range(cnt):
                    k, off = self._dec("string", data, off)
                    v, off = self._dec(s["values"], data, off)
                    m[k] = v
        if t == "record":
            rec: Dict[str, Any] = {}
            for f in s["fields"]:
                rec[f["name"]], off = self._dec(f["type"], data, off)
            return rec, off
        raise AvroError(f"unsupported type {t!r}")
