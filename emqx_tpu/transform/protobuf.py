"""Protobuf (proto3 subset): a .proto source parser + wire codec.

The reference compiles .proto sources at runtime (gpb behind
apps/emqx_schema_registry, serde type `protobuf`); this module covers
the subset IoT payload schemas actually use — scalar fields, repeated
fields, nested/imported-by-name message types and enums:

    wire types: 0 varint (int32/64, uint, sint zigzag, bool, enum)
                1 64-bit (fixed64, sfixed64, double)
                2 length-delimited (string, bytes, message, packed)
                5 32-bit (fixed32, sfixed32, float)

Unknown fields are skipped on decode (proto3 semantics); missing
fields decode to defaults. oneof/groups/maps/services are not
supported and raise at PARSE time — a schema the codec can't honor is
rejected when it is registered, never mid-traffic.
"""

from __future__ import annotations

import re
import struct
from typing import Any, Dict, List, Optional, Tuple


class ProtobufError(ValueError):
    pass


_SCALARS = {
    "double", "float", "int32", "int64", "uint32", "uint64", "sint32",
    "sint64", "fixed32", "fixed64", "sfixed32", "sfixed64", "bool",
    "string", "bytes",
}
_VARINT = {"int32", "int64", "uint32", "uint64", "sint32", "sint64", "bool"}
_UNSUPPORTED = ("oneof", "group", "map<", "service", "extend")


class Field:
    def __init__(self, name: str, ftype: str, number: int, repeated: bool):
        self.name = name
        self.ftype = ftype
        self.number = number
        self.repeated = repeated


class ProtoFile:
    """Parsed .proto: message name -> fields, enum name -> symbol map."""

    def __init__(self, source: str) -> None:
        self.messages: Dict[str, List[Field]] = {}
        self.enums: Dict[str, Dict[str, int]] = {}
        self._parse(source)

    def _parse(self, src: str) -> None:
        src = re.sub(r"//[^\n]*", "", src)
        src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
        for kw in _UNSUPPORTED:
            if kw in src:
                raise ProtobufError(f"unsupported proto feature: {kw}")
        # nested blocks flatten into the global namespace (enough for
        # the flat schemas bridges carry; name clashes reject)
        self._parse_block(src, "")

    def _parse_block(self, src: str, prefix: str) -> None:
        pos = 0
        while True:
            m = re.search(r"\b(message|enum)\s+(\w+)\s*\{", src[pos:])
            if m is None:
                break
            kind, name = m.group(1), m.group(2)
            start = pos + m.end()
            depth = 1
            i = start
            while i < len(src) and depth:
                if src[i] == "{":
                    depth += 1
                elif src[i] == "}":
                    depth -= 1
                i += 1
            if depth:
                raise ProtobufError(f"unbalanced braces in {name}")
            body = src[start : i - 1]
            if name in self.messages or name in self.enums:
                raise ProtobufError(f"duplicate type {name}")
            if kind == "enum":
                self._parse_enum(name, body)
            else:
                self._parse_block(body, name)  # nested types first
                self._parse_message(name, body)
            pos = i

    def _parse_enum(self, name: str, body: str) -> None:
        syms: Dict[str, int] = {}
        for sm in re.finditer(r"(\w+)\s*=\s*(-?\d+)\s*;", body):
            syms[sm.group(1)] = int(sm.group(2))
        self.enums[name] = syms

    def _parse_message(self, name: str, body: str) -> None:
        # strip nested blocks already handled
        flat = re.sub(r"\b(message|enum)\s+\w+\s*\{[^{}]*\}", "", body)
        fields: List[Field] = []
        for fm in re.finditer(
            r"(repeated\s+|optional\s+|required\s+)?(\w+)\s+(\w+)\s*=\s*(\d+)",
            flat,
        ):
            label, ftype, fname, num = fm.groups()
            if ftype in ("message", "enum", "syntax", "package", "option"):
                continue
            fields.append(Field(
                fname, ftype, int(num),
                (label or "").strip() == "repeated",
            ))
        self.messages[name] = fields

    def field_type(self, f: Field) -> str:
        if f.ftype in _SCALARS:
            return f.ftype
        if f.ftype in self.enums:
            return "enum"
        if f.ftype in self.messages:
            return "message"
        raise ProtobufError(f"unknown field type {f.ftype!r}")


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, off: int) -> Tuple[int, int]:
    u, shift = 0, 0
    while True:
        if off >= len(data):
            raise ProtobufError("truncated varint")
        b = data[off]
        off += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            return u, off
        shift += 7
        if shift > 70:
            raise ProtobufError("varint too long")


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


class ProtoCodec:
    def __init__(self, proto: ProtoFile, message_type: str) -> None:
        if message_type not in proto.messages:
            raise ProtobufError(f"message {message_type!r} not defined")
        self.proto = proto
        self.message_type = message_type

    # --- encode ----------------------------------------------------------

    def encode(self, value: Dict[str, Any],
               mtype: Optional[str] = None) -> bytes:
        mtype = mtype or self.message_type
        out = bytearray()
        for f in self.proto.messages[mtype]:
            if f.name not in value or value[f.name] is None:
                continue
            vs = value[f.name] if f.repeated else [value[f.name]]
            for v in vs:
                out += self._enc_field(f, v)
        return bytes(out)

    def _enc_field(self, f: Field, v: Any) -> bytes:
        ft = self.proto.field_type(f)
        num = f.number
        if ft == "message":
            body = self.encode(v, f.ftype)
            return _uvarint((num << 3) | 2) + _uvarint(len(body)) + body
        if ft == "enum":
            syms = self.proto.enums[f.ftype]
            iv = syms[v] if isinstance(v, str) else int(v)
            return _uvarint((num << 3) | 0) + _uvarint(iv & 0xFFFFFFFFFFFFFFFF)
        t = f.ftype
        if t in _VARINT:
            if t in ("sint32", "sint64"):
                u = _zigzag(int(v))
            elif t == "bool":
                u = 1 if v else 0
            else:
                u = int(v) & 0xFFFFFFFFFFFFFFFF
            return _uvarint((num << 3) | 0) + _uvarint(u)
        if t in ("fixed64", "sfixed64", "double"):
            fmt = {"double": "<d", "fixed64": "<Q", "sfixed64": "<q"}[t]
            return _uvarint((num << 3) | 1) + struct.pack(fmt, v)
        if t in ("fixed32", "sfixed32", "float"):
            fmt = {"float": "<f", "fixed32": "<I", "sfixed32": "<i"}[t]
            return _uvarint((num << 3) | 5) + struct.pack(fmt, v)
        if t in ("string", "bytes"):
            b = v.encode() if isinstance(v, str) else bytes(v)
            return _uvarint((num << 3) | 2) + _uvarint(len(b)) + b
        raise ProtobufError(f"cannot encode type {t!r}")

    # --- decode ----------------------------------------------------------

    def decode(self, data: bytes, mtype: Optional[str] = None) -> Dict[str, Any]:
        mtype = mtype or self.message_type
        fields = {f.number: f for f in self.proto.messages[mtype]}
        out: Dict[str, Any] = {
            f.name: [] for f in fields.values() if f.repeated
        }
        off = 0
        n = len(data)
        while off < n:
            tag, off = _read_uvarint(data, off)
            num, wt = tag >> 3, tag & 0x7
            f = fields.get(num)
            if wt == 0:
                u, off = _read_uvarint(data, off)
                raw: Any = u
            elif wt == 1:
                raw = data[off : off + 8]
                off += 8
            elif wt == 2:
                ln, off = _read_uvarint(data, off)
                if off + ln > n:
                    raise ProtobufError("truncated length-delimited field")
                raw = data[off : off + ln]
                off += ln
            elif wt == 5:
                raw = data[off : off + 4]
                off += 4
            else:
                raise ProtobufError(f"unsupported wire type {wt}")
            if f is None:
                continue  # unknown field: proto3 skip
            v = self._coerce(f, wt, raw)
            if f.repeated:
                if isinstance(v, list):
                    out[f.name].extend(v)  # packed
                else:
                    out[f.name].append(v)
            else:
                out[f.name] = v
        return out

    def _coerce(self, f: Field, wt: int, raw: Any) -> Any:
        ft = self.proto.field_type(f)
        if ft == "message":
            return self.decode(bytes(raw), f.ftype)
        if ft == "enum":
            rev = {v: k for k, v in self.proto.enums[f.ftype].items()}
            return rev.get(int(raw), int(raw))
        t = f.ftype
        if t in _VARINT and wt == 0:
            if t in ("sint32", "sint64"):
                return _unzigzag(raw)
            if t == "bool":
                return bool(raw)
            if t in ("int32", "int64") and raw >= 1 << 63:
                return raw - (1 << 64)  # negative two's complement
            return raw
        if wt == 2 and t in _VARINT and f.repeated:
            vals = []  # packed repeated varints
            off = 0
            while off < len(raw):
                u, off = _read_uvarint(raw, off)
                vals.append(
                    _unzigzag(u) if t in ("sint32", "sint64") else u
                )
            return vals
        if t == "double":
            return struct.unpack("<d", raw)[0]
        if t == "float":
            return struct.unpack("<f", raw)[0]
        if t in ("fixed64",):
            return struct.unpack("<Q", raw)[0]
        if t in ("sfixed64",):
            return struct.unpack("<q", raw)[0]
        if t in ("fixed32",):
            return struct.unpack("<I", raw)[0]
        if t in ("sfixed32",):
            return struct.unpack("<i", raw)[0]
        if t == "string":
            return bytes(raw).decode("utf-8")
        if t == "bytes":
            return bytes(raw)
        raise ProtobufError(f"cannot decode {t!r} (wire type {wt})")


def make_codec_cache(proto: "ProtoFile"):
    """Per-proto memoized ProtoCodec lookup: cache = make_codec_cache(p);
    cache("MsgType") -> codec. Shared by the gRPC-speaking modules."""
    codecs: Dict[str, ProtoCodec] = {}

    def get(mtype: str) -> ProtoCodec:
        c = codecs.get(mtype)
        if c is None:
            c = codecs[mtype] = ProtoCodec(proto, mtype)
        return c

    return get
