"""Message transformation: declarative rewrites of matched publishes.

Parity with apps/emqx_message_transformation: transformations carry a
topic filter list, payload decoder/encoder (json | none), and an
operation list assigning values (literals or ${var} templates over
message fields and payload paths) to targets (payload.<path>, topic,
qos, retain, user_property.<k>); failure action drop | ignore, firing
'message.transformation_failed' on error.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..broker.hooks import STOP
from ..broker.message import Message
from ..ops import topic as topic_mod
from ..ops.host_index import TopicTrie


class TransformError(ValueError):
    pass


def _get_path(obj: Any, path: List[str]):
    for p in path:
        if isinstance(obj, dict):
            obj = obj.get(p)
        else:
            return None
    return obj


def _set_path(obj: dict, path: List[str], value: Any) -> None:
    for p in path[:-1]:
        nxt = obj.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            obj[p] = nxt
        obj = nxt
    obj[path[-1]] = value


def _render(template: Any, msg: Message, payload: Any):
    """A value: literal, or '${...}' reference into the message
    (topic/qos/retain/clientid/username/payload.<path>)."""
    if not (isinstance(template, str) and template.startswith("${")
            and template.endswith("}")):
        return template
    ref = template[2:-1]
    if ref == "topic":
        return msg.topic
    if ref == "qos":
        return msg.qos
    if ref == "retain":
        return msg.retain
    if ref == "clientid":
        return msg.from_client
    if ref == "username":
        return msg.headers.get("username", "")
    if ref == "timestamp":
        return msg.timestamp
    if ref == "payload":
        return payload
    if ref.startswith("payload."):
        return _get_path(payload, ref[len("payload."):].split("."))
    raise TransformError(f"unknown reference {template!r}")


class Transformation:
    def __init__(self, conf: dict):
        self.name = conf["name"]
        self.topics = list(conf["topics"])
        self.payload_decoder = conf.get("payload_decoder", "json")
        self.payload_encoder = conf.get("payload_encoder", self.payload_decoder)
        if self.payload_decoder not in ("json", "none"):
            raise ValueError(f"unknown payload_decoder {self.payload_decoder!r}")
        if self.payload_encoder not in ("json", "none"):
            raise ValueError(f"unknown payload_encoder {self.payload_encoder!r}")
        self.failure_action = conf.get("failure_action", "drop")
        if self.failure_action not in ("drop", "ignore"):
            raise ValueError(f"unknown failure_action {self.failure_action!r}")
        self.operations = list(conf.get("operations", ()))
        # payload ops with a non-json pipeline would be silently
        # discarded at encode time — reject the CONFIG, not the traffic
        if any(op.get("key", "").startswith("payload") for op in self.operations):
            if self.payload_decoder != "json" or self.payload_encoder != "json":
                raise ValueError(
                    "payload operations require payload_decoder and "
                    "payload_encoder to be 'json'"
                )
        self.enabled = conf.get("enabled", True)
        self.matched = 0
        self.failed = 0

    def apply(self, msg: Message) -> Message:
        self.matched += 1
        payload: Any = None
        if self.payload_decoder == "json":
            try:
                payload = json.loads(msg.payload) if msg.payload else {}
            except (ValueError, UnicodeDecodeError) as e:
                raise TransformError(f"payload decode: {e}") from e
        out = Message(**{**msg.__dict__})
        out.props = dict(msg.props)
        out.headers = dict(msg.headers)
        payload_dirty = False
        for op in self.operations:
            key, value = op["key"], _render(op.get("value"), msg, payload)
            if key == "topic":
                if not isinstance(value, str) or not value:
                    raise TransformError("topic must be a non-empty string")
                topic_mod.validate_name(value)
                out.topic = value
            elif key == "qos":
                if value not in (0, 1, 2):
                    raise TransformError(f"bad qos {value!r}")
                out.qos = value
            elif key == "retain":
                out.retain = bool(value)
            elif key.startswith("payload"):
                if self.payload_decoder != "json":
                    raise TransformError("payload ops need the json decoder")
                if key == "payload":
                    payload = value
                else:
                    if not isinstance(payload, dict):
                        payload = {}
                    _set_path(payload, key[len("payload."):].split("."), value)
                payload_dirty = True
            elif key.startswith("user_property."):
                up = dict(out.props.get("user_property") or {})
                up[key[len("user_property."):]] = str(value)
                out.props["user_property"] = up
            else:
                raise TransformError(f"unknown target {key!r}")
        if payload_dirty and self.payload_encoder == "json":
            out.payload = json.dumps(payload, separators=(",", ":")).encode()
        return out


class MessageTransformation:
    def __init__(self, broker):
        self.broker = broker
        self._transforms: Dict[str, Transformation] = {}
        self._order: List[str] = []
        self._index = TopicTrie()
        self._enabled = False

    def put(self, conf: dict) -> Transformation:
        t = Transformation(conf)
        # validate EVERYTHING before touching live state — a bad
        # filter must not leave a half-registered transform active
        for flt in t.topics:
            topic_mod.validate_filter(flt)
        old = self._transforms.get(t.name)
        if old is not None:
            self._drop_index(old)
        else:
            self._order.append(t.name)
        self._transforms[t.name] = t
        for flt in t.topics:
            self._index.insert(topic_mod.words(flt), t.name)
        return t

    def delete(self, name: str) -> bool:
        t = self._transforms.pop(name, None)
        if t is None:
            return False
        self._order.remove(name)
        self._drop_index(t)
        return True

    def _drop_index(self, t: Transformation) -> None:
        for flt in t.topics:
            try:
                self._index.remove(topic_mod.words(flt), t.name)
            except KeyError:
                pass

    def list(self) -> List[dict]:
        return [
            {
                "name": n,
                "topics": self._transforms[n].topics,
                "matched": self._transforms[n].matched,
                "failed": self._transforms[n].failed,
            }
            for n in self._order
        ]

    def enable(self) -> None:
        if not self._enabled:
            # after validation (860): validate the ORIGINAL payload
            self.broker.hooks.add("message.publish", self._on_publish, priority=850)
            self._enabled = True

    def disable(self) -> None:
        if self._enabled:
            self.broker.hooks.delete("message.publish", self._on_publish)
            self._enabled = False

    def _on_publish(self, msg: Message):
        names = set(self._index.match(topic_mod.words(msg.topic)))
        if not names:
            return None
        cur = msg
        changed = False
        for name in self._order:
            if name not in names:
                continue
            t = self._transforms[name]
            if not t.enabled:
                continue
            try:
                cur = t.apply(cur)
                changed = True
            except TransformError:
                t.failed += 1
                self.broker.metrics.inc("message_transformation.failed")
                self.broker.hooks.run("message.transformation_failed", cur, name)
                if t.failure_action == "ignore":
                    continue
                out = Message(**{**cur.__dict__})
                out.headers = dict(cur.headers, allow_publish=False)
                return (STOP, out)
        return cur if changed else None
