"""Schema registry: named payload schemas shared by validation,
transformation, and rules (emqx_schema_registry analog). Built-in
serde types: a JSON-Schema subset, AVRO binary (transform/avro.py,
written from the Avro spec like the reference's erlavro serde), a
proto3 subset compiled from .proto source (transform/protobuf.py),
plus a seam for callable external decoders. A process-default
registry instance backs the rule-engine schema_decode/schema_encode
functions (emqx_schema_registry_serde:handle_rule_function).
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional


class SchemaError(ValueError):
    pass


def check_json_schema(schema: dict, value: Any, path: str = "$") -> None:
    """Validate `value` against a JSON-Schema subset: type, properties,
    required, items, enum, minimum/maximum, minLength/maxLength.
    Raises SchemaError with the failing path."""
    t = schema.get("type")
    if t is not None:
        ok = {
            "object": lambda v: isinstance(v, dict),
            "array": lambda v: isinstance(v, list),
            "string": lambda v: isinstance(v, str),
            "number": lambda v: isinstance(v, (int, float))
            and not isinstance(v, bool),
            "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
            "boolean": lambda v: isinstance(v, bool),
            "null": lambda v: v is None,
        }.get(t)
        if ok is None:
            raise SchemaError(f"unknown schema type {t!r}")
        if not ok(value):
            raise SchemaError(f"{path}: expected {t}")
    if "enum" in schema and value not in schema["enum"]:
        raise SchemaError(f"{path}: {value!r} not in enum")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            raise SchemaError(f"{path}: {value} < minimum")
        if "maximum" in schema and value > schema["maximum"]:
            raise SchemaError(f"{path}: {value} > maximum")
    if isinstance(value, str):
        if "minLength" in schema and len(value) < schema["minLength"]:
            raise SchemaError(f"{path}: too short")
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            raise SchemaError(f"{path}: too long")
    if isinstance(value, dict):
        for req in schema.get("required", ()):
            if req not in value:
                raise SchemaError(f"{path}.{req}: required")
        props = schema.get("properties", {})
        for k, sub in props.items():
            if k in value:
                check_json_schema(sub, value[k], f"{path}.{k}")
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            check_json_schema(schema["items"], item, f"{path}[{i}]")


class SchemaRegistry:
    def __init__(self) -> None:
        self._schemas: Dict[str, dict] = {}
        # external decoder seam: name -> fn(payload: bytes) -> decoded
        self._external: Dict[str, Callable[[bytes], Any]] = {}
        # compiled avro/protobuf codecs
        self._codecs: Dict[str, Any] = {}

    def put(self, name: str, spec: dict) -> None:
        stype = spec.get("type")
        if stype == "json_schema":
            if not isinstance(spec.get("schema"), dict):
                raise SchemaError("json_schema needs a 'schema' object")
        elif stype == "avro":
            from .avro import AvroError, AvroSchema

            try:
                self._codecs[name] = AvroSchema(spec["schema"])
            except (AvroError, KeyError) as e:
                raise SchemaError(f"bad avro schema: {e}") from e
        elif stype == "protobuf":
            from .protobuf import ProtoCodec, ProtoFile, ProtobufError

            try:
                self._codecs[name] = ProtoCodec(
                    ProtoFile(spec["source"]), spec["message_type"]
                )
            except (ProtobufError, KeyError) as e:
                # a schema the codec can't honor is rejected at
                # registration, never mid-traffic
                raise SchemaError(f"bad protobuf schema: {e}") from e
        elif stype != "external":
            raise SchemaError(f"unsupported schema type {stype!r}")
        self._schemas[name] = spec

    def put_external(self, name: str, decoder: Callable[[bytes], Any]) -> None:
        self._schemas[name] = {"type": "external"}
        self._external[name] = decoder

    def delete(self, name: str) -> bool:
        self._external.pop(name, None)
        self._codecs.pop(name, None)
        return self._schemas.pop(name, None) is not None

    def get(self, name: str) -> Optional[dict]:
        return self._schemas.get(name)

    def list(self) -> List[str]:
        return sorted(self._schemas)

    def check_payload(self, name: str, payload: bytes) -> Any:
        """Decode + validate; raises SchemaError; returns decoded value."""
        spec = self._schemas.get(name)
        if spec is None:
            raise SchemaError(f"schema {name!r} not found")
        if spec["type"] == "external":
            try:
                return self._external[name](payload)
            except SchemaError:
                raise
            except Exception as e:
                raise SchemaError(f"external decode failed: {e}") from e
        if spec["type"] in ("avro", "protobuf"):
            try:
                return self._codecs[name].decode(payload)
            except Exception as e:
                raise SchemaError(f"{spec['type']} decode failed: {e}") from e
        try:
            value = json.loads(payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise SchemaError(f"payload is not JSON: {e}") from e
        check_json_schema(spec["schema"], value)
        return value

    def encode_payload(self, name: str, value: Any) -> bytes:
        """Encode a decoded value back to wire bytes (rule function
        schema_encode; json_schema validates then dumps)."""
        spec = self._schemas.get(name)
        if spec is None:
            raise SchemaError(f"schema {name!r} not found")
        if spec["type"] in ("avro", "protobuf"):
            try:
                return self._codecs[name].encode(value)
            except Exception as e:
                raise SchemaError(f"{spec['type']} encode failed: {e}") from e
        if spec["type"] == "json_schema":
            check_json_schema(spec["schema"], value)
            return json.dumps(value).encode()
        raise SchemaError(f"schema {name!r} cannot encode")


_default: Optional[SchemaRegistry] = None


def default_registry() -> SchemaRegistry:
    """Process-default instance (the reference's registry is a global
    gen_server); boot shares it between validation, transformation,
    and the rule functions."""
    global _default
    if _default is None:
        _default = SchemaRegistry()
    return _default


def set_default_registry(reg: SchemaRegistry) -> None:
    global _default
    _default = reg
