"""Payload governance: schema registry, schema validation, message
transformation — the emqx_schema_registry / emqx_schema_validation /
emqx_message_transformation trio.

All three hang off the 'message.publish' hook fold exactly where the
reference registers them (emqx_schema_validation.erl
on_message_publish; transformation runs after validation), with
topic-indexed matching so per-publish cost is one trie walk, not a
scan of every rule.
"""

from .registry import SchemaRegistry, SchemaError
from .transformation import MessageTransformation
from .validation import SchemaValidation

__all__ = [
    "SchemaRegistry",
    "SchemaError",
    "SchemaValidation",
    "MessageTransformation",
]
