"""Schema validation: per-topic payload gates on the publish path.

Parity with apps/emqx_schema_validation: validations carry a topic
filter list, a check list (schema refs or sql-like predicates), a
strategy (all_pass | any_pass), and a failure action (drop |
disconnect); matched via a topic index, evaluated in order, firing the
'schema.validation_failed' hookpoint and metrics on failure.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

from ..broker.hooks import STOP
from ..broker.message import Message
from ..ops import topic as topic_mod
from ..ops.host_index import TopicTrie
from .registry import (
    SchemaError, SchemaRegistry, check_json_schema, default_registry,
)


class Validation:
    def __init__(self, conf: dict, registry: SchemaRegistry):
        self.name = conf["name"]
        self.topics = list(conf["topics"])
        self.strategy = conf.get("strategy", "all_pass")
        assert self.strategy in ("all_pass", "any_pass")
        self.failure_action = conf.get("failure_action", "drop")
        assert self.failure_action in ("drop", "disconnect", "ignore")
        self.registry = registry
        self.checks: List[dict] = list(conf["checks"])
        self.enabled = conf.get("enabled", True)
        self.matched = 0
        self.succeeded = 0
        self.failed = 0

    def _one(self, check: dict, msg: Message) -> bool:
        ctype = check.get("type", "schema")
        if ctype == "schema":
            try:
                self.registry.check_payload(check["schema"], msg.payload)
                return True
            except SchemaError:
                return False
        if ctype == "json_schema":  # inline schema
            try:
                value = json.loads(msg.payload)
                check_json_schema(check["schema"], value)
                return True
            except (ValueError, SchemaError):
                return False
        if ctype == "predicate":  # callable seam (sql checks analog)
            try:
                return bool(check["fn"](msg))
            except Exception:
                return False
        return False

    def run(self, msg: Message) -> bool:
        self.matched += 1
        results = (self._one(c, msg) for c in self.checks)
        ok = all(results) if self.strategy == "all_pass" else any(results)
        if ok:
            self.succeeded += 1
        else:
            self.failed += 1
        return ok


class SchemaValidation:
    def __init__(self, broker, registry: Optional[SchemaRegistry] = None):
        self.broker = broker
        self.registry = registry or default_registry()
        self._validations: Dict[str, Validation] = {}
        self._order: List[str] = []
        self._index = TopicTrie()
        self._enabled = False

    # --- config ----------------------------------------------------------

    def put(self, conf: dict) -> Validation:
        v = Validation(conf, self.registry)
        # validate EVERYTHING before touching live state — a bad
        # filter must not leave a half-registered validation active
        for flt in v.topics:
            topic_mod.validate_filter(flt)
        old = self._validations.get(v.name)
        if old is not None:
            self._drop_index(old)
        else:
            self._order.append(v.name)
        self._validations[v.name] = v
        for flt in v.topics:
            self._index.insert(topic_mod.words(flt), v.name)
        return v

    def delete(self, name: str) -> bool:
        v = self._validations.pop(name, None)
        if v is None:
            return False
        self._order.remove(name)
        self._drop_index(v)
        return True

    def _drop_index(self, v: Validation) -> None:
        for flt in v.topics:
            try:
                self._index.remove(topic_mod.words(flt), v.name)
            except KeyError:
                pass

    def list(self) -> List[dict]:
        return [
            {
                "name": n,
                "topics": self._validations[n].topics,
                "strategy": self._validations[n].strategy,
                "failure_action": self._validations[n].failure_action,
                "matched": self._validations[n].matched,
                "failed": self._validations[n].failed,
            }
            for n in self._order
        ]

    # --- hook -------------------------------------------------------------

    def enable(self) -> None:
        if not self._enabled:
            # after rewrite (910) / delayed (900), before transformation
            self.broker.hooks.add("message.publish", self._on_publish, priority=860)
            self._enabled = True

    def disable(self) -> None:
        if self._enabled:
            self.broker.hooks.delete("message.publish", self._on_publish)
            self._enabled = False

    def _on_publish(self, msg: Message):
        names = set(self._index.match(topic_mod.words(msg.topic)))
        if not names:
            return None
        for name in self._order:
            if name not in names:
                continue
            v = self._validations[name]
            if not v.enabled or v.run(msg):
                continue
            self.broker.metrics.inc("schema_validation.failed")
            self.broker.hooks.run("schema.validation_failed", msg, name)
            if v.failure_action == "ignore":
                continue
            out = Message(**{**msg.__dict__})
            out.headers = dict(msg.headers, allow_publish=False)
            if v.failure_action == "disconnect":
                out.headers["disconnect"] = True
            return (STOP, out)
        return None
