"""License parsing + connection-quota enforcement.

The reference ships this as a whole app (apps/emqx_license/src/
emqx_license.erl, emqx_license_parser_v20220101.erl,
emqx_license_checker.erl, emqx_license_resources.erl): a signed
license key carries a max-connections entitlement; a checker caches
the effective limits and a 'client.connect' hook rejects CONNECTs
with RC QUOTA_EXCEEDED once the (cached) connection count passes the
limit with a 10% grace factor; watermark alarms warn the operator
before the wall.

Key format (mirrors emqx_license_parser_v20220101.erl:34-60's
`base64(payload).base64(signature)` shape, re-keyed for this
framework): payload is newline-joined fields

    FORMAT_VERSION       ("220111")
    license type         (0 official | 1 trial | 2 community)
    customer type        (0..11; 10 = community)
    customer name
    customer email
    deployment name
    start date           (YYYYMMDD)
    days valid           ("0" = perpetual)
    max connections

signed with Ed25519. The verification public key defaults to the
built-in community key and is overridable via `license.public_key`
(deployments issuing their own entitlements). The special key value
"default" is the unlimited community license — the OSS build's
behavior, but through the same enforcement seam so a quota applies
the moment a real key is configured.
"""

from __future__ import annotations

import base64
import binascii
import datetime as _dt
import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

log = logging.getLogger("emqx_tpu.license")

FORMAT_VERSION = "220111"
TYPE_OFFICIAL, TYPE_TRIAL, TYPE_COMMUNITY = 0, 1, 2
UNLIMITED = float("inf")
EXPIRED = "expired"

CHECK_INTERVAL = 5.0  # cached connection-count refresh (checker:13)
GRACE_FACTOR = 1.1  # emqx_license.erl:176 — reject past max * 1.1

# Built-in community verification key. The matching PRIVATE key is
# intentionally not distributed; self-issued deployments configure
# license.public_key with their own.
COMMUNITY_PUBLIC_KEY_PEM = """-----BEGIN PUBLIC KEY-----
MCowBQYDK2VwAyEAYROpEmQ1Ys0TJYLfOMfS2PoOjJITK5A9BFkx9OiTSxE=
-----END PUBLIC KEY-----
"""

DEFAULT_KEY = "default"


class LicenseError(ValueError):
    pass


@dataclass
class License:
    license_type: int = TYPE_COMMUNITY
    customer_type: int = 10
    customer: str = "community"
    email: str = ""
    deployment: str = "default"
    start_date: str = "20200101"  # YYYYMMDD
    days: int = 0  # 0 = perpetual
    max_connections: float = UNLIMITED

    @property
    def type_name(self) -> str:
        return {TYPE_OFFICIAL: "official", TYPE_TRIAL: "trial"}.get(
            self.license_type, "community"
        )

    def expiry_epoch(self) -> float:
        if self.days <= 0:
            return UNLIMITED
        d = _dt.datetime.strptime(self.start_date, "%Y%m%d").replace(
            tzinfo=_dt.timezone.utc
        )
        return (d + _dt.timedelta(days=self.days)).timestamp()

    def expired(self, now: Optional[float] = None) -> bool:
        return (now or time.time()) > self.expiry_epoch()

    def summary(self) -> Dict:
        exp = self.expiry_epoch()
        return {
            "customer": self.customer,
            "customer_type": self.customer_type,
            "deployment": self.deployment,
            "email": self.email,
            "type": self.type_name,
            "start_at": f"{self.start_date[:4]}-{self.start_date[4:6]}-"
                        f"{self.start_date[6:]}",
            "expiry_at": (
                "never" if exp == UNLIMITED
                else _dt.datetime.fromtimestamp(
                    exp, _dt.timezone.utc
                ).strftime("%Y-%m-%d")
            ),
            "expiry": self.expired(),
            "max_connections": (
                "unlimited" if self.max_connections == UNLIMITED
                else int(self.max_connections)
            ),
        }


def sign_license(lic: License, private_key) -> str:
    """Issue a key for `lic` (test/ops tooling; Ed25519 private key)."""
    payload = "\n".join(
        [
            FORMAT_VERSION,
            str(lic.license_type),
            str(lic.customer_type),
            lic.customer,
            lic.email,
            lic.deployment,
            lic.start_date,
            str(lic.days),
            str(
                0
                if lic.max_connections == UNLIMITED
                else int(lic.max_connections)
            ),
        ]
    ).encode()
    sig = private_key.sign(payload)
    return (
        base64.b64encode(payload).decode()
        + "."
        + base64.b64encode(sig).decode()
    )


def parse_license(key: str, public_key_pem: Optional[str] = None) -> License:
    """Parse + verify a key. "default" yields the community license."""
    key = (key or DEFAULT_KEY).strip()
    if key == DEFAULT_KEY:
        return License()
    if "." not in key:
        raise LicenseError("malformed license key (expected payload.sig)")
    p64, s64 = key.split(".", 1)
    try:
        payload = base64.b64decode(p64, validate=True)
        sig = base64.b64decode(s64, validate=True)
    except (binascii.Error, ValueError) as e:
        raise LicenseError(f"malformed license key: {e}") from None
    from cryptography.hazmat.primitives.serialization import (
        load_pem_public_key,
    )

    pub = load_pem_public_key(
        (public_key_pem or COMMUNITY_PUBLIC_KEY_PEM).encode()
    )
    try:
        pub.verify(sig, payload)
    except Exception:
        raise LicenseError("invalid license signature") from None
    fields = payload.decode("utf-8", "replace").split("\n")
    if len(fields) != 9:
        raise LicenseError(f"license payload has {len(fields)} fields, not 9")
    if fields[0] != FORMAT_VERSION:
        raise LicenseError(f"unsupported license format {fields[0]!r}")
    try:
        maxc = int(fields[8])
        lic = License(
            license_type=int(fields[1]),
            customer_type=int(fields[2]),
            customer=fields[3],
            email=fields[4],
            deployment=fields[5],
            start_date=fields[6],
            days=int(fields[7]),
            max_connections=UNLIMITED if maxc == 0 else float(maxc),
        )
        lic.expiry_epoch()  # validates start_date format
    except (ValueError, TypeError) as e:
        raise LicenseError(f"bad license field: {e}") from None
    return lic


def _parse_watermark(v, default: float) -> float:
    if v is None:
        return default
    if isinstance(v, str) and v.endswith("%"):
        return float(v[:-1]) / 100.0
    return float(v)


class LicenseChecker:
    """Cached-limit connect gate + watermark alarm (emqx_license_checker
    + emqx_license_resources in one object; no gen_server needed — the
    broker is single-loop and the count fetch is cached)."""

    ALARM = "license_quota"

    def __init__(
        self,
        key: str = DEFAULT_KEY,
        count_fn: Optional[Callable[[], int]] = None,
        alarms=None,
        public_key_pem: Optional[str] = None,
        low_watermark=0.75,
        high_watermark=0.80,
        persist_fn: Optional[Callable[[str], None]] = None,
    ):
        self.public_key_pem = public_key_pem
        self.persist_fn = persist_fn
        self.license = parse_license(key, public_key_pem)
        self.key = key or DEFAULT_KEY
        self.count_fn = count_fn or (lambda: 0)
        self.alarms = alarms
        self.low_watermark = _parse_watermark(low_watermark, 0.75)
        self.high_watermark = _parse_watermark(high_watermark, 0.80)
        self._cached_count = 0
        self._counted_at = 0.0
        self._alarm_active = False

    # --- emqx_license:update_key -------------------------------------
    def update_key(self, key: str) -> License:
        lic = parse_license(key, self.public_key_pem)  # throws on bad
        self.license = lic
        self.key = key
        if self.persist_fn is not None:
            # write through to config (emqx_conf:update override — the
            # key must survive a restart, emqx_license.erl:60-76)
            self.persist_fn(key)
        log.info(
            "license updated: %s, max_connections=%s",
            lic.customer, lic.max_connections,
        )
        self._watermark_alarm()
        return lic

    def update_setting(self, setting: Dict) -> None:
        if "connection_low_watermark" in setting:
            self.low_watermark = _parse_watermark(
                setting["connection_low_watermark"], self.low_watermark
            )
        if "connection_high_watermark" in setting:
            self.high_watermark = _parse_watermark(
                setting["connection_high_watermark"], self.high_watermark
            )

    # --- emqx_license_checker:limits ----------------------------------
    def limits(self) -> Dict:
        if self.license.expired():
            return {"max_connections": EXPIRED}
        return {"max_connections": self.license.max_connections}

    def connection_count(self) -> int:
        now = time.time()
        if now - self._counted_at >= CHECK_INTERVAL:
            self._cached_count = int(self.count_fn())
            self._counted_at = now
        return self._cached_count

    # --- emqx_license:check (the 'client.connect' hook) ---------------
    def check_connect(self) -> Optional[str]:
        """None = admit; else a rejection reason string."""
        lim = self.limits()["max_connections"]
        if lim == EXPIRED:
            log.error("connection rejected: license expired")
            return "license_expired"
        if lim == UNLIMITED:
            return None
        count = self.connection_count()
        self._watermark_alarm(count, lim)
        if count > lim * GRACE_FACTOR:
            log.error(
                "connection rejected: license limit reached (%d > %d)",
                count, int(lim),
            )
            return "license_quota"
        return None

    def _watermark_alarm(self, count=None, lim=None) -> None:
        if self.alarms is None:
            return
        if lim is None:
            lim = self.limits()["max_connections"]
        if lim in (EXPIRED, UNLIMITED):
            # upgrading to unlimited (or expiring) must not strand an
            # active quota alarm
            if self._alarm_active:
                try:
                    self.alarms.deactivate(self.ALARM)
                except Exception:
                    pass
                self._alarm_active = False
            return
        if count is None:
            count = self.connection_count()
        frac = count / lim if lim else 1.0
        if frac >= self.high_watermark and not self._alarm_active:
            try:
                self.alarms.activate(
                    self.ALARM,
                    details={"count": count, "max": int(lim)},
                    message=(
                        f"License: {count} connections >= "
                        f"{self.high_watermark:.0%} of limit {int(lim)}"
                    ),
                )
                self._alarm_active = True
            except Exception:
                pass
        elif frac < self.low_watermark and self._alarm_active:
            try:
                self.alarms.deactivate(self.ALARM)
            except Exception:
                pass
            self._alarm_active = False

    # --- wiring --------------------------------------------------------
    def attach(self, broker) -> None:
        """Register the connect gate at the 'client.connect' hookpoint
        (highest priority — quota rejects before auth providers run,
        emqx_license_app's hook posture)."""

        def _gate(conninfo, acc):
            reason = self.check_connect()
            if reason is None:
                return None  # continue the fold
            from .broker.hooks import STOP

            from .broker.packet import RC

            return (STOP, RC.QUOTA_EXCEEDED)

        # priority above exhook's 500: quota sheds before any
        # out-of-process OnClientConnect round trip runs
        broker.hooks.add("client.connect", _gate, priority=1000)

    def info(self) -> Dict:
        lim = self.limits()["max_connections"]
        return {
            **self.license.summary(),
            "connection_low_watermark": f"{self.low_watermark:.0%}",
            "connection_high_watermark": f"{self.high_watermark:.0%}",
            "live_connections": self.connection_count(),
            "effective_max_connections": (
                "expired" if lim == EXPIRED
                else "unlimited" if lim == UNLIMITED
                else int(lim)
            ),
        }
