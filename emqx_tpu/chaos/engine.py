"""Chaos engine core: the session fleet, the Zipf storm generator,
and the scenario driver that runs the catalog while the sentinel
judges the outcome.

Scale notes (why the fleet looks like this):
  * sessions are REAL `broker.Session` objects opened through
    `Broker.open_session` — the same registry, route writes, fanout
    plans, and delivery loops production traffic exercises — but they
    share ONE SessionConfig and one no-op sink, so a million of them
    fit in a few GB and build at ~50k/s;
  * queued-while-disconnected QoS0 is disabled in the shared config
    (`mqueue_store_qos0=False`): a disconnect wave under a live storm
    must not turn into a million growing mqueues;
  * publishes ride `DispatchEngine.submit_many` — one future per storm
    chunk instead of one per publish — so a single driver task can
    saturate the pipelined device path;
  * topic skew is Zipf over subscription groups (the head of the
    distribution stays hot enough to live in the match cache, the tail
    keeps the kernel honest), which is the shape real MQTT fleets
    exhibit.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..broker.message import Message
from ..broker.packet import SubOpts
from ..broker.session import SessionConfig
from ..obs.profiler import STAGE_MARK

log = logging.getLogger("emqx_tpu.chaos")


class ContractViolation(AssertionError):
    """A scenario's expected-response contract did not hold."""


def _noop_sink(pkts) -> None:
    return None


class SessionFleet:
    """N lightweight-but-real sessions on one broker. Session i
    subscribes the wildcard filter `<prefix>/<i % groups>/+`, so the
    fleet materializes `groups` distinct device rows with a bounded
    per-filter fan (sessions/groups) — a million sessions is a million
    Session objects and ~groups cuckoo slots, not a million copies of
    one filter."""

    def __init__(
        self,
        broker,
        prefix: str = "s",
        sessions: int = 10_000,
        groups: Optional[int] = None,
        session_expiry_s: float = 3600.0,
    ) -> None:
        self.broker = broker
        self.prefix = prefix
        self.n = int(sessions)
        self.groups = int(groups) if groups else max(1, self.n // 5)
        # ONE config + ONE sink shared fleet-wide (see module notes)
        self.cfg = SessionConfig(
            session_expiry_interval=session_expiry_s,
            max_mqueue_len=16,
            mqueue_store_qos0=False,
            # the storm fleet stays in the live router even when the
            # durable tier is attached: a million DS sessions is a
            # different experiment than a million live ones
            durable=False,
        )
        self.sink = _noop_sink
        self.clients: List[str] = []

    def filter_of(self, group: int) -> str:
        return f"{self.prefix}/{group}/+"

    def topic_of(self, group: int, suffix) -> str:
        return f"{self.prefix}/{group}/{suffix}"

    async def build(
        self,
        batch: int = 4096,
        progress: Optional[Callable[[str], None]] = None,
    ) -> None:
        b = self.broker
        opts = SubOpts(qos=0)
        append = self.clients.append
        for i in range(self.n):
            cid = f"{self.prefix}c{i}"
            s, _present = b.open_session(cid, clean_start=True, cfg=self.cfg)
            s.outgoing_sink = self.sink
            b.subscribe(s, self.filter_of(i % self.groups), opts)
            append(cid)
            if (i + 1) % batch == 0:
                # yield: the cluster syncer, heartbeats, and the storm
                # (when already running) get their loop turns
                await asyncio.sleep(0)
                if progress is not None and (i + 1) % (batch * 32) == 0:
                    progress(f"fleet {self.prefix}: {i + 1}/{self.n}")

    def fan(self) -> int:
        """Subscribers per group filter (the delivery fan of one
        storm topic)."""
        return max(1, self.n // self.groups)


class ZipfTopics:
    """Zipf-skewed topic generator over a fleet's groups. Rank→group is
    a fixed permutation so the hot head isn't the first groups by id;
    draws are O(chunk · log groups) via searchsorted over the cached
    CDF. A `victim_share` slice of traffic targets the victim fleet's
    groups so the cluster forward leg stays continuously exercised."""

    def __init__(
        self,
        fleet: SessionFleet,
        s: float = 1.2,
        seed: int = 7,
        hot_suffixes: int = 16,
        victim: Optional[SessionFleet] = None,
        victim_share: float = 0.05,
    ) -> None:
        self.fleet = fleet
        self.victim = victim
        self.victim_share = victim_share if victim is not None else 0.0
        self.rng = np.random.default_rng(seed)
        self.hot_suffixes = hot_suffixes
        w = 1.0 / np.arange(1, fleet.groups + 1, dtype=np.float64) ** s
        self._cdf = np.cumsum(w / w.sum())
        self._perm = self.rng.permutation(fleet.groups)
        if victim is not None:
            wv = 1.0 / np.arange(1, victim.groups + 1, dtype=np.float64) ** s
            self._vcdf = np.cumsum(wv / wv.sum())
            self._vperm = self.rng.permutation(victim.groups)

    def draw(self, n: int) -> List[str]:
        rng = self.rng
        nv = int(n * self.victim_share)
        nm = n - nv
        groups = self._perm[
            np.searchsorted(self._cdf, rng.random(nm), side="right").clip(
                0, len(self._perm) - 1
            )
        ]
        sufs = rng.integers(0, self.hot_suffixes, size=n)
        pref = self.fleet.prefix
        out = [
            f"{pref}/{g}/{s_}" for g, s_ in zip(groups.tolist(), sufs.tolist())
        ]
        if nv:
            vg = self._vperm[
                np.searchsorted(
                    self._vcdf, rng.random(nv), side="right"
                ).clip(0, len(self._vperm) - 1)
            ]
            vp = self.victim.prefix
            out.extend(
                f"{vp}/{g}/{s_}"
                for g, s_ in zip(vg.tolist(), sufs[nm:].tolist())
            )
        return out


class ChaosEngine:
    """Drives the soak: owns the fleets, the background storm task, the
    fault-injection bookkeeping, and the scenario contract plumbing.
    One engine per soak run; scenarios receive it as their context."""

    CHAOS_PREFIX = "chaos"

    def __init__(
        self,
        broker,
        obs,
        *,
        node=None,
        victim=None,
        victim_obs=None,
        sessions: int = 10_000,
        victim_sessions: int = 0,
        groups: Optional[int] = None,
        zipf_s: float = 1.2,
        seed: int = 7,
        storm_chunk: int = 256,
        sample_n: int = 64,
        chaos_filters: int = 4,
        chaos_fan: int = 5,
        detect_rounds: int = 12,
        detect_burst: int = 256,
        settle_timeout: float = 10.0,
        breaker_threshold: int = 3,
        probe_backoff_ms: float = 50.0,
        durable_sessions: int = 8,
        data_dir: Optional[str] = None,
        progress: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.broker = broker
        self.obs = obs
        self.node = node
        self.victim = victim
        self.victim_obs = victim_obs
        self.sessions = sessions
        self.victim_sessions = victim_sessions
        self.zipf_s = zipf_s
        self.seed = seed
        self.storm_chunk = storm_chunk
        self.sample_n = sample_n
        self.n_chaos_filters = chaos_filters
        self.chaos_fan = chaos_fan
        self.detect_rounds = detect_rounds
        self.detect_burst = detect_burst
        self.settle_timeout = settle_timeout
        self.breaker_threshold = breaker_threshold
        self.probe_backoff_ms = probe_backoff_ms
        # device-link fault seam (chaos/faults.py), installed at setup
        self.injector = None
        # durable tier (emqx_tpu/ds): a small QoS1 fleet persisted
        # through the WAL-backed store, plus the disk fault seam the
        # crash-consistency scenarios drive. Opened at setup when a
        # data_dir exists; survives ds_kill()/ds_reboot() cycles.
        self.durable_sessions = durable_sessions
        self.data_dir = data_dir
        self.durable_db = None
        self.durable_mgr = None
        self.disk_injector = None
        self.ds_recovery: Dict[str, Any] = {}
        self.ds_shard_failures: List[tuple] = []  # (ts, shard, errname)
        self.dur_published = 0
        self.dur_delivered = 0
        self._dur_reboots = 0
        self.progress = progress or (lambda msg: log.info("%s", msg))

        self.fleet = SessionFleet(broker, "s", sessions, groups=groups)
        self.victim_fleet: Optional[SessionFleet] = None
        if victim is not None and victim_sessions:
            self.victim_fleet = SessionFleet(
                victim.broker, "v", victim_sessions
            )
        self.topics: Optional[ZipfTopics] = None
        self.chaos_filters: List[str] = []
        self._chaos_seq = 0
        self._payload = b"soak"

        # soak accounting
        self.published = 0
        self.delivered = 0
        self.storm_errors = 0
        self._storm_elapsed = 0.0
        self._storm_task: Optional[asyncio.Task] = None
        self._storm_stop = True
        self.setup_seconds = 0.0
        self.faults_injected = 0
        self.faults_detected = 0
        self.fault_kinds: Dict[str, int] = {}
        self.detections: List[tuple] = []  # (monotonic ts, summary)
        self.scenario_results: List[Any] = []
        # wall-clock submit→delivered latency per storm CHUNK: the
        # end-to-end proxy the sentinel's stage spans don't cover
        # (spans sum attributed stage time; the wall clock also eats
        # loop scheduling + pipeline residency)
        from ..obs.kernel_telemetry import StreamingHistogram

        self.chunk_hist = StreamingHistogram()

    # --- wiring -----------------------------------------------------------

    @property
    def router(self):
        return self.broker.router

    @property
    def sentinel(self):
        return self.obs.sentinel

    @property
    def alarms(self):
        return self.obs.alarms

    @property
    def flight(self):
        return self.obs.flight

    def counters(self) -> Dict[str, int]:
        return dict(self.router.telemetry.counters)

    # --- setup ------------------------------------------------------------

    async def setup(self) -> None:
        from .faults import DeviceFaultInjector, DiskFaultInjector

        t0 = time.monotonic()
        if self.broker.engine is None:
            self.broker.enable_dispatch_engine()
        # breaker tuned to soak cadence: trip within a couple of storm
        # chunks, probe fast enough that recovery fits a scenario
        # window (production defaults are seconds-scale)
        de = self.broker.engine
        de.breaker_threshold = self.breaker_threshold
        de.probe_backoff_s = self.probe_backoff_ms / 1e3
        de.probe_backoff_max_s = max(
            de.probe_backoff_s * 8, de.probe_backoff_s
        )
        # the XLA-boundary fault seam the device scenarios drive;
        # healthy cost is one falsy test per device leg
        self.injector = DeviceFaultInjector().install(self.router)
        # the disk-IO fault seam (ds/diskio.py) the durable-tier
        # scenarios drive; healthy cost is one falsy module read per op
        self.disk_injector = DiskFaultInjector(seed=self.seed).install()
        if self.data_dir is not None and self.durable_sessions > 0:
            self._open_durable(first=True)
        st = self.sentinel
        st.sample_n = self.sample_n
        st.on_divergence.append(
            lambda summary: self.detections.append(
                (time.monotonic(), summary)
            )
        )
        self.progress(f"building fleet: {self.sessions} sessions")
        await self.fleet.build(progress=self.progress)
        if self.victim_fleet is not None:
            self.progress(
                f"building victim fleet: {self.victim_sessions} sessions"
            )
            await self.victim_fleet.build(progress=self.progress)
        # dedicated chaos-target filters: corruption scenarios corrupt
        # THESE device rows, so the main fleet's groups keep serving
        # clean while the fault is live (scoped blast radius)
        opts = SubOpts(qos=0)
        for k in range(self.n_chaos_filters):
            flt = f"{self.CHAOS_PREFIX}/{k}/+"
            for j in range(self.chaos_fan):
                s, _ = self.broker.open_session(
                    f"{self.CHAOS_PREFIX}-{k}-{j}",
                    clean_start=True,
                    cfg=self.fleet.cfg,
                )
                s.outgoing_sink = self.fleet.sink
                self.broker.subscribe(s, flt, opts)
            self.chaos_filters.append(flt)
        self.topics = ZipfTopics(
            self.fleet,
            s=self.zipf_s,
            seed=self.seed,
            victim=self.victim_fleet,
        )
        if self.node is not None:
            await self.node.flush()
        if self.victim is not None:
            await self.victim.flush()
        # warm the device path: compile the kernels, drain the first
        # sync, and serve one burst through every chaos filter so their
        # rows exist device-side before any corruption lands
        await self.burst(self.topics.draw(max(64, self.storm_chunk)))
        await self.burst([self.fresh_topic(f) for f in self.chaos_filters])
        self.setup_seconds = time.monotonic() - t0
        self.progress(
            f"setup done in {self.setup_seconds:.1f}s: "
            f"{len(self.broker.sessions)} sessions on main broker"
        )

    # --- storm ------------------------------------------------------------

    def storm_start(self) -> None:
        if self._storm_task is not None:
            return
        self._storm_stop = False
        self._storm_t0 = time.monotonic()
        # retained handle + supervised finish (see _storm_done): a
        # chaos-injected failure in the generator must surface
        self._storm_task = asyncio.get_running_loop().create_task(
            self._storm_loop()
        )
        self._storm_task.add_done_callback(self._storm_done)

    def _storm_done(self, task: asyncio.Task) -> None:
        if not task.cancelled() and task.exception() is not None:
            log.error("storm generator died", exc_info=task.exception())

    async def storm_stop(self) -> None:
        if self._storm_task is None:
            return
        self._storm_stop = True
        try:
            await self._storm_task
        finally:
            self._storm_task = None
            self._storm_elapsed += time.monotonic() - self._storm_t0

    async def _storm_loop(self) -> None:
        eng = self.broker.engine
        draw = self.topics.draw
        chunk = self.storm_chunk
        payload = self._payload
        # one chunk in flight while the next is drawn/encoded: the
        # await lands on the PREVIOUS chunk's future, so the pipeline
        # never idles between chunks
        pending = None
        while not self._storm_stop:
            # explicit yield: when a chunk flushes+collects inline its
            # future is already done, and awaiting a done future does
            # NOT suspend — without this the storm busy-spins and
            # starves timers, audits, and the scenarios themselves
            await asyncio.sleep(0)
            # storm_gen mark: topic draw + Message construction is the
            # generator's own cost, not the broker's — bucket it so the
            # profiler's `other` bin stops absorbing the storm itself
            STAGE_MARK.stage = "storm_gen"
            msgs = [Message(topic=t, payload=payload) for t in draw(chunk)]
            STAGE_MARK.stage = ""
            fut = eng.submit_many(msgs)
            n_sent = len(msgs)
            t_sub = time.monotonic()
            if pending is not None:
                try:
                    self.delivered += await pending[0]
                    self.published += pending[1]
                    self.chunk_hist.observe(
                        time.monotonic() - pending[2]
                    )
                except Exception:
                    self.storm_errors += 1
                    log.exception("storm chunk failed")
                    await asyncio.sleep(0.01)
            pending = (fut, n_sent, t_sub)
        if pending is not None:
            try:
                self.delivered += await pending[0]
                self.published += pending[1]
                self.chunk_hist.observe(time.monotonic() - pending[2])
            except Exception:
                self.storm_errors += 1

    def storm_elapsed(self) -> float:
        live = (
            time.monotonic() - self._storm_t0
            if self._storm_task is not None
            else 0.0
        )
        return self._storm_elapsed + live

    # --- scenario plumbing ------------------------------------------------

    def fresh_topic(self, flt: str) -> str:
        """A never-seen topic matching `flt` (…/+): cache-miss by
        construction, so the device kernel — not the match cache —
        serves it."""
        self._chaos_seq += 1
        return flt[:-1] + f"w{self._chaos_seq}"

    async def burst(self, topics: Sequence[str]) -> int:
        """Publish a targeted burst through the pipelined engine, then
        drain the sentinel's deferred audit turn. Returns deliveries."""
        n = await self.broker.engine.submit_many(
            [Message(topic=t, payload=self._payload) for t in topics]
        )
        await asyncio.sleep(0)
        self.sentinel.run_audits()
        self.published += len(topics)
        self.delivered += n
        return n

    async def route_churn(self, n: int = 64) -> int:
        """Live route churn: `n` add legs (fresh temp-session
        subscriptions on never-seen filters) followed by `n` delete
        legs (their unsubscribes), with a device sync + served burst in
        between — the subscribe/unsubscribe traffic a degraded mesh
        must keep absorbing. Returns routes churned."""
        b = self.broker
        self._chaos_seq += 1
        seq = self._chaos_seq
        s, _ = b.open_session(f"churn{seq}", True)
        s.outgoing_sink = _noop_sink
        flts = [f"churn/{seq}/{i}/+" for i in range(n)]
        for flt in flts:
            b.subscribe(s, flt, SubOpts(qos=0))
        self.router.device_table.sync()
        await self.burst([flts[0][:-1] + "x", flts[-1][:-1] + "x"])
        for flt in flts:
            b.unsubscribe(s, flt)
        b.close_session(s, discard=True)
        self.router.device_table.sync()
        return 2 * n  # add legs + delete legs

    def reset_flight_cooldown(self, rule: str) -> None:
        """Clear one trigger rule's cooldown latch. Scenario contracts
        demand a bundle PER scenario; the production cooldown would
        (correctly) coalesce two faults 30s apart into one bundle."""
        fl = self.flight
        if fl is not None:
            fl._last_fired.pop(rule, None)

    def record_fault(self, kind: str, detail: Dict[str, Any]) -> None:
        """Every injection is stamped into the flight ring AND freezes
        a bundle (chaos_fault rule): the forensic record of a chaos
        window carries the inject next to the detections it provoked."""
        self.faults_injected += 1
        self.fault_kinds[kind] = self.fault_kinds.get(kind, 0) + 1
        fl = self.flight
        if fl is not None:
            fl.recorder.record("chaos.inject", "", {"kind": kind, **detail})
            fl.maybe_trigger("chaos_fault", {"kind": kind, **detail})

    def scaled_timeout(self, base: float) -> float:
        """Box-scaled deadline: `base` tuned-wall seconds stretched by
        the measured box-throughput ratio (chaos/boxcal.py). The
        SOAK_r19 `takeover_imported` fix: a wall-clock-fixed 10s settle
        window red-flags a 1-core box that finishes the same work in
        11.4s — the budget must scale with the box, the way the
        replica_drift repair budget already scales with pair count."""
        from .boxcal import scaled

        return scaled(base)

    async def wait_for(
        self,
        pred: Callable[[], bool],
        timeout: float = 5.0,
        poll: float = 0.02,
    ) -> Optional[float]:
        """Poll `pred` until true; returns elapsed seconds or None on
        timeout. The background storm keeps running underneath."""
        t0 = time.monotonic()
        while True:
            if pred():
                return time.monotonic() - t0
            if time.monotonic() - t0 > timeout:
                return None
            await asyncio.sleep(poll)

    async def drive_until(
        self,
        pred: Callable[[], bool],
        flt: Optional[str] = None,
        timeout: float = 10.0,
    ) -> Optional[float]:
        """Like wait_for, but each poll round ALSO pushes a small fresh
        burst through the engine — recovery legs (table re-sync,
        auto-unquarantine) only advance when matches are served."""
        t0 = time.monotonic()
        while True:
            if pred():
                return time.monotonic() - t0
            if time.monotonic() - t0 > timeout:
                return None
            topics = (
                [self.fresh_topic(flt)]
                if flt is not None
                else self.topics.draw(16)
            )
            await self.burst(topics)
            await asyncio.sleep(0.01)

    async def settle(self, timeout: Optional[float] = None) -> None:
        """Drain cluster op queues and give spawned takeover/forward
        tasks their turns."""
        for node in (self.node, self.victim):
            if node is not None:
                try:
                    await node.flush()
                except Exception:
                    log.exception("settle flush failed")
        t0 = time.monotonic()
        limit = timeout if timeout is not None else 0.1
        while time.monotonic() - t0 < limit:
            await asyncio.sleep(0.02)
            if self.node is None or not self.node._tasks:
                break

    # --- verification -----------------------------------------------------

    async def audit_sweep(self, per_groups: int = 512) -> Dict[str, Any]:
        """Full-truth verification pass: serve a batch through the
        device path and compare EVERY answer against the host oracle.
        This is the 'zero silent divergence' leg — anything the
        sampled audit missed shows up here."""
        r = self.router
        rng = np.random.default_rng(self.seed + 1)
        n_groups = min(per_groups, self.fleet.groups)
        picks = rng.choice(self.fleet.groups, size=n_groups, replace=False)
        topics = [
            self.fleet.topic_of(int(g), f"sweep{self._chaos_seq}")
            for g in picks
        ]
        topics += [self.fresh_topic(f) for f in self.chaos_filters]
        served = r.match_filters_finish(r.match_filters_begin(topics))
        silent = []
        for t, s_ in zip(topics, served):
            if sorted(s_) != sorted(r.match_filters(t)):
                silent.append(t)
        return {
            "topics_swept": len(topics),
            "silent_divergences": len(silent),
            "diverging_topics": silent[:8],
        }

    async def drain_clean_streak(self) -> None:
        """Serve enough clean sampled publishes to clear the divergence
        alarm (CLEAN_STREAK_TO_CLEAR consecutive clean audits)."""
        from ..obs.sentinel import CLEAN_STREAK_TO_CLEAR

        need = (CLEAN_STREAK_TO_CLEAR + 4) * max(1, self.sentinel.sample_n)
        step = max(64, self.storm_chunk)
        for _ in range(0, need, step):
            await self.burst(self.topics.draw(step))
            if not self.alarms.is_active("xla_audit_divergence"):
                break

    # --- durable tier -----------------------------------------------------

    def _open_durable(self, first: bool) -> None:
        """Open (or re-open after ds_kill) the durable tier from
        `data_dir`: the WAL-backed message DB, the durable session
        manager with its persist gate, the fail-stop wiring, and the
        QoS1 mini-fleet on `dur/<k>/+`. On reboot (`first=False`) this
        IS the boot-side recovery path: shard WALs replay CRC-verified,
        sessions resume at their committed positions (at-least-once),
        and the ps-routes rebuild from their subscriptions."""
        from ..ds.api import Db
        from ..ds.session_ds import DurableSessionManager

        ds_dir = os.path.join(self.data_dir, "ds")
        t0 = time.monotonic()
        self.durable_db = Db(
            "chaos-messages", data_dir=ds_dir, n_shards=2,
            buffer_flush_ms=5,
        )
        self.durable_db.storage.on_shard_failed = self._on_shard_failed
        self.durable_mgr = DurableSessionManager(
            self.durable_db, state_dir=ds_dir
        )
        self.broker.enable_durable(self.durable_mgr)
        # recovery wall-time is bounded by replay cost: compact any
        # shard whose WAL bloated past the ratio while we were down
        compacted = self.durable_db.maybe_compact()
        cfg = SessionConfig(
            session_expiry_interval=3600.0, max_mqueue_len=512
        )
        for k in range(self.durable_sessions):
            s, _present = self.broker.open_session(
                f"dur-{k}", clean_start=first, cfg=cfg
            )
            self.broker.subscribe(s, f"dur/{k}/+", SubOpts(qos=1))
        self.ds_recovery = {
            "open_ms": round((time.monotonic() - t0) * 1e3, 2),
            "db": self.durable_db.recovery_report(),
            "sessions": self.durable_mgr.recovery_report(),
            "compacted_shards": compacted,
            "reboots": self._dur_reboots,
        }

    def _on_shard_failed(self, shard_id: int, exc: BaseException) -> None:
        """Fail-stop fan-out (called OUTSIDE the shard lock, possibly
        from the buffer flush thread): page + freeze forensics."""
        self.ds_shard_failures.append(
            (time.monotonic(), shard_id, type(exc).__name__)
        )
        self.alarms.ensure(
            f"ds_shard_failed_{shard_id}",
            details={"shard": shard_id, "error": str(exc)},
            message=f"durable shard {shard_id} fail-stopped: {exc}",
        )
        fl = self.flight
        if fl is not None:
            fl.maybe_trigger(
                "ds_shard_failed",
                {"shard": shard_id, "error": str(exc)},
            )

    async def durable_publish(self, n: int = 8) -> List[bytes]:
        """Publish `n` QoS1 messages into the durable tier through the
        broker publish path (the persist gate stores them), then flush
        the DS buffer so the batch reaches the WAL fsynced — i.e.
        acked-durable. Returns the unique payloads (the loss-accounting
        ledger). The flush raises ShardFailedError when the target
        shard fail-stops under an injected disk fault."""
        payloads: List[bytes] = []
        groups = max(1, self.durable_sessions)
        base = self.dur_published
        for i in range(n):
            self._chaos_seq += 1
            p = f"dur{self._chaos_seq}".encode()
            self.broker.publish(
                Message(
                    topic=f"dur/{(base + i) % groups}/m{self._chaos_seq}",
                    payload=p,
                    qos=1,
                )
            )
            payloads.append(p)
        self.dur_published += n
        self.durable_db.buffer.flush_now()
        await asyncio.sleep(0)
        return payloads

    async def durable_drain(self, rounds: int = 64) -> List[bytes]:
        """Pump every durable session and puback everything delivered,
        committing stream positions (the consumed ledger). Returns the
        delivered payloads."""
        got: List[bytes] = []
        mgr = self.durable_mgr
        for _ in range(rounds):
            new = 0
            for s in list(mgr.sessions.values()):
                if not s.client_id.startswith("dur-"):
                    continue
                s.connected = True
                for pkt in mgr.pump(s):
                    got.append(bytes(pkt.payload))
                    if pkt.packet_id:
                        s.on_puback(pkt.packet_id)
                    new += 1
            if new == 0:
                break
            await asyncio.sleep(0)
        self.dur_delivered += len(got)
        return got

    async def ds_recover(self) -> List[int]:
        """Probe-verified recovery of every fail-stopped shard: reopen
        + replay + write/fsync/read-back probe; a shard's alarm clears
        only when its probe passes."""
        ok: List[int] = []
        for sid in list(self.durable_db.failed_shards()):
            if self.durable_db.recover_shard(sid):
                ok.append(sid)
                self.alarms.ensure_deactivated(f"ds_shard_failed_{sid}")
        return ok

    def ds_kill(self) -> None:
        """Simulated SIGKILL of the durable tier: unflushed buffer
        dropped (it was never acked durable), no fsync boundary on the
        WALs, persist gate detached, session objects lost with the
        process. The data dir survives for ds_reboot()."""
        mgr, db = self.durable_mgr, self.durable_db
        if mgr is None:
            return
        self.broker.hooks.delete("message.publish", mgr._persist_gate)
        self.broker.durable = None
        mgr.kill()
        db.kill()
        for cid in [
            c for c in self.broker.sessions if c.startswith("dur-")
        ]:
            self.broker.sessions.pop(cid, None)
            self.broker.router.dest_store.note_session(cid, None)
        self.durable_mgr = None
        self.durable_db = None

    async def ds_reboot(self) -> float:
        """Boot-side crash recovery from the surviving data dir: WAL
        replay (CRC-verified, torn tail truncated), durable sessions
        resumed at committed positions, ps-routes rebuilt. Returns
        recovery wall-time ms."""
        from ..ds.metrics import DS_METRICS

        t0 = time.monotonic()
        self._dur_reboots += 1
        self._open_durable(first=False)
        ms = (time.monotonic() - t0) * 1e3
        self.ds_recovery["recovery_ms"] = round(ms, 2)
        DS_METRICS.gauge("recovery_last_ms", ms)
        await asyncio.sleep(0)
        return ms

    # --- the soak ---------------------------------------------------------

    async def run(
        self,
        scenarios: Optional[Sequence] = None,
        baseline_s: float = 10.0,
    ) -> Dict[str, Any]:
        """Run the catalog under a continuous storm; returns the soak
        row. Contract violations are collected per scenario and raised
        as ONE ContractViolation after the row is assembled — the row
        itself records exactly which check failed."""
        from .scenarios import scenario_catalog

        if not self.fleet.clients:
            await self.setup()
        cat = list(
            scenarios
            if scenarios is not None
            else scenario_catalog(cluster=self.victim is not None)
        )
        t_run0 = time.monotonic()
        self.storm_start()
        results = []
        try:
            if baseline_s > 0:
                await asyncio.sleep(baseline_s)
            for sc in cat:
                if sc.needs_cluster and self.victim is None:
                    continue
                if sc.needs_mesh and getattr(
                    self.router.device_table, "mesh", None
                ) is None:
                    continue
                if getattr(sc, "needs_durable", False) and (
                    self.durable_db is None
                ):
                    continue
                self.progress(f"scenario: {sc.name}")
                res = await sc.run(self)
                results.append(res)
                self.scenario_results.append(res)
        finally:
            await self.storm_stop()
        # end-state verification: recover the clean streak, then the
        # full-truth sweep
        await self.drain_clean_streak()
        sweep = await self.audit_sweep()
        if self.node is not None and self.victim is not None:
            # the storm is quiet but the LAST ping round's repair may
            # still be paging routes across; the ledger must snapshot
            # the converged state, not a resync in flight. Budget
            # mirrors the replica_drift repair bound: ping rounds +
            # settle + a full-contribution paged resync.
            ms = self.node.membership
            await self.wait_for(
                lambda: not self.node._resync
                and not self.victim._resync
                and self.node.replica_digests()
                == self.victim.replica_digests(),
                timeout=(
                    (ms.heartbeat_interval + ms.ping_timeout) * 6
                    + self.settle_timeout
                    + max(30.0, len(self.node._cluster_pairs) / 5_000.0)
                ),
            )
        row = self.soak_row(results, sweep, time.monotonic() - t_run0)
        bad = [
            f"{res.name}: {chk.name} ({chk.detail})"
            for res in results
            for chk in res.checks
            if not chk.ok
        ]
        if sweep["silent_divergences"]:
            bad.append(f"final sweep: {sweep['silent_divergences']} silent")
        row["contracts_ok"] = not bad
        row["violations"] = bad
        return row

    def soak_row(
        self, results, sweep: Dict[str, Any], run_seconds: float
    ) -> Dict[str, Any]:
        import platform

        import jax

        st = self.sentinel
        counters = self.counters()
        elapsed = max(self.storm_elapsed(), 1e-9)
        sessions_total = len(self.broker.sessions) + (
            len(self.victim.broker.sessions) if self.victim else 0
        )
        alarms_fired = self.alarms.fired_since(0.0)
        row = {
            "sessions": sessions_total,
            "connected": self.broker.connected_count(),
            "subscriptions": len(self.broker.suboptions),
            "groups": self.fleet.groups,
            "zipf_s": self.zipf_s,
            "setup_seconds": round(self.setup_seconds, 2),
            "run_seconds": round(run_seconds, 2),
            "storm": {
                "published": self.published,
                "delivered": self.delivered,
                "storm_seconds": round(elapsed, 2),
                "sustained_pub_per_sec": round(self.published / elapsed, 1),
                "delivered_per_sec": round(self.delivered / elapsed, 1),
                "errors": self.storm_errors,
                # wall-clock submit→delivered per storm chunk of
                # `storm_chunk` publishes: e2e including loop
                # scheduling + pipeline residency, so chaos-window
                # stalls (purges, rejoins) land here in full
                "chunk_size": self.storm_chunk,
                "e2e_chunk_p50_ms": round(
                    self.chunk_hist.percentile(50) * 1e3, 2
                ),
                "e2e_chunk_p99_ms": round(
                    self.chunk_hist.percentile(99) * 1e3, 2
                ),
            },
            "publish_p50_ms_incl_chaos": round(
                st.total_hist.percentile(50) * 1e3, 4
            ),
            "publish_p99_ms_incl_chaos": round(
                st.total_hist.percentile(99) * 1e3, 4
            ),
            "stage_p99_ms": {
                s_: round(h.percentile(99) * 1e3, 4)
                for s_, h in sorted(st.stage_hist.items())
            },
            "divergences_injected": self.faults_injected,
            "divergences_detected": self.faults_detected,
            # corruption faults are detected by the shadow audit (and
            # counted in audit.divergence_total); wire faults
            # (partition) by the membership layer
            "faults_by_kind": dict(sorted(self.fault_kinds.items())),
            "silent_divergences": sweep["silent_divergences"],
            "final_sweep": sweep,
            "audit": {
                "total": counters.get("audit_total", 0),
                "clean": counters.get("audit_clean_total", 0),
                "divergence_total": counters.get(
                    "audit_divergence_total", 0
                ),
                "skipped_stale": counters.get(
                    "audit_skipped_stale_total", 0
                ),
                "quarantined": counters.get("audit_quarantine_total", 0),
                "unquarantined": counters.get(
                    "audit_unquarantine_total", 0
                ),
            },
            "rpc": {
                "retries": counters.get("rpc_retry_total", 0),
                "unreachable": counters.get("rpc_unreachable_total", 0),
            },
            # device failure domain: the breaker's whole trip →
            # degrade → probe → resync → close ledger, plus admission
            "breaker": {
                "state_at_end": self.broker.engine.breaker_state,
                "trips": counters.get("breaker_trips_total", 0),
                "recoveries": counters.get("breaker_recoveries_total", 0),
                "device_failures": counters.get(
                    "breaker_device_failures_total", 0
                ),
                "fallback_publishes": counters.get(
                    "breaker_fallback_total", 0
                ),
                "degraded_batches": counters.get(
                    "breaker_degraded_batches_total", 0
                ),
                "probes": counters.get("breaker_probe_total", 0),
                "probe_failures": counters.get(
                    "breaker_probe_failures_total", 0
                ),
                "device_resyncs": counters.get("device_resyncs_total", 0),
                # shard failure domain (chip-granular breaker)
                "shard_trips": counters.get(
                    "breaker_shard_trips_total", 0
                ),
                "shard_evacuations": counters.get(
                    "breaker_shard_evacuations_total", 0
                ),
                "shard_recoveries": counters.get(
                    "breaker_shard_recoveries_total", 0
                ),
                "shard_overlays": counters.get("shard_overlay_total", 0),
                "queue_shed": counters.get("queue_shed_total", 0),
                "queue_blocked": counters.get("queue_blocked_total", 0),
                "queue_deadline_expired": counters.get(
                    "queue_deadline_expired_total", 0
                ),
            },
            "slo": {
                name: obj.evaluate() for name, obj in st.slo.items()
            },
            "alarms_fired": alarms_fired,
            "alarms_active_at_end": sorted(
                a["name"] for a in self.alarms.get_alarms("activated")
            ),
            "flight_bundles": (
                len(self.flight.store.list())
                if self.flight is not None
                else 0
            ),
            "quarantined_at_end": self.router.quarantined_filters(),
            "scenarios": {r.name: r.as_dict() for r in results},
            "knobs": {
                "sample_n": self.sample_n,
                "storm_chunk": self.storm_chunk,
                "chaos_filters": self.n_chaos_filters,
                "chaos_fan": self.chaos_fan,
                "victim_sessions": self.victim_sessions,
            },
            "provenance": {
                "python": platform.python_version(),
                "jax": jax.__version__,
                "platform": jax.devices()[0].platform,
                "devices": len(jax.devices()),
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            },
        }
        if self.node is not None:
            from ..cluster.metrics import CLUSTER_METRICS

            csnap = CLUSTER_METRICS.snapshot()

            def _node_summary(node) -> Dict[str, Any]:
                st = node.cluster_status()
                return {
                    "minority": st["minority"],
                    "needs_rejoin": st["needs_rejoin"],
                    "partition_trips": st["partition_trips"],
                    "partition_heals": st["partition_heals"],
                    "rejoins_completed": st["autoheal"][
                        "rejoins_completed"
                    ],
                    "antientropy": st["antientropy"],
                    "registry_conflicts": st["registry_conflicts"],
                    "digests": st["digests"],
                }

            row["cluster"] = {
                "nodes": 2,
                "heartbeat_interval": self.node.membership.heartbeat_interval,
                "victim_sessions_at_end": len(self.victim.broker.sessions),
                "cluster_routes_main": len(self.node._cluster_pairs),
                # the acceptance ledger: both nodes' route-table digests
                # must be byte-equal after the catalog's partitions heal
                "digests_equal_at_end": (
                    self.node.replica_digests()
                    == self.victim.replica_digests()
                ),
                "partitions": csnap.get("partition_total", 0),
                "heals": csnap.get("heal_total", 0),
                "autoheal_rejoins": csnap.get("autoheal_rejoin_total", 0),
                "antientropy_checks": csnap.get(
                    "antientropy_checks_total", 0
                ),
                "antientropy_divergences": csnap.get(
                    "antientropy_divergence_total", 0
                ),
                "antientropy_repairs": csnap.get(
                    "antientropy_repairs_total", 0
                ),
                "registry_conflicts": csnap.get(
                    "registry_conflicts_total", 0
                ),
                "asymmetry_detected": csnap.get("asymmetry_total", 0),
                "per_node": {
                    self.node.node_id: _node_summary(self.node),
                    self.victim.node_id: _node_summary(self.victim),
                },
            }
        if self.durable_db is not None:
            from ..ds.metrics import DS_METRICS

            dsnap = DS_METRICS.snapshot()
            row["ds"] = {
                # crash-consistency ledger: the kill→reboot→recover
                # walk plus the process-global WAL/shard counters
                "recovery": self.ds_recovery,
                "reboots": self._dur_reboots,
                "durable_published": self.dur_published,
                "durable_delivered": self.dur_delivered,
                "shard_failures": len(self.ds_shard_failures),
                "failed_at_end": self.durable_db.failed_shards(),
                "wal_replayed_records": dsnap.get(
                    "wal_replayed_records_total", 0
                ),
                "wal_torn_records": dsnap.get("wal_torn_records_total", 0),
                "wal_crc_failures": dsnap.get("wal_crc_failures_total", 0),
                "wal_upgraded_files": dsnap.get(
                    "wal_upgraded_files_total", 0
                ),
                "shard_fail_stops": dsnap.get("shard_failures_total", 0),
                "shard_recoveries": dsnap.get("shard_recoveries_total", 0),
                "recovery_last_ms": dsnap.get("recovery_last_ms", 0.0),
                "disk_faults_injected": (
                    dict(sorted(self.disk_injector.injected.items()))
                    if self.disk_injector is not None
                    else {}
                ),
            }
        return row

    # --- builders / teardown ----------------------------------------------

    @classmethod
    async def standalone(
        cls,
        *,
        sessions: int = 10_000,
        data_dir: Optional[str] = None,
        mesh=None,
        **kw,
    ) -> "ChaosEngine":
        import tempfile

        from ..broker.pubsub import Broker
        from ..obs import Observability

        base = data_dir or tempfile.mkdtemp(prefix="chaos_")
        broker = Broker(mesh=mesh)
        obs = Observability(
            broker,
            node_name="chaos@local",
            trace_dir=f"{base}/trace",
            flight_dir=f"{base}/flight",
        )
        return cls(broker, obs, sessions=sessions, data_dir=base, **kw)

    @classmethod
    async def cluster(
        cls,
        *,
        sessions: int = 10_000,
        victim_sessions: int = 2_000,
        heartbeat_interval: float = 1.0,
        ping_timeout: float = 3.0,
        data_dir: Optional[str] = None,
        **kw,
    ) -> "ChaosEngine":
        import tempfile

        from ..cluster.node import ClusterBroker, ClusterNode
        from ..obs import Observability

        base = data_dir or tempfile.mkdtemp(prefix="chaos_")
        mb, vb = ClusterBroker(), ClusterBroker()
        obs = Observability(
            mb,
            node_name="chaos-main",
            trace_dir=f"{base}/trace",
            flight_dir=f"{base}/flight",
        )
        vobs = Observability(
            vb, node_name="chaos-victim", flight=False,
            trace_dir=f"{base}/vtrace",
        )
        # ping timeout decoupled from the interval: storm windows stall
        # the shared loop for whole batches, and a stall must cost at
        # most one miss, not a spurious nodedown (see Membership)
        main = ClusterNode(
            "chaos-main", broker=mb,
            heartbeat_interval=heartbeat_interval,
            ping_timeout=ping_timeout,
        )
        victim = ClusterNode(
            "chaos-victim", broker=vb,
            heartbeat_interval=heartbeat_interval,
            ping_timeout=ping_timeout,
        )
        main.attach_obs(alarms=obs.alarms, flight=obs.flight)
        victim.attach_obs(alarms=vobs.alarms, flight=vobs.flight)
        addr = await main.start()
        await victim.start()
        await victim.join(addr)
        return cls(
            mb,
            obs,
            node=main,
            victim=victim,
            victim_obs=vobs,
            sessions=sessions,
            victim_sessions=victim_sessions,
            data_dir=base,
            **kw,
        )

    async def close(self) -> None:
        await self.storm_stop()
        eng = self.broker.engine
        if eng is not None and not eng.closed:
            await eng.stop()
        if self.disk_injector is not None:
            self.disk_injector.heal()
            self.disk_injector.uninstall()
        if self.durable_mgr is not None:
            try:
                self.durable_mgr.close()
            except Exception:
                log.exception("durable manager close failed")
            self.durable_mgr = None
        if self.durable_db is not None:
            try:
                self.durable_db.close()
            except Exception:
                log.exception("durable db close failed")
            self.durable_db = None
        for node in (self.victim, self.node):
            if node is not None:
                try:
                    await node.stop()
                except Exception:
                    log.exception("node stop failed")
        for o in (self.victim_obs, self.obs):
            if o is not None:
                o.stop()


async def run_soak(
    *,
    sessions: int = 1_000_000,
    victim_sessions: int = 20_000,
    groups: Optional[int] = None,
    zipf_s: float = 1.2,
    sample_n: int = 64,
    baseline_s: float = 20.0,
    scenarios: Optional[Sequence[str]] = None,
    report_path: Optional[str] = "SOAK_r13.json",
    data_dir: Optional[str] = None,
    progress: Optional[Callable[[str], None]] = None,
    strict: bool = True,
    **engine_kw,
) -> Dict[str, Any]:
    """Build the engine (clustered when victim_sessions > 0), run the
    scenario catalog under the storm, write the committed soak row, and
    assert the contracts. The one entry both `bench.py --soak` and
    `python -m emqx_tpu.chaos` call."""
    from .scenarios import scenario_catalog

    if victim_sessions > 0:
        eng = await ChaosEngine.cluster(
            sessions=sessions,
            victim_sessions=victim_sessions,
            groups=groups,
            zipf_s=zipf_s,
            sample_n=sample_n,
            data_dir=data_dir,
            progress=progress,
            **engine_kw,
        )
    else:
        eng = await ChaosEngine.standalone(
            sessions=sessions,
            groups=groups,
            zipf_s=zipf_s,
            sample_n=sample_n,
            data_dir=data_dir,
            progress=progress,
            **engine_kw,
        )
    try:
        await eng.setup()
        cat = None
        if scenarios is not None:
            by_name = {
                s.name: s
                for s in scenario_catalog(cluster=eng.victim is not None)
            }
            cat = [by_name[n] for n in scenarios]
        row = await eng.run(cat, baseline_s=baseline_s)
    finally:
        await eng.close()
    if report_path:
        with open(report_path, "w") as f:
            json.dump(row, f, indent=1, default=str)
        (progress or log.info)(f"soak row written: {report_path}")
    if strict and not row["contracts_ok"]:
        raise ContractViolation("; ".join(row["violations"]))
    return row
