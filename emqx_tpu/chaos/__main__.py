"""Standalone chaos soak driver.

    python -m emqx_tpu.chaos --sessions 1000000 --out SOAK_r13.json

Builds a two-node in-process cluster (set --victim-sessions 0 for a
single broker), sustains the Zipf publish storm, runs the scenario
catalog, asserts every contract, and writes the soak row. Exit code 1
when any contract is violated.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from .engine import ContractViolation, run_soak
from .scenarios import CATALOG


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m emqx_tpu.chaos",
        description="million-session soak + chaos scenarios, "
        "judged by the sentinel",
    )
    ap.add_argument("--sessions", type=int, default=1_000_000)
    ap.add_argument("--victim-sessions", type=int, default=20_000)
    ap.add_argument("--groups", type=int, default=None,
                    help="distinct subscription groups (default n/5)")
    ap.add_argument("--zipf", type=float, default=1.2, dest="zipf_s")
    ap.add_argument("--sample-n", type=int, default=64,
                    help="sentinel audit sampling (1/N publishes)")
    ap.add_argument("--baseline", type=float, default=20.0,
                    help="clean storm seconds before the first fault")
    ap.add_argument("--scenario", action="append", choices=CATALOG,
                    help="run only these scenarios (repeatable)")
    ap.add_argument("--out", default="SOAK_r13.json")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--lenient", action="store_true",
                    help="report contract violations without failing")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )

    def progress(msg: str) -> None:
        print(f"[chaos] {msg}", file=sys.stderr, flush=True)

    try:
        row = asyncio.run(
            run_soak(
                sessions=args.sessions,
                victim_sessions=args.victim_sessions,
                groups=args.groups,
                zipf_s=args.zipf_s,
                sample_n=args.sample_n,
                baseline_s=args.baseline,
                scenarios=args.scenario,
                report_path=args.out,
                data_dir=args.data_dir,
                progress=progress,
                strict=not args.lenient,
            )
        )
    except ContractViolation as e:
        print(f"[chaos] CONTRACT VIOLATION: {e}", file=sys.stderr)
        return 1
    ok = row["contracts_ok"]
    progress(
        f"{'PASS' if ok else 'FAIL'}: {row['sessions']} sessions, "
        f"{row['storm']['sustained_pub_per_sec']} pub/s sustained, "
        f"p99 {row['publish_p99_ms_incl_chaos']}ms, "
        f"faults {row['divergences_detected']}/"
        f"{row['divergences_injected']} detected, "
        f"{row['silent_divergences']} silent"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
