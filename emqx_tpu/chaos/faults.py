"""Device fault seam — the injectable error/latency layer at the XLA
boundary.

Everything the broker asks of the accelerator funnels through five
legs: `Router.match_filters_begin` (encode + kernel launch),
`match_filters_finish` (device->host fetch), `resolve_fanout_begin` /
`resolve_fanout_finish` (the dedup/max-QoS plan kernel), and the
device-table `sync` (delta scatter / full upload, on `DeviceTable` and
`ShardedDeviceTable` alike). Each leg carries a `fault_injector`
None-seam (one attribute read when absent — the broker.tracer
discipline), and this module is the thing that plugs into it: a
controllable fault source that can

  * raise a bounded burst of **transient** `XlaRuntimeError`-class
    failures (the flaky-link / preempted-kernel mode the dispatch
    engine's failover must absorb invisibly);
  * declare **sticky device loss** — every device leg fails until
    `heal()` — the mode that must trip the engine's circuit breaker
    into host-degraded service;
  * **stall** a bounded number of transfers for a fixed wall-clock
    delay WITHOUT failing them (the slow-HBM / congested-link mode):
    results stay correct, but the batch blows the engine's per-batch
    deadline, which counts toward the breaker exactly like a failure —
    slow is a fault even when it is not wrong;
  * arm a **seeded probabilistic schedule** (`fail_random`) — every
    matching check faults with probability p drawn from the injector's
    own `random.Random(seed)`, so a chaos run replays bit-identically
    from its seed.

Faults can be scoped to **shards** (`shards=...` on every programming
call): the sub-axis columns of a `ShardedDeviceTable` mesh. A
shard-scoped fault fires on the mesh-wide device legs only while at
least one target shard is still *in* the mesh (`lost_shards` on the
table — an evacuated chip is no longer touched by device dispatches),
and the raised error carries a `shard` attribute so the dispatch
engine's breaker can account the failure per shard instead of
forfeiting the whole mesh. The extra `shard_probe` leg is the
recovery path's direct probe of one (possibly evacuated) chip: it
keeps failing until `heal()` regardless of evacuation, which is what
makes the probe→rebalance chain honest.

The real production fault this seam stands in for surfaces as
`jaxlib.xla_extension.XlaRuntimeError`; the injected classes derive
from `DeviceLinkError` so handlers written against the seam catch both
shapes through one `except Exception` (counted — the static gate's
dispatch-path lint enforces that no device-leg handler swallows
silently)."""

from __future__ import annotations

import errno
import random
import time
from typing import Any, Dict, FrozenSet, Optional, Sequence, Tuple

from ..ds import diskio
from ..ds.diskio import (
    DiskFaultError,
    DiskFullError,
    DiskIOError,
    FsyncFailedError,
    SimulatedCrash,
)
from ..ds.metrics import DS_METRICS

# the legs check() is called with — one name per XLA-boundary seam
LEGS = (
    "match_begin",
    "match_finish",
    "fanout_begin",
    "fanout_finish",
    "sync",
)

# the per-shard recovery probe (dispatch engine shard breaker): not a
# broker dispatch leg, so it is NOT part of LEGS — an un-scoped fault
# still covers it (all-legs faults fail the probe until heal()), and
# it ignores lost_shards: probing the evacuated chip is its whole job
SHARD_PROBE_LEG = "shard_probe"


class DeviceLinkError(RuntimeError):
    """Base of the injected XlaRuntimeError-class failures. `shard` is
    the sub-axis column a shard-scoped fault was attributed to (None
    for whole-device faults) — the dispatch engine's breaker reads it
    to keep the failure domain chip-granular."""

    shard: Optional[int] = None


class TransientDeviceError(DeviceLinkError):
    """A one-off device fault: retry/fallback should absorb it."""


class DeviceLostError(DeviceLinkError):
    """Sticky device loss: every device leg fails until heal()."""


class DeviceDeadlineExceeded(DeviceLinkError):
    """A transfer abandoned past its deadline (wedged link)."""


# sentinel: the programmed fault does not apply to this check
_SKIP = object()


class DeviceFaultInjector:
    """One injector per Router; installed on the router AND its device
    table so route-churn syncs outside the publish path are injectable
    too. `check(leg)` is the hot-path entry: when healthy it is one
    falsy test, so leaving the injector installed for a whole soak
    costs nothing measurable. `seed` fixes the probabilistic schedule
    (`fail_random`) AND `pick_shard`, so a chaos run replays from its
    seed."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._random_p = 0.0
        self._legs: Optional[Tuple[str, ...]] = None
        self._shards: Optional[FrozenSet[int]] = None
        self.checks_total = 0
        self.faults_raised = 0
        self.stalls_injected = 0
        # per-(leg, shard) injected-fault ledger; mirrored on the
        # scrape as emqx_xla_fault_injected_total{leg,shard}
        self.injected: Dict[Tuple[str, str], int] = {}
        self.telemetry: Any = None
        self._router: Any = None

    # --- wiring -----------------------------------------------------------

    def install(self, router: Any) -> "DeviceFaultInjector":
        """Attach to every seam of one Router (idempotent)."""
        router.fault_injector = self
        router.device_table.fault_injector = self
        self.telemetry = router.telemetry
        self._router = router
        return self

    def uninstall(self) -> None:
        r = self._router
        if r is not None:
            if r.fault_injector is self:
                r.fault_injector = None
            if r.device_table.fault_injector is self:
                r.device_table.fault_injector = None
        self._router = None

    # --- fault programming ------------------------------------------------

    def fail_transient(
        self,
        n: int = 1,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """The next `n` device-leg checks (optionally scoped to `legs`
        and/or `shards`) raise TransientDeviceError, then the link is
        healthy again."""
        self._transient_left = int(n)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def fail_sticky(
        self,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Device loss: every check fails until heal(). With `shards`,
        only the targeted sub-axis columns are lost — the chip-loss
        mode the shard breaker must evacuate around."""
        self._sticky = True
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def stall(
        self,
        seconds: float,
        n: int = 1,
        fail: bool = False,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Stall the next `n` checks for `seconds` of wall clock. With
        `fail=False` (default) the leg then SUCCEEDS — the
        slow-but-correct mode that must blow the engine's per-batch
        deadline; `fail=True` additionally abandons the transfer
        (DeviceDeadlineExceeded), the wedged-link mode."""
        self._stall_left = int(n)
        self._stall_s = float(seconds)
        self._stall_fail = bool(fail)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def fail_random(
        self,
        p: float,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Arm the seeded probabilistic schedule: every matching check
        raises TransientDeviceError with probability `p`, drawn from
        the injector's `random.Random(seed)` — deterministic given the
        seed and the check sequence (reproducible background noise)."""
        self._random_p = float(p)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def heal(self) -> None:
        """Clear every programmed fault: the link is healthy."""
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._random_p = 0.0
        self._legs = None
        self._shards = None

    @property
    def healthy(self) -> bool:
        return not (
            self._sticky
            or self._transient_left > 0
            or self._stall_left > 0
            or self._random_p > 0.0
        )

    def pick_shard(self, n_shards: int) -> int:
        """Seeded victim-shard draw for scenario scripts."""
        return self.rng.randrange(int(n_shards))

    # --- the seam entry ---------------------------------------------------

    def _lost_shards(self) -> FrozenSet[int]:
        r = self._router
        if r is None:
            return frozenset()
        lost = getattr(r.device_table, "lost_shards", None)
        return frozenset(lost) if lost else frozenset()

    def _target_shard(self, leg: str, shard: Optional[int]) -> Any:
        """Resolve shard scoping for one check: `_SKIP` (fault does not
        apply here), None (untargeted whole-device fault), or the int
        shard the raised error is attributed to."""
        targets = self._shards
        if targets is None:
            return None
        if shard is not None:
            # shard-scoped call site (the recovery probe of ONE chip)
            return shard if shard in targets else _SKIP
        if leg == SHARD_PROBE_LEG:
            live = targets
        else:
            # mesh-wide device leg: an evacuated chip is out of the
            # mesh, so device dispatches no longer touch it
            live = targets - self._lost_shards()
        if not live:
            return _SKIP
        return min(live)

    def _record_injected(self, leg: str, shard: Optional[int]) -> str:
        label = "all" if shard is None else str(shard)
        key = (leg, label)
        self.injected[key] = self.injected.get(key, 0) + 1
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.count_labeled(
                "fault_injected_total", {"leg": leg, "shard": label}
            )
        return label

    def check(self, leg: str, shard: Optional[int] = None) -> None:
        """Called by every XLA-boundary leg. Healthy: one falsy test.
        Faulty: count, then stall and/or raise per the programmed
        mode. `shard` names the single chip a shard-scoped call site
        (the recovery probe) touches; mesh-wide legs pass None and the
        injector attributes the fault to one live target shard."""
        if self.healthy:
            return
        if self._legs is not None and leg not in self._legs:
            return
        tshard = self._target_shard(leg, shard)
        if tshard is _SKIP:
            return
        self.checks_total += 1
        tel = self.telemetry
        if self._stall_left > 0:
            self._stall_left -= 1
            self.stalls_injected += 1
            self._record_injected(leg, tshard)
            if tel is not None and tel.enabled:
                tel.count("chaos_device_stalls_total")
            time.sleep(self._stall_s)
            if not self._stall_fail:
                return
            self.faults_raised += 1
            if tel is not None and tel.enabled:
                tel.count("chaos_device_faults_total")
            err: DeviceLinkError = DeviceDeadlineExceeded(
                f"injected transfer stall abandoned at {leg} "
                f"({self._stall_s * 1e3:.0f}ms)"
            )
            err.shard = tshard
            raise err
        if self._random_p > 0.0 and not (
            self._sticky or self._transient_left > 0
        ):
            if self.rng.random() >= self._random_p:
                return
        self.faults_raised += 1
        self._record_injected(leg, tshard)
        if tel is not None and tel.enabled:
            tel.count("chaos_device_faults_total")
        if self._sticky:
            where = leg if tshard is None else f"{leg} shard {tshard}"
            err = DeviceLostError(f"injected device loss at {where}")
            err.shard = tshard
            raise err
        if self._transient_left > 0:
            self._transient_left -= 1
        err = TransientDeviceError(f"injected transient XLA fault at {leg}")
        err.shard = tshard
        raise err

    def status(self) -> dict:
        return {
            "healthy": self.healthy,
            "sticky": self._sticky,
            "transient_left": self._transient_left,
            "stall_left": self._stall_left,
            "random_p": self._random_p,
            "legs": list(self._legs) if self._legs else None,
            "shards": sorted(self._shards) if self._shards else None,
            "seed": self.seed,
            "checks_total": self.checks_total,
            "faults_raised": self.faults_raised,
            "stalls_injected": self.stalls_injected,
            "injected": {
                f"{leg}/{shard}": n
                for (leg, shard), n in sorted(self.injected.items())
            },
        }


# --- the cluster replica seam ---------------------------------------------


class ReplicaDriftInjector:
    """Cluster-replica drift seam: re-registers one ClusterNode's
    "route"/"push" v1 handler with a wrapper that silently DROPS the
    next `n` op batches while still acknowledging them. The origin's
    push call succeeds, so it never schedules the peer into `_resync`
    — the replica drifts with no nodedown, no failed RPC, no signal at
    all. This is the exact fault class route anti-entropy exists for:
    only the digest exchange on the ping path can see it."""

    def __init__(self, node: Any) -> None:
        self.node = node
        self._orig = node.rpc.registry.lookup("route", 1, "push")
        self._drop_left = 0
        self.dropped_batches = 0
        self.dropped_ops = 0
        self.installed = True
        node.rpc.registry.register("route", 1, "push", self._wrapped)

    def drop_next(self, n: int = 1) -> None:
        """Silently drop the next `n` inbound op batches."""
        self._drop_left = int(n)

    def _wrapped(self, origin: str, ops: Any) -> None:
        if self._drop_left > 0:
            self._drop_left -= 1
            self.dropped_batches += 1
            self.dropped_ops += len(ops)
            return None  # ACKed but never applied: silent drift
        return self._orig(origin, ops)

    def uninstall(self) -> None:
        if self.installed:
            self.node.rpc.registry.register("route", 1, "push", self._orig)
            self.installed = False

    def status(self) -> dict:
        return {
            "installed": self.installed,
            "drop_left": self._drop_left,
            "dropped_batches": self.dropped_batches,
            "dropped_ops": self.dropped_ops,
        }


# --- the disk seam --------------------------------------------------------

# the legs DiskFaultInjector.check() is called with — one name per
# durable-tier I/O seam (emqx_tpu/ds/diskio.py)
DISK_LEGS = (
    "open",
    "append",
    "fsync",
    "dir_fsync",
    "rename",
)

# named places the process can die during compaction choreography —
# each one is a distinct on-disk state the reopen must recover from
CRASH_POINTS = (
    "compact_before_tmp_fsync",
    "compact_after_tmp_fsync",
    "compact_before_rename",
    "compact_after_rename",
)

_DISK_ERRORS: Dict[str, Any] = {
    "enospc": (DiskFullError, errno.ENOSPC, "injected ENOSPC"),
    "eio": (DiskIOError, errno.EIO, "injected EIO"),
    "fsync": (FsyncFailedError, errno.EIO, "injected fsync failure"),
}


class DiskFaultInjector:
    """The durable tier's fault source — installs into the
    `ds/diskio` None-seam so every WAL append, fsync, rename and
    directory fsync in the process becomes injectable (the disk analog
    of DeviceFaultInjector's XLA seam). Modes:

      * **transient / sticky errno faults** (`fail_transient`,
        `fail_sticky`): ENOSPC (full disk), EIO (media error) or
        fsync failure, optionally scoped to `legs` and/or `paths`
        (substring match — one shard's file vs. the whole tier). A
        failed fsync must FAIL-STOP the shard: the storage layer
        never retries it, because the kernel may already have dropped
        the dirty pages (the fsyncgate loss mode).
      * **torn write** (`torn_write`): the next matching append puts
        only the first N bytes in the file and then 'the process
        dies' (SimulatedCrash) — the classic crash-mid-record state
        WAL v2's CRC framing exists to detect.
      * **crash points** (`crash_at`): die at a named step of the
        compaction swap — before/after tmp-fsync, before/after
        rename — each leaving a distinct on-disk state the reopen
        replay must recover to a consistent store.
      * **bit flip** (`corrupt_at`): flip bits at a byte offset of a
        closed WAL file, the silent-media-corruption mode replay's
        CRC check must refuse to deserialize.
      * seeded probabilistic schedule (`fail_random`), replayable
        from `seed` like the device injector's.

    Healthy cost: one falsy module-global read per I/O op."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._sticky: Optional[str] = None  # error kind, or None
        self._transient_left = 0
        self._transient_kind = "eio"
        self._random_p = 0.0
        self._random_kind = "eio"
        self._torn: Optional[int] = None
        self._crash: Optional[str] = None
        self._legs: Optional[Tuple[str, ...]] = None
        self._paths: Optional[Tuple[str, ...]] = None
        self.checks_total = 0
        self.faults_raised = 0
        self.crashes_injected = 0
        self.injected: Dict[str, int] = {}

    # --- wiring -----------------------------------------------------------

    def install(self) -> "DiskFaultInjector":
        """Attach to the process-wide ds/diskio seam (idempotent)."""
        diskio.install_injector(self)
        return self

    def uninstall(self) -> None:
        diskio.uninstall_injector(self)

    # --- fault programming ------------------------------------------------

    def fail_transient(
        self,
        n: int = 1,
        kind: str = "eio",
        legs: Optional[Sequence[str]] = None,
        paths: Optional[Sequence[str]] = None,
    ) -> None:
        """The next `n` matching disk ops fail with `kind`
        (enospc/eio/fsync), then the disk is healthy again."""
        self._transient_left = int(n)
        self._transient_kind = kind
        self._legs = tuple(legs) if legs else None
        self._paths = tuple(paths) if paths else None

    def fail_sticky(
        self,
        kind: str = "eio",
        legs: Optional[Sequence[str]] = None,
        paths: Optional[Sequence[str]] = None,
    ) -> None:
        """Every matching disk op fails with `kind` until heal() —
        the full-disk / dead-media mode the shard breaker must
        fail-stop around."""
        self._sticky = kind
        self._legs = tuple(legs) if legs else None
        self._paths = tuple(paths) if paths else None

    def torn_write(
        self, nbytes: int, paths: Optional[Sequence[str]] = None
    ) -> None:
        """The next matching append writes only its first `nbytes`
        and then the process dies (SimulatedCrash). nbytes may exceed
        the record — it is clamped, so 0 = crash before any byte."""
        self._torn = max(0, int(nbytes))
        self._paths = tuple(paths) if paths else None

    def crash_at(
        self, point: str, paths: Optional[Sequence[str]] = None
    ) -> None:
        """Die at a named compaction crash point (CRASH_POINTS)."""
        if point not in CRASH_POINTS:
            raise ValueError(f"unknown crash point: {point}")
        self._crash = point
        self._paths = tuple(paths) if paths else None

    def fail_random(
        self,
        p: float,
        kind: str = "eio",
        legs: Optional[Sequence[str]] = None,
        paths: Optional[Sequence[str]] = None,
    ) -> None:
        """Seeded probabilistic schedule: every matching op fails with
        probability `p` — deterministic given seed + op sequence."""
        self._random_p = float(p)
        self._random_kind = kind
        self._legs = tuple(legs) if legs else None
        self._paths = tuple(paths) if paths else None

    def heal(self) -> None:
        """Clear every programmed fault: the disk is healthy."""
        self._sticky = None
        self._transient_left = 0
        self._random_p = 0.0
        self._torn = None
        self._crash = None
        self._legs = None
        self._paths = None

    @property
    def healthy(self) -> bool:
        return not (
            self._sticky is not None
            or self._transient_left > 0
            or self._random_p > 0.0
            or self._torn is not None
            or self._crash is not None
        )

    # --- direct media corruption -----------------------------------------

    @staticmethod
    def tear_tail(path: str, garbage: bytes = b"\x7f" * 7) -> None:
        """Append a partial record to a (closed) WAL file — the
        on-disk state a crash mid-append leaves behind, engine-
        independent (the live `torn_write` seam can only tear the
        Python engine's writes; the native engine writes from C)."""
        with open(path, "ab") as f:
            f.write(garbage)

    @staticmethod
    def corrupt_at(path: str, offset: int, xor: int = 0xFF) -> None:
        """Flip bits at `offset` of a (closed) file — silent media
        corruption; replay's CRC verification must refuse the record.
        Negative offsets index from the end."""
        with open(path, "r+b") as f:
            if offset < 0:
                f.seek(offset, 2)
            else:
                f.seek(offset)
            pos = f.tell()
            b = f.read(1)
            if not b:
                raise ValueError(f"offset {offset} past EOF of {path}")
            f.seek(pos)
            f.write(bytes([b[0] ^ (xor & 0xFF)]))

    # --- the seam entries (called by ds/diskio) ---------------------------

    def _match_path(self, path: str) -> bool:
        targets = self._paths
        if targets is None:
            return True
        return any(t in path for t in targets)

    def _record_injected(self, leg: str) -> None:
        self.injected[leg] = self.injected.get(leg, 0) + 1
        DS_METRICS.count_injected(leg)

    def _raise(self, kind: str, leg: str, path: str) -> None:
        cls, eno, msg = _DISK_ERRORS[kind]
        self.faults_raised += 1
        self._record_injected(leg)
        err = cls(f"{msg} at {leg}: {path}", path)
        err.errno = eno
        raise err

    def torn_len(self, path: str, n: int) -> Optional[int]:
        """Consulted by the append seam BEFORE the errno gate: when a
        torn write is armed for this path, returns how many bytes to
        land before the crash; the arm is one-shot."""
        if self._torn is None or not self._match_path(path):
            return None
        torn, self._torn = self._torn, None
        self.crashes_injected += 1
        self._record_injected("torn_write")
        return min(torn, n)

    def check(self, leg: str, path: str) -> None:
        """Called by every diskio seam entry. Healthy: one falsy test
        (done by the caller reading the module slot); here the
        programmed mode decides."""
        if self._legs is not None and leg not in self._legs:
            return
        if not self._match_path(path):
            return
        self.checks_total += 1
        if self._sticky is not None:
            self._raise(self._sticky, leg, path)
        if self._transient_left > 0:
            self._transient_left -= 1
            self._raise(self._transient_kind, leg, path)
        if self._random_p > 0.0 and self.rng.random() < self._random_p:
            self._raise(self._random_kind, leg, path)

    def crash_check(self, point: str, path: str) -> None:
        """Consulted at every named crash point; fires (one-shot) when
        exactly this point is armed."""
        if self._crash != point or not self._match_path(path):
            return
        self._crash = None
        self.crashes_injected += 1
        self._record_injected(point)
        raise SimulatedCrash(f"injected crash at {point}: {path}", path)

    def status(self) -> dict:
        return {
            "healthy": self.healthy,
            "sticky": self._sticky,
            "transient_left": self._transient_left,
            "random_p": self._random_p,
            "torn": self._torn,
            "crash": self._crash,
            "legs": list(self._legs) if self._legs else None,
            "paths": list(self._paths) if self._paths else None,
            "seed": self.seed,
            "checks_total": self.checks_total,
            "faults_raised": self.faults_raised,
            "crashes_injected": self.crashes_injected,
            "injected": dict(sorted(self.injected.items())),
        }


__all__ = [
    "LEGS",
    "SHARD_PROBE_LEG",
    "DISK_LEGS",
    "CRASH_POINTS",
    "DeviceLinkError",
    "TransientDeviceError",
    "DeviceLostError",
    "DeviceDeadlineExceeded",
    "DeviceFaultInjector",
    "ReplicaDriftInjector",
    "DiskFaultInjector",
    "DiskFaultError",
    "DiskFullError",
    "DiskIOError",
    "FsyncFailedError",
    "SimulatedCrash",
]
