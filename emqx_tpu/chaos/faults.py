"""Device fault seam — the injectable error/latency layer at the XLA
boundary.

Everything the broker asks of the accelerator funnels through five
legs: `Router.match_filters_begin` (encode + kernel launch),
`match_filters_finish` (device->host fetch), `resolve_fanout_begin` /
`resolve_fanout_finish` (the dedup/max-QoS plan kernel), and the
device-table `sync` (delta scatter / full upload, on `DeviceTable` and
`ShardedDeviceTable` alike). Each leg carries a `fault_injector`
None-seam (one attribute read when absent — the broker.tracer
discipline), and this module is the thing that plugs into it: a
controllable fault source that can

  * raise a bounded burst of **transient** `XlaRuntimeError`-class
    failures (the flaky-link / preempted-kernel mode the dispatch
    engine's failover must absorb invisibly);
  * declare **sticky device loss** — every device leg fails until
    `heal()` — the mode that must trip the engine's circuit breaker
    into host-degraded service;
  * **stall** a bounded number of transfers for a fixed wall-clock
    delay WITHOUT failing them (the slow-HBM / congested-link mode):
    results stay correct, but the batch blows the engine's per-batch
    deadline, which counts toward the breaker exactly like a failure —
    slow is a fault even when it is not wrong;
  * arm a **seeded probabilistic schedule** (`fail_random`) — every
    matching check faults with probability p drawn from the injector's
    own `random.Random(seed)`, so a chaos run replays bit-identically
    from its seed.

Faults can be scoped to **shards** (`shards=...` on every programming
call): the sub-axis columns of a `ShardedDeviceTable` mesh. A
shard-scoped fault fires on the mesh-wide device legs only while at
least one target shard is still *in* the mesh (`lost_shards` on the
table — an evacuated chip is no longer touched by device dispatches),
and the raised error carries a `shard` attribute so the dispatch
engine's breaker can account the failure per shard instead of
forfeiting the whole mesh. The extra `shard_probe` leg is the
recovery path's direct probe of one (possibly evacuated) chip: it
keeps failing until `heal()` regardless of evacuation, which is what
makes the probe→rebalance chain honest.

The real production fault this seam stands in for surfaces as
`jaxlib.xla_extension.XlaRuntimeError`; the injected classes derive
from `DeviceLinkError` so handlers written against the seam catch both
shapes through one `except Exception` (counted — the static gate's
dispatch-path lint enforces that no device-leg handler swallows
silently)."""

from __future__ import annotations

import random
import time
from typing import Any, Dict, FrozenSet, Optional, Sequence, Tuple

# the legs check() is called with — one name per XLA-boundary seam
LEGS = (
    "match_begin",
    "match_finish",
    "fanout_begin",
    "fanout_finish",
    "sync",
)

# the per-shard recovery probe (dispatch engine shard breaker): not a
# broker dispatch leg, so it is NOT part of LEGS — an un-scoped fault
# still covers it (all-legs faults fail the probe until heal()), and
# it ignores lost_shards: probing the evacuated chip is its whole job
SHARD_PROBE_LEG = "shard_probe"


class DeviceLinkError(RuntimeError):
    """Base of the injected XlaRuntimeError-class failures. `shard` is
    the sub-axis column a shard-scoped fault was attributed to (None
    for whole-device faults) — the dispatch engine's breaker reads it
    to keep the failure domain chip-granular."""

    shard: Optional[int] = None


class TransientDeviceError(DeviceLinkError):
    """A one-off device fault: retry/fallback should absorb it."""


class DeviceLostError(DeviceLinkError):
    """Sticky device loss: every device leg fails until heal()."""


class DeviceDeadlineExceeded(DeviceLinkError):
    """A transfer abandoned past its deadline (wedged link)."""


# sentinel: the programmed fault does not apply to this check
_SKIP = object()


class DeviceFaultInjector:
    """One injector per Router; installed on the router AND its device
    table so route-churn syncs outside the publish path are injectable
    too. `check(leg)` is the hot-path entry: when healthy it is one
    falsy test, so leaving the injector installed for a whole soak
    costs nothing measurable. `seed` fixes the probabilistic schedule
    (`fail_random`) AND `pick_shard`, so a chaos run replays from its
    seed."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._random_p = 0.0
        self._legs: Optional[Tuple[str, ...]] = None
        self._shards: Optional[FrozenSet[int]] = None
        self.checks_total = 0
        self.faults_raised = 0
        self.stalls_injected = 0
        # per-(leg, shard) injected-fault ledger; mirrored on the
        # scrape as emqx_xla_fault_injected_total{leg,shard}
        self.injected: Dict[Tuple[str, str], int] = {}
        self.telemetry: Any = None
        self._router: Any = None

    # --- wiring -----------------------------------------------------------

    def install(self, router: Any) -> "DeviceFaultInjector":
        """Attach to every seam of one Router (idempotent)."""
        router.fault_injector = self
        router.device_table.fault_injector = self
        self.telemetry = router.telemetry
        self._router = router
        return self

    def uninstall(self) -> None:
        r = self._router
        if r is not None:
            if r.fault_injector is self:
                r.fault_injector = None
            if r.device_table.fault_injector is self:
                r.device_table.fault_injector = None
        self._router = None

    # --- fault programming ------------------------------------------------

    def fail_transient(
        self,
        n: int = 1,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """The next `n` device-leg checks (optionally scoped to `legs`
        and/or `shards`) raise TransientDeviceError, then the link is
        healthy again."""
        self._transient_left = int(n)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def fail_sticky(
        self,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Device loss: every check fails until heal(). With `shards`,
        only the targeted sub-axis columns are lost — the chip-loss
        mode the shard breaker must evacuate around."""
        self._sticky = True
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def stall(
        self,
        seconds: float,
        n: int = 1,
        fail: bool = False,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Stall the next `n` checks for `seconds` of wall clock. With
        `fail=False` (default) the leg then SUCCEEDS — the
        slow-but-correct mode that must blow the engine's per-batch
        deadline; `fail=True` additionally abandons the transfer
        (DeviceDeadlineExceeded), the wedged-link mode."""
        self._stall_left = int(n)
        self._stall_s = float(seconds)
        self._stall_fail = bool(fail)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def fail_random(
        self,
        p: float,
        legs: Optional[Sequence[str]] = None,
        shards: Optional[Sequence[int]] = None,
    ) -> None:
        """Arm the seeded probabilistic schedule: every matching check
        raises TransientDeviceError with probability `p`, drawn from
        the injector's `random.Random(seed)` — deterministic given the
        seed and the check sequence (reproducible background noise)."""
        self._random_p = float(p)
        self._legs = tuple(legs) if legs else None
        self._shards = frozenset(shards) if shards is not None else None

    def heal(self) -> None:
        """Clear every programmed fault: the link is healthy."""
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._random_p = 0.0
        self._legs = None
        self._shards = None

    @property
    def healthy(self) -> bool:
        return not (
            self._sticky
            or self._transient_left > 0
            or self._stall_left > 0
            or self._random_p > 0.0
        )

    def pick_shard(self, n_shards: int) -> int:
        """Seeded victim-shard draw for scenario scripts."""
        return self.rng.randrange(int(n_shards))

    # --- the seam entry ---------------------------------------------------

    def _lost_shards(self) -> FrozenSet[int]:
        r = self._router
        if r is None:
            return frozenset()
        lost = getattr(r.device_table, "lost_shards", None)
        return frozenset(lost) if lost else frozenset()

    def _target_shard(self, leg: str, shard: Optional[int]) -> Any:
        """Resolve shard scoping for one check: `_SKIP` (fault does not
        apply here), None (untargeted whole-device fault), or the int
        shard the raised error is attributed to."""
        targets = self._shards
        if targets is None:
            return None
        if shard is not None:
            # shard-scoped call site (the recovery probe of ONE chip)
            return shard if shard in targets else _SKIP
        if leg == SHARD_PROBE_LEG:
            live = targets
        else:
            # mesh-wide device leg: an evacuated chip is out of the
            # mesh, so device dispatches no longer touch it
            live = targets - self._lost_shards()
        if not live:
            return _SKIP
        return min(live)

    def _record_injected(self, leg: str, shard: Optional[int]) -> str:
        label = "all" if shard is None else str(shard)
        key = (leg, label)
        self.injected[key] = self.injected.get(key, 0) + 1
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.count_labeled(
                "fault_injected_total", {"leg": leg, "shard": label}
            )
        return label

    def check(self, leg: str, shard: Optional[int] = None) -> None:
        """Called by every XLA-boundary leg. Healthy: one falsy test.
        Faulty: count, then stall and/or raise per the programmed
        mode. `shard` names the single chip a shard-scoped call site
        (the recovery probe) touches; mesh-wide legs pass None and the
        injector attributes the fault to one live target shard."""
        if self.healthy:
            return
        if self._legs is not None and leg not in self._legs:
            return
        tshard = self._target_shard(leg, shard)
        if tshard is _SKIP:
            return
        self.checks_total += 1
        tel = self.telemetry
        if self._stall_left > 0:
            self._stall_left -= 1
            self.stalls_injected += 1
            self._record_injected(leg, tshard)
            if tel is not None and tel.enabled:
                tel.count("chaos_device_stalls_total")
            time.sleep(self._stall_s)
            if not self._stall_fail:
                return
            self.faults_raised += 1
            if tel is not None and tel.enabled:
                tel.count("chaos_device_faults_total")
            err: DeviceLinkError = DeviceDeadlineExceeded(
                f"injected transfer stall abandoned at {leg} "
                f"({self._stall_s * 1e3:.0f}ms)"
            )
            err.shard = tshard
            raise err
        if self._random_p > 0.0 and not (
            self._sticky or self._transient_left > 0
        ):
            if self.rng.random() >= self._random_p:
                return
        self.faults_raised += 1
        self._record_injected(leg, tshard)
        if tel is not None and tel.enabled:
            tel.count("chaos_device_faults_total")
        if self._sticky:
            where = leg if tshard is None else f"{leg} shard {tshard}"
            err = DeviceLostError(f"injected device loss at {where}")
            err.shard = tshard
            raise err
        if self._transient_left > 0:
            self._transient_left -= 1
        err = TransientDeviceError(f"injected transient XLA fault at {leg}")
        err.shard = tshard
        raise err

    def status(self) -> dict:
        return {
            "healthy": self.healthy,
            "sticky": self._sticky,
            "transient_left": self._transient_left,
            "stall_left": self._stall_left,
            "random_p": self._random_p,
            "legs": list(self._legs) if self._legs else None,
            "shards": sorted(self._shards) if self._shards else None,
            "seed": self.seed,
            "checks_total": self.checks_total,
            "faults_raised": self.faults_raised,
            "stalls_injected": self.stalls_injected,
            "injected": {
                f"{leg}/{shard}": n
                for (leg, shard), n in sorted(self.injected.items())
            },
        }
