"""Device fault seam — the injectable error/latency layer at the XLA
boundary.

Everything the broker asks of the accelerator funnels through five
legs: `Router.match_filters_begin` (encode + kernel launch),
`match_filters_finish` (device->host fetch), `resolve_fanout_begin` /
`resolve_fanout_finish` (the dedup/max-QoS plan kernel), and the
device-table `sync` (delta scatter / full upload, on `DeviceTable` and
`ShardedDeviceTable` alike). Each leg carries a `fault_injector`
None-seam (one attribute read when absent — the broker.tracer
discipline), and this module is the thing that plugs into it: a
controllable fault source that can

  * raise a bounded burst of **transient** `XlaRuntimeError`-class
    failures (the flaky-link / preempted-kernel mode the dispatch
    engine's failover must absorb invisibly);
  * declare **sticky device loss** — every device leg fails until
    `heal()` — the mode that must trip the engine's circuit breaker
    into host-degraded service;
  * **stall** a bounded number of transfers for a fixed wall-clock
    delay WITHOUT failing them (the slow-HBM / congested-link mode):
    results stay correct, but the batch blows the engine's per-batch
    deadline, which counts toward the breaker exactly like a failure —
    slow is a fault even when it is not wrong.

The real production fault this seam stands in for surfaces as
`jaxlib.xla_extension.XlaRuntimeError`; the injected classes derive
from `DeviceLinkError` so handlers written against the seam catch both
shapes through one `except Exception` (counted — the static gate's
dispatch-path lint enforces that no device-leg handler swallows
silently)."""

from __future__ import annotations

import time
from typing import Optional, Sequence

# the legs check() is called with — one name per XLA-boundary seam
LEGS = (
    "match_begin",
    "match_finish",
    "fanout_begin",
    "fanout_finish",
    "sync",
)


class DeviceLinkError(RuntimeError):
    """Base of the injected XlaRuntimeError-class failures."""


class TransientDeviceError(DeviceLinkError):
    """A one-off device fault: retry/fallback should absorb it."""


class DeviceLostError(DeviceLinkError):
    """Sticky device loss: every device leg fails until heal()."""


class DeviceDeadlineExceeded(DeviceLinkError):
    """A transfer abandoned past its deadline (wedged link)."""


class DeviceFaultInjector:
    """One injector per Router; installed on the router AND its device
    table so route-churn syncs outside the publish path are injectable
    too. `check(leg)` is the hot-path entry: when healthy it is one
    falsy test, so leaving the injector installed for a whole soak
    costs nothing measurable."""

    def __init__(self) -> None:
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._legs: Optional[Sequence[str]] = None
        self.checks_total = 0
        self.faults_raised = 0
        self.stalls_injected = 0
        self.telemetry = None
        self._router = None

    # --- wiring -----------------------------------------------------------

    def install(self, router) -> "DeviceFaultInjector":
        """Attach to every seam of one Router (idempotent)."""
        router.fault_injector = self
        router.device_table.fault_injector = self
        self.telemetry = router.telemetry
        self._router = router
        return self

    def uninstall(self) -> None:
        r = self._router
        if r is not None:
            if r.fault_injector is self:
                r.fault_injector = None
            if r.device_table.fault_injector is self:
                r.device_table.fault_injector = None
        self._router = None

    # --- fault programming ------------------------------------------------

    def fail_transient(
        self, n: int = 1, legs: Optional[Sequence[str]] = None
    ) -> None:
        """The next `n` device-leg checks (optionally scoped to `legs`)
        raise TransientDeviceError, then the link is healthy again."""
        self._transient_left = int(n)
        self._legs = tuple(legs) if legs else None

    def fail_sticky(self, legs: Optional[Sequence[str]] = None) -> None:
        """Device loss: every check fails until heal()."""
        self._sticky = True
        self._legs = tuple(legs) if legs else None

    def stall(
        self,
        seconds: float,
        n: int = 1,
        fail: bool = False,
        legs: Optional[Sequence[str]] = None,
    ) -> None:
        """Stall the next `n` checks for `seconds` of wall clock. With
        `fail=False` (default) the leg then SUCCEEDS — the
        slow-but-correct mode that must blow the engine's per-batch
        deadline; `fail=True` additionally abandons the transfer
        (DeviceDeadlineExceeded), the wedged-link mode."""
        self._stall_left = int(n)
        self._stall_s = float(seconds)
        self._stall_fail = bool(fail)
        self._legs = tuple(legs) if legs else None

    def heal(self) -> None:
        """Clear every programmed fault: the link is healthy."""
        self._sticky = False
        self._transient_left = 0
        self._stall_left = 0
        self._stall_s = 0.0
        self._stall_fail = False
        self._legs = None

    @property
    def healthy(self) -> bool:
        return not (
            self._sticky or self._transient_left > 0 or self._stall_left > 0
        )

    # --- the seam entry ---------------------------------------------------

    def check(self, leg: str) -> None:
        """Called by every XLA-boundary leg. Healthy: one falsy test.
        Faulty: count, then stall and/or raise per the programmed
        mode."""
        if self.healthy:
            return
        if self._legs is not None and leg not in self._legs:
            return
        self.checks_total += 1
        tel = self.telemetry
        if self._stall_left > 0:
            self._stall_left -= 1
            self.stalls_injected += 1
            if tel is not None and tel.enabled:
                tel.count("chaos_device_stalls_total")
            time.sleep(self._stall_s)
            if not self._stall_fail:
                return
            self.faults_raised += 1
            if tel is not None and tel.enabled:
                tel.count("chaos_device_faults_total")
            raise DeviceDeadlineExceeded(
                f"injected transfer stall abandoned at {leg} "
                f"({self._stall_s * 1e3:.0f}ms)"
            )
        self.faults_raised += 1
        if tel is not None and tel.enabled:
            tel.count("chaos_device_faults_total")
        if self._sticky:
            raise DeviceLostError(f"injected device loss at {leg}")
        self._transient_left -= 1
        raise TransientDeviceError(
            f"injected transient XLA fault at {leg}"
        )

    def status(self) -> dict:
        return {
            "healthy": self.healthy,
            "sticky": self._sticky,
            "transient_left": self._transient_left,
            "stall_left": self._stall_left,
            "legs": list(self._legs) if self._legs else None,
            "checks_total": self.checks_total,
            "faults_raised": self.faults_raised,
            "stalls_injected": self.stalls_injected,
        }
