"""The chaos scenario catalog, each with its expected-response
contract — the analog of the reference's cross-app suites (SURVEY
L1/L2): `emqx_cm` / channel-takeover tests, `emqx_node_rebalance`
evacuation/purge SUITEs, `emqx_router_helper` nodedown purge, and the
route-consistency checks. A scenario does three things: inject the
fault, drive the system while the fault is live, and assert the
broker's *response* — detection, alarming, quarantine, recovery — not
merely that it survived.

Contract vocabulary (every scenario emits `Check` rows):
  * detection:  the sentinel confirms the fault within one audit
    window (a bounded number of sampled publishes);
  * paging:     the matching alarm fired during the scenario window —
    SLOs hold OR burn-rate alarms fire, never breached-and-silent;
  * forensics:  a flight bundle captured the anomaly;
  * recovery:   quarantine engaged AND auto-cleared on the next clean
    sync; cluster state reconverged after heal;
  * accounting: `emqx_xla_audit_divergence_total` moved for every
    injected fault — nothing detected-but-uncounted.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..broker.packet import SubOpts
from ..cluster.metrics import CLUSTER_METRICS

log = logging.getLogger("emqx_tpu.chaos.scenarios")


def _sink(pkts) -> None:
    return None

DIVERGENCE_ALARM = "xla_audit_divergence"


@dataclass
class Check:
    name: str
    ok: bool
    detail: str = ""


@dataclass
class ScenarioResult:
    name: str
    checks: List[Check] = field(default_factory=list)
    detect_ms: Optional[float] = None
    recovery_ms: Optional[float] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "checks": [
                {"name": c.name, "ok": c.ok, "detail": c.detail}
                for c in self.checks
            ],
            "detect_ms": self.detect_ms,
            "recovery_ms": self.recovery_ms,
            **self.extra,
        }


class Scenario:
    """Base: a named fault + its contract. `run` receives the engine
    and returns a ScenarioResult whose checks the engine asserts."""

    name = "scenario"
    reference = ""  # the reference suite this mirrors (PARITY.md)
    needs_cluster = False
    needs_mesh = False  # requires a ShardedDeviceTable (multi-chip)
    needs_durable = False  # requires the WAL-backed durable tier

    async def run(self, eng) -> ScenarioResult:  # pragma: no cover
        raise NotImplementedError


def _slo_check(eng, t0_wall: float) -> Check:
    """SLOs hold OR burn alarms fire: an objective that burned through
    the window without paging is the one forbidden state."""
    silent = []
    for name, obj in eng.sentinel.slo.items():
        s = obj.evaluate()
        alarm = f"xla_slo_{name}_burn"
        if s["breached"] and not (
            eng.alarms.is_active(alarm)
            or alarm in eng.alarms.fired_since(t0_wall)
        ):
            silent.append(name)
    return Check(
        "slo_holds_or_alarms",
        not silent,
        "breached-and-silent: " + ",".join(silent) if silent else "clean",
    )


def _fires(eng, rule: str) -> int:
    """How many times a flight trigger rule has FIRED (bundle written).
    The rotation-immune count — `store.list()` drops old bundles at
    max_snapshots, which would make a presence check racy."""
    fl = eng.flight
    if fl is None:
        return 0
    return fl.triggers_total.get(rule, 0)


class StormBaseline(Scenario):
    """No fault at all: a pure storm window. The contract is the
    boring one production lives on — deliveries flow, zero divergence,
    SLOs clean or paged."""

    name = "storm_baseline"
    reference = "emqx_broker_SUITE publish storms"

    def __init__(self, seconds: float = 5.0):
        self.seconds = seconds

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        t0w = time.time()
        d0, p0 = eng.delivered, eng.published
        det0 = len(eng.detections)
        await asyncio.sleep(self.seconds)
        res.checks.append(
            Check(
                "deliveries_flow",
                eng.delivered > d0 and eng.published > p0,
                f"+{eng.published - p0} pub / +{eng.delivered - d0} dlv",
            )
        )
        res.checks.append(
            Check(
                "no_divergence",
                len(eng.detections) == det0,
                f"{len(eng.detections) - det0} unexpected",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["window_s"] = self.seconds
        return res


class _CorruptionBase(Scenario):
    """Shared inject→detect→quarantine→auto-clear→verify walk; the
    subclasses differ only in WHAT they corrupt."""

    def _corrupt(self, eng, flt: str) -> int:  # pragma: no cover
        raise NotImplementedError

    async def _one_fault(self, eng, flt: str, res: ScenarioResult) -> None:
        c0 = eng.counters()
        det0 = len(eng.detections)
        # warm: the row must be device-resident and serving clean
        fan0 = await eng.burst([eng.fresh_topic(flt)])
        corrupted = self._corrupt(eng, flt)
        eng.record_fault(self.name, {"filter": flt, "slots": corrupted})
        res.checks.append(
            Check("injectable", corrupted >= 1, f"{flt}: {corrupted} slots")
        )
        if corrupted < 1:
            return
        t_inj = time.monotonic()
        t0w = time.time()
        detected = False
        rounds = 0
        for rounds in range(1, eng.detect_rounds + 1):
            await eng.burst(
                [eng.fresh_topic(flt) for _ in range(eng.detect_burst)]
            )
            if len(eng.detections) > det0:
                detected = True
                break
        window = rounds * eng.detect_burst
        res.checks.append(
            Check(
                "detected_within_window",
                detected,
                f"{window} publishes ({rounds} rounds, "
                f"sample 1/{eng.sentinel.sample_n})",
            )
        )
        if detected:
            eng.faults_detected += 1
            res.detect_ms = round(
                (eng.detections[-1][0] - t_inj) * 1e3, 2
            )
        # recovery: quarantine engaged, then auto-cleared by the next
        # clean table sync (driving fresh matches forces the sync)
        c1 = eng.counters()
        res.checks.append(
            Check(
                "quarantine_engaged",
                c1.get("audit_quarantine_total", 0)
                > c0.get("audit_quarantine_total", 0),
                f"quarantined={eng.router.quarantined_filters()}",
            )
        )
        rec = await eng.drive_until(
            lambda: not eng.router.quarantined_filters()
            and eng.counters().get("audit_unquarantine_total", 0)
            > c0.get("audit_unquarantine_total", 0),
            flt=flt,
            timeout=eng.settle_timeout,
        )
        res.checks.append(
            Check(
                "quarantine_auto_cleared",
                rec is not None,
                f"{round(rec * 1e3, 1)}ms" if rec is not None else "timeout",
            )
        )
        if rec is not None:
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        # post-recovery: the healed device serves the full fan again
        post = await eng.burst([eng.fresh_topic(flt) for _ in range(4)])
        res.checks.append(
            Check(
                "post_recovery_serving",
                post == 4 * eng.chaos_fan and fan0 == eng.chaos_fan,
                f"fan {post}/4 bursts (want {4 * eng.chaos_fan})",
            )
        )
        c2 = eng.counters()
        res.checks.append(
            Check(
                "divergence_accounted",
                c2.get("audit_divergence_total", 0)
                > c0.get("audit_divergence_total", 0),
                f"+{c2.get('audit_divergence_total', 0) - c0.get('audit_divergence_total', 0)}",
            )
        )
        res.checks.append(
            Check(
                "alarm_raised",
                DIVERGENCE_ALARM in eng.alarms.fired_since(t0w)
                or eng.alarms.is_active(DIVERGENCE_ALARM),
                DIVERGENCE_ALARM,
            )
        )


class RowCorruption(_CorruptionBase):
    """Scoped device-row decay: one filter's cuckoo slot emptied on
    device while every other row keeps serving — detection must come
    from the sampled shadow audit, not from gross failure."""

    name = "row_corruption"
    reference = (
        "route-consistency checks (emqx_router_SUITE) against "
        "single-row device memory decay"
    )

    def __init__(self, faults: int = 2):
        self.faults = faults

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        fires0 = _fires(eng, "audit_divergence")
        eng.reset_flight_cooldown("audit_divergence")
        for i in range(self.faults):
            flt = eng.chaos_filters[i % len(eng.chaos_filters)]
            await self._one_fault(eng, flt, res)
        # scenario-level: ≥1 bundle froze for this window (the rule's
        # cooldown intentionally coalesces faults inside one window)
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "audit_divergence") > fires0,
                "audit_divergence trigger fired",
            )
        )
        res.extra["faults"] = self.faults
        return res

    def _corrupt(self, eng, flt: str) -> int:
        return eng.router.chaos_corrupt_rows([flt])


class SlotDecay(_CorruptionBase):
    """Whole-table decay: every device cuckoo slot empties at once (the
    gross-failure mode). The first detected divergence quarantines and
    flags a FULL index re-upload, so ONE quarantine cycle must heal
    the entire table."""

    name = "slot_decay"
    reference = "whole-table memory decay vs emqx route rebuild"

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        fires0 = _fires(eng, "audit_divergence")
        eng.reset_flight_cooldown("audit_divergence")
        await self._one_fault(eng, eng.chaos_filters[0], res)
        # the whole table healed, not just the audited filter: every
        # chaos filter must serve its full fan again
        post = await eng.burst(
            [eng.fresh_topic(f) for f in eng.chaos_filters]
        )
        res.checks.append(
            Check(
                "whole_table_healed",
                post == len(eng.chaos_filters) * eng.chaos_fan,
                f"{post} deliveries from {len(eng.chaos_filters)} filters",
            )
        )
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "audit_divergence") > fires0,
                "audit_divergence trigger fired",
            )
        )
        return res

    def _corrupt(self, eng, flt: str) -> int:
        return eng.router.chaos_corrupt_slots()


class DeviceLoss(Scenario):
    """Device-link failure walked end to end under the live storm:
    (1) a transient fault burst is absorbed INVISIBLY by the host
    failover (zero publisher errors, fallback counted, breaker stays
    closed); (2) sticky device loss trips the breaker within its
    failure budget — host-degraded service stays correct and
    audit-clean, the `xla_device_breaker` alarm pages, a
    `device_breaker_trip` flight bundle freezes; (3) healing the link
    lets the canary probe resync full device state and close the
    breaker, verified divergence-free by a full-truth sweep."""

    name = "device_loss"
    reference = (
        "emqx_olp load-control backoff (SURVEY.md:96) applied to the "
        "device link; breaker trip/recover around XLA faults"
    )

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        de = eng.broker.engine
        inj = eng.injector
        c = eng.counters
        t0w = time.time()
        err0 = eng.storm_errors
        c0 = c()
        det0 = len(eng.detections)
        fires0 = _fires(eng, "device_breaker_trip")
        eng.reset_flight_cooldown("device_breaker_trip")
        # --- phase 1: transient blip — failover absorbs, breaker holds
        inj.fail_transient(2)
        await eng.burst(
            [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(8)]
        )
        while not inj.healthy:  # storm may not have hit the seam yet
            await eng.burst([eng.fresh_topic(eng.chaos_filters[0])])
        c1 = c()
        res.checks.append(
            Check(
                "transient_absorbed",
                de.breaker_state == "closed"
                and c1.get("breaker_device_failures_total", 0)
                > c0.get("breaker_device_failures_total", 0)
                and eng.storm_errors == err0,
                f"+{c1.get('breaker_device_failures_total', 0) - c0.get('breaker_device_failures_total', 0)}"
                " faults, 0 publisher errors, breaker closed",
            )
        )
        # --- phase 2: sticky loss — trip within the failure budget
        inj.fail_sticky()
        eng.record_fault(self.name, {"mode": "sticky"})
        t_inj = time.monotonic()
        # failure budget: threshold batches + slack for in-flight ones
        budget = de.breaker_threshold + 4
        tripped = None
        for _ in range(budget):
            await eng.burst([eng.fresh_topic(eng.chaos_filters[0])])
            if de.breaker_state == "open":
                tripped = time.monotonic() - t_inj
                break
        res.checks.append(
            Check(
                "breaker_tripped_within_budget",
                tripped is not None,
                f"{tripped * 1e3:.0f}ms, budget {budget} batches"
                if tripped is not None
                else f"not within {budget} batches",
            )
        )
        if tripped is not None:
            eng.faults_detected += 1
            res.detect_ms = round(tripped * 1e3, 2)
        # --- degraded-but-correct: full fan from the host walk, zero
        # publisher-visible errors, zero audit divergence
        fan = await eng.burst(
            [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(4)]
        )
        res.checks.append(
            Check(
                "degraded_serving_correct",
                fan == 4 * eng.chaos_fan,
                f"fan {fan}/{4 * eng.chaos_fan} host-side",
            )
        )
        res.checks.append(
            Check(
                "alarm_raised",
                eng.alarms.is_active("xla_device_breaker")
                or "xla_device_breaker" in eng.alarms.fired_since(t0w),
                "xla_device_breaker",
            )
        )
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "device_breaker_trip") > fires0,
                "device_breaker_trip trigger fired",
            )
        )
        res.checks.append(
            Check(
                "no_divergence_while_degraded",
                len(eng.detections) == det0,
                f"{len(eng.detections) - det0} unexpected",
            )
        )
        # --- phase 3: heal -> probe -> resync -> close
        inj.heal()
        rec = await eng.wait_for(
            lambda: de.breaker_state == "closed",
            timeout=eng.settle_timeout + de.probe_backoff_max_s * 4,
        )
        res.checks.append(
            Check(
                "breaker_recovered",
                rec is not None,
                f"{rec * 1e3:.0f}ms after heal" if rec is not None
                else "probe never closed the breaker",
            )
        )
        if rec is not None:
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        c2 = c()
        res.checks.append(
            Check(
                "recovery_resynced_device",
                c2.get("device_resyncs_total", 0)
                > c0.get("device_resyncs_total", 0)
                and c2.get("breaker_recoveries_total", 0)
                > c0.get("breaker_recoveries_total", 0),
                f"resyncs +{c2.get('device_resyncs_total', 0) - c0.get('device_resyncs_total', 0)}",
            )
        )
        res.checks.append(
            Check(
                "alarm_cleared",
                not eng.alarms.is_active("xla_device_breaker"),
                "xla_device_breaker deactivated",
            )
        )
        # post-close: device-served again, full fan, zero divergence
        # (the sentinel's shadow audit samples these bursts; the sweep
        # compares EVERY answer to the oracle)
        post = await eng.burst(
            [eng.fresh_topic(f) for f in eng.chaos_filters]
        )
        res.checks.append(
            Check(
                "post_recovery_full_fan",
                post == len(eng.chaos_filters) * eng.chaos_fan,
                f"{post} deliveries device-side",
            )
        )
        sweep = await eng.audit_sweep(per_groups=128)
        res.checks.append(
            Check(
                "divergence_free_after_close",
                sweep["silent_divergences"] == 0,
                f"{sweep['topics_swept']} topics swept",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["trip_after_failures"] = de.breaker_threshold
        return res


class DeviceFlap(Scenario):
    """Repeated loss/heal cycles (a flapping accelerator link): each
    cycle must trip and fully recover — no wedged half-open state, no
    publisher-visible errors, no leftover alarm — and the breaker's
    counters must account for every cycle."""

    name = "device_flap"
    reference = (
        "emqx_limiter token-bucket refill (SURVEY.md:376) analog: "
        "repeated overload/recover cycles must stay bounded"
    )

    def __init__(self, cycles: int = 3):
        self.cycles = cycles

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        de = eng.broker.engine
        inj = eng.injector
        t0w = time.time()
        err0 = eng.storm_errors
        c0 = eng.counters()
        recovered = 0
        t_first = None
        for cycle in range(self.cycles):
            inj.fail_sticky()
            eng.record_fault(self.name, {"cycle": cycle})
            if t_first is None:
                t_first = time.monotonic()
            tripped = None
            for _ in range(de.breaker_threshold + 4):
                await eng.burst([eng.fresh_topic(eng.chaos_filters[0])])
                if de.breaker_state == "open":
                    tripped = True
                    break
            if tripped:
                eng.faults_detected += 1
            inj.heal()
            rec = await eng.wait_for(
                lambda: de.breaker_state == "closed",
                timeout=eng.settle_timeout + de.probe_backoff_max_s * 4,
            )
            if tripped and rec is not None:
                recovered += 1
        res.checks.append(
            Check(
                "every_cycle_recovered",
                recovered == self.cycles,
                f"{recovered}/{self.cycles} trip+recover cycles",
            )
        )
        c1 = eng.counters()
        res.checks.append(
            Check(
                "flaps_accounted",
                c1.get("breaker_trips_total", 0)
                - c0.get("breaker_trips_total", 0) == self.cycles
                and c1.get("breaker_recoveries_total", 0)
                - c0.get("breaker_recoveries_total", 0) == self.cycles,
                f"trips +{c1.get('breaker_trips_total', 0) - c0.get('breaker_trips_total', 0)}, "
                f"recoveries +{c1.get('breaker_recoveries_total', 0) - c0.get('breaker_recoveries_total', 0)}",
            )
        )
        if t_first is not None:
            res.detect_ms = round((time.monotonic() - t_first) * 1e3, 2)
            res.recovery_ms = res.detect_ms
        res.checks.append(
            Check(
                "breaker_closed_at_end",
                de.breaker_state == "closed"
                and not eng.alarms.is_active("xla_device_breaker"),
                f"state={de.breaker_state}",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        sweep = await eng.audit_sweep(per_groups=64)
        res.checks.append(
            Check(
                "audit_clean_after_flaps",
                sweep["silent_divergences"] == 0,
                f"{sweep['topics_swept']} topics swept",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["cycles"] = self.cycles
        return res


class ChipLoss(Scenario):
    """One chip of the mesh dies under the live storm: the shard
    breaker must keep the failure domain chip-granular. Contract:
    (1) sticky loss scoped to ONE shard trips the SHARD breaker within
    its failure budget while the whole-device breaker stays closed and
    the table is never suspended; (2) the lost shard's slice is
    evacuated onto the survivor mesh (N-1 chips serve the whole table
    on device) with the alarm paged and a flight bundle frozen;
    (3) route churn keeps landing while degraded; (4) healing the chip
    lets the per-shard probe rebalance back to the full mesh with a
    verified canary, the alarm clears, and a full-truth sweep finds
    zero silent divergence."""

    name = "chip_loss"
    reference = (
        "emqx_node_rebalance evacuation SUITE (SURVEY L2) applied to "
        "the mesh sub-axis: lose a member, evacuate live state, keep "
        "serving, rebalance back"
    )
    needs_mesh = True

    def __init__(self, shard: Optional[int] = None):
        self.shard = shard

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        de = eng.broker.engine
        inj = eng.injector
        dt = eng.router.device_table
        c = eng.counters
        t0w = time.time()
        err0 = eng.storm_errors
        c0 = c()
        n0 = dt.n_shards
        victim = (
            self.shard if self.shard is not None
            else inj.pick_shard(n0)
        )
        res.extra["victim_shard"] = victim
        fires0 = _fires(eng, "device_breaker_trip")
        eng.reset_flight_cooldown("device_breaker_trip")
        # --- sticky loss scoped to ONE chip
        inj.fail_sticky(shards=[victim])
        eng.record_fault(self.name, {"shard": victim})
        t_inj = time.monotonic()
        budget = de.breaker_threshold + 4
        tripped = None
        for _ in range(budget):
            await eng.burst(
                [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(2)]
            )
            if victim in de.open_shards or not dt.lost_shards == set():
                tripped = time.monotonic() - t_inj
                break
        res.checks.append(
            Check(
                "shard_tripped_within_budget",
                tripped is not None,
                f"{tripped * 1e3:.0f}ms, budget {budget} batches"
                if tripped is not None
                else f"not within {budget} batches",
            )
        )
        if tripped is not None:
            eng.faults_detected += 1
            res.detect_ms = round(tripped * 1e3, 2)
        # --- failure domain stayed chip-granular: whole breaker closed,
        # table never suspended
        res.checks.append(
            Check(
                "whole_table_never_suspended",
                de.breaker_state == "closed"
                and not eng.router.device_suspended,
                f"breaker={de.breaker_state}, "
                f"suspended={eng.router.device_suspended}",
            )
        )
        # --- evacuated onto the survivor mesh: N-1 device service
        res.checks.append(
            Check(
                "evacuated_to_survivors",
                dt.lost_shards == {victim} and dt.n_shards == n0 - 1,
                f"lost={sorted(dt.lost_shards)}, mesh {dt.n_shards}/{n0}",
            )
        )
        fan = await eng.burst(
            [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(4)]
        )
        res.checks.append(
            Check(
                "degraded_serving_correct",
                fan == 4 * eng.chaos_fan,
                f"fan {fan}/{4 * eng.chaos_fan} on N-1 mesh",
            )
        )
        res.checks.append(
            Check(
                "alarm_raised",
                eng.alarms.is_active("xla_device_breaker")
                or "xla_device_breaker" in eng.alarms.fired_since(t0w),
                "xla_device_breaker",
            )
        )
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "device_breaker_trip") > fires0,
                "device_breaker_trip trigger fired",
            )
        )
        # --- route churn while degraded: subscribe/unsubscribe legs
        # keep landing on the survivor mesh
        churned = await eng.route_churn(32)
        res.checks.append(
            Check(
                "churn_lands_while_degraded",
                churned == 64 and eng.storm_errors == err0,
                f"{churned} add+delete legs on N-1 mesh",
            )
        )
        # --- heal -> probe -> rebalance back to N -> verified close
        inj.heal()
        rec = await eng.wait_for(
            lambda: victim not in de.open_shards and not dt.lost_shards,
            timeout=eng.settle_timeout + de.probe_backoff_max_s * 4,
        )
        res.checks.append(
            Check(
                "rebalanced_back_to_full_mesh",
                rec is not None and dt.n_shards == n0,
                f"{rec * 1e3:.0f}ms after heal, mesh {dt.n_shards}/{n0}"
                if rec is not None
                else "probe never rebalanced the shard back",
            )
        )
        if rec is not None:
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        c2 = c()
        res.checks.append(
            Check(
                "shard_cycle_accounted",
                c2.get("breaker_shard_trips_total", 0)
                > c0.get("breaker_shard_trips_total", 0)
                and c2.get("breaker_shard_evacuations_total", 0)
                > c0.get("breaker_shard_evacuations_total", 0)
                and c2.get("breaker_shard_recoveries_total", 0)
                > c0.get("breaker_shard_recoveries_total", 0),
                "trip+evacuation+recovery counted",
            )
        )
        res.checks.append(
            Check(
                "alarm_cleared",
                not eng.alarms.is_active("xla_device_breaker"),
                "xla_device_breaker deactivated",
            )
        )
        post = await eng.burst(
            [eng.fresh_topic(f) for f in eng.chaos_filters]
        )
        res.checks.append(
            Check(
                "post_recovery_full_fan",
                post == len(eng.chaos_filters) * eng.chaos_fan,
                f"{post} deliveries on restored mesh",
            )
        )
        sweep = await eng.audit_sweep(per_groups=128)
        res.checks.append(
            Check(
                "divergence_free_after_rebalance",
                sweep["silent_divergences"] == 0,
                f"{sweep['topics_swept']} topics swept",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["mesh_shards"] = n0
        return res


class ChipFlap(Scenario):
    """Repeated chip loss/heal cycles: every cycle must evacuate to
    N-1 and rebalance back to N — no wedged degraded mesh, no leaked
    lost shards, exact trip/recovery accounting, zero publisher
    errors."""

    name = "chip_flap"
    reference = (
        "emqx_node_rebalance repeated evacuate/rejoin cycles on one "
        "member; flapping-link discipline at shard granularity"
    )
    needs_mesh = True

    def __init__(self, cycles: int = 2, shard: Optional[int] = None):
        self.cycles = cycles
        self.shard = shard

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        de = eng.broker.engine
        inj = eng.injector
        dt = eng.router.device_table
        t0w = time.time()
        err0 = eng.storm_errors
        c0 = eng.counters()
        n0 = dt.n_shards
        victim = (
            self.shard if self.shard is not None
            else inj.pick_shard(n0)
        )
        res.extra["victim_shard"] = victim
        recovered = 0
        t_first = None
        for cycle in range(self.cycles):
            inj.fail_sticky(shards=[victim])
            eng.record_fault(self.name, {"cycle": cycle, "shard": victim})
            if t_first is None:
                t_first = time.monotonic()
            tripped = False
            for _ in range(de.breaker_threshold + 4):
                await eng.burst(
                    [eng.fresh_topic(eng.chaos_filters[0])
                     for _ in range(2)]
                )
                if victim in de.open_shards or dt.lost_shards:
                    tripped = True
                    break
            if tripped:
                eng.faults_detected += 1
            inj.heal()
            rec = await eng.wait_for(
                lambda: victim not in de.open_shards
                and not dt.lost_shards,
                timeout=eng.settle_timeout + de.probe_backoff_max_s * 4,
            )
            if tripped and rec is not None:
                recovered += 1
        res.checks.append(
            Check(
                "every_cycle_recovered",
                recovered == self.cycles,
                f"{recovered}/{self.cycles} evacuate+rebalance cycles",
            )
        )
        c1 = eng.counters()
        res.checks.append(
            Check(
                "flaps_accounted",
                c1.get("breaker_shard_trips_total", 0)
                - c0.get("breaker_shard_trips_total", 0) == self.cycles
                and c1.get("breaker_shard_recoveries_total", 0)
                - c0.get("breaker_shard_recoveries_total", 0)
                == self.cycles,
                f"shard trips +{c1.get('breaker_shard_trips_total', 0) - c0.get('breaker_shard_trips_total', 0)}, "
                f"recoveries +{c1.get('breaker_shard_recoveries_total', 0) - c0.get('breaker_shard_recoveries_total', 0)}",
            )
        )
        if t_first is not None:
            res.detect_ms = round((time.monotonic() - t_first) * 1e3, 2)
            res.recovery_ms = res.detect_ms
        res.checks.append(
            Check(
                "full_mesh_at_end",
                dt.n_shards == n0 and not dt.lost_shards
                and not de.open_shards
                and not eng.alarms.is_active("xla_device_breaker"),
                f"mesh {dt.n_shards}/{n0}, lost={sorted(dt.lost_shards)}",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        sweep = await eng.audit_sweep(per_groups=64)
        res.checks.append(
            Check(
                "audit_clean_after_flaps",
                sweep["silent_divergences"] == 0,
                f"{sweep['topics_swept']} topics swept",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["cycles"] = self.cycles
        return res


class ReshardChurn(Scenario):
    """Administrative re-shard cycles under the storm (no fault at
    all): evacuate a shard and rebalance it back, repeatedly, while
    publishes and route churn keep flowing — the emqx_node_rebalance
    admin-rebalance analog. Every cycle must advance the shard-map
    generation, and the storm must see zero errors and zero
    divergence; this is the proof the re-shard machinery itself is
    production-safe, independent of any breaker."""

    name = "reshard_churn"
    reference = (
        "emqx_node_rebalance admin API: operator-driven rebalance "
        "under load, no member failure involved"
    )
    needs_mesh = True

    def __init__(self, cycles: int = 2):
        self.cycles = cycles

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        dt = eng.router.device_table
        t0w = time.time()
        err0 = eng.storm_errors
        det0 = len(eng.detections)
        n0 = dt.n_shards
        gen0 = dt.shard_gen
        t0 = time.monotonic()
        cycles_ok = 0
        for cycle in range(self.cycles):
            victim = cycle % n0
            eng.record_fault(self.name, {"cycle": cycle, "shard": victim})
            if not eng.router.evacuate_shard(victim):
                break
            fan = await eng.burst(
                [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(2)]
            )
            await eng.route_churn(16)
            ok_deg = (
                dt.n_shards == n0 - 1 and fan == 2 * eng.chaos_fan
            )
            if not eng.router.rebalance_shard(victim):
                break
            fan = await eng.burst(
                [eng.fresh_topic(eng.chaos_filters[0]) for _ in range(2)]
            )
            if ok_deg and dt.n_shards == n0 and fan == 2 * eng.chaos_fan:
                cycles_ok += 1
                # the reshard was observed end-to-end (N-1 service,
                # generation bump, N restored): count the detection
                # that matches this cycle's recorded fault
                eng.faults_detected += 1
        res.checks.append(
            Check(
                "every_cycle_reserved_correctly",
                cycles_ok == self.cycles,
                f"{cycles_ok}/{self.cycles} evacuate+rebalance cycles "
                "served full fan at N-1 and N",
            )
        )
        res.checks.append(
            Check(
                "shard_map_generation_advanced",
                dt.shard_gen >= gen0 + 2 * self.cycles,
                f"gen {gen0} -> {dt.shard_gen}",
            )
        )
        res.checks.append(
            Check(
                "full_mesh_at_end",
                dt.n_shards == n0 and not dt.lost_shards,
                f"mesh {dt.n_shards}/{n0}",
            )
        )
        res.checks.append(
            Check(
                "no_divergence",
                len(eng.detections) == det0,
                f"{len(eng.detections) - det0} unexpected",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        sweep = await eng.audit_sweep(per_groups=64)
        res.checks.append(
            Check(
                "audit_clean_after_reshard",
                sweep["silent_divergences"] == 0,
                f"{sweep['topics_swept']} topics swept",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.recovery_ms = round((time.monotonic() - t0) * 1e3, 2)
        res.extra["cycles"] = self.cycles
        return res


class DisconnectTakeover(Scenario):
    """Mass-disconnect + same-node session takeover: a wave of the
    fleet drops (eviction agent), the storm keeps running, the wave
    reconnects with clean_start=False and must resume its sessions —
    routes intact, no divergence, deliveries restored."""

    name = "disconnect_takeover"
    reference = (
        "emqx_cm takeover + emqx_eviction_agent_SUITE "
        "(connection eviction, session preservation)"
    )

    def __init__(self, wave: Optional[int] = None):
        self.wave = wave

    async def run(self, eng) -> ScenarioResult:
        from ..cluster.rebalance import EvictionAgent

        res = ScenarioResult(self.name)
        t0w = time.time()
        det0 = len(eng.detections)
        fleet = eng.fleet
        wave = self.wave or max(100, fleet.n // 20)
        wave = min(wave, len(fleet.clients))
        pre_connected = eng.broker.connected_count()
        agent = EvictionAgent(eng.broker)
        t_wave = time.monotonic()
        evicted = agent.evict_connections(wave)
        res.checks.append(
            Check("wave_evicted", evicted == wave, f"{evicted}/{wave}")
        )
        # the fleet builds first, so eviction order == fleet order
        wave_cids = [
            cid
            for cid in fleet.clients[: wave * 2]
            if not eng.broker.sessions[cid].connected
        ]
        res.checks.append(
            Check(
                "wave_identified",
                len(wave_cids) == evicted,
                f"{len(wave_cids)} disconnected",
            )
        )
        d0 = eng.delivered
        await asyncio.sleep(0.2)  # storm runs against the degraded fleet
        # takeover: reconnect with clean_start=False -> session resumed
        resumed = 0
        b = eng.broker
        for i, cid in enumerate(wave_cids):
            s, present = b.open_session(
                cid, clean_start=False, cfg=fleet.cfg
            )
            s.outgoing_sink = fleet.sink
            resumed += bool(present)
            if (i + 1) % 2048 == 0:
                await asyncio.sleep(0)
        res.recovery_ms = round((time.monotonic() - t_wave) * 1e3, 2)
        res.checks.append(
            Check(
                "sessions_resumed",
                resumed == len(wave_cids),
                f"{resumed}/{len(wave_cids)} session_present",
            )
        )
        subs_ok = all(
            len(b.sessions[cid].subscriptions) == 1
            for cid in wave_cids[:32]
        )
        res.checks.append(
            Check("subscriptions_survived", subs_ok, "sampled 32")
        )
        res.checks.append(
            Check(
                "connected_restored",
                eng.broker.connected_count() == pre_connected,
                f"{eng.broker.connected_count()}/{pre_connected}",
            )
        )
        res.checks.append(
            Check(
                "no_divergence",
                len(eng.detections) == det0,
                f"{len(eng.detections) - det0} unexpected",
            )
        )
        res.checks.append(
            Check(
                "deliveries_flow", eng.delivered > d0,
                f"+{eng.delivered - d0}",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["wave"] = wave
        return res


class PartitionNodedown(Scenario):
    """Cluster partition through the RPC black-hole seam: the victim
    vanishes without an RST. Contract: control-plane calls stay
    BOUNDED (timeout + counted retries, no hang), the membership
    declares the peer down within its miss budget, the survivor purges
    the dead node's contribution in one batched sweep, and heal+rejoin
    reconverges both replicas — forwards flowing again."""

    name = "partition_nodedown"
    reference = (
        "emqx_router_helper nodedown purge + ekka membership "
        "partition handling"
    )
    needs_cluster = True

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        main, victim = eng.node, eng.victim
        ma, va = main.rpc.listen_addr, victim.rpc.listen_addr
        # the reconvergence target is the victim's LOCAL truth (its
        # announced route contribution) — the survivor-side pair count
        # can already be racing a heartbeat miss under load
        vpairs = len(victim._local_refs)
        res.extra["victim_routes_before"] = vpairs
        c0 = eng.counters()
        eng.record_fault(
            "partition", {"victim": victim.node_id, "routes": vpairs}
        )
        # a wire fault is not an audit divergence; the injection counts
        # as detected when the MEMBERSHIP layer declares the nodedown
        main.rpc.partition(va)
        victim.rpc.partition(ma)
        t_inj = time.monotonic()
        # rollup first, while the victim is still a member: it must
        # report the peer unreachable, not hang on it (if a heartbeat
        # already dropped the peer, that IS the detection — accept it)
        t_roll = time.monotonic()
        roll = await main.sentinel_rollup()
        roll_s = time.monotonic() - t_roll
        res.checks.append(
            Check(
                "rollup_bounded",
                (
                    roll["cluster"]["unreachable"] >= 1
                    or victim.node_id not in main.membership.members
                )
                and roll_s < 15.0,
                f"{roll_s * 1e3:.0f}ms, "
                f"unreachable={roll['cluster']['unreachable']}",
            )
        )
        # bounded control-plane RPC: the retried call must fail within
        # its budget, never hang on the black hole. The wall-clock
        # bound is generous — under storm load the event loop itself
        # stalls for whole batches — but it is a BOUND, which is the
        # contract (the pre-PR behavior was an open-ended hang).
        t_call = time.monotonic()
        raised = False
        try:
            await main.call_retry(
                va, "node", "info", timeout=0.3, retries=1
            )
        except (Exception,):
            raised = True
        elapsed = time.monotonic() - t_call
        res.checks.append(
            Check(
                "rpc_bounded",
                raised and elapsed < 10.0,
                f"failed in {elapsed * 1e3:.0f}ms (bound 10s)",
            )
        )
        c1 = eng.counters()
        res.checks.append(
            Check(
                "rpc_retry_counted",
                c1.get("rpc_retry_total", 0) > c0.get("rpc_retry_total", 0)
                and c1.get("rpc_unreachable_total", 0)
                > c0.get("rpc_unreachable_total", 0),
                f"retries +{c1.get('rpc_retry_total', 0) - c0.get('rpc_retry_total', 0)}",
            )
        )
        # failure detection within the miss budget (each heartbeat
        # cycle = interval + ping timeout while black-holed)
        ms = main.membership
        budget = (
            (ms.heartbeat_interval + ms.ping_timeout)
            * (ms.miss_threshold + 2)
            + 3.0
        )
        down = await eng.wait_for(
            lambda: victim.node_id not in ms.members,
            timeout=budget,
        )
        res.checks.append(
            Check(
                "nodedown_detected",
                down is not None,
                f"{down:.2f}s (budget {budget:.1f}s)"
                if down is not None
                else f"not within {budget:.1f}s",
            )
        )
        if down is not None:
            eng.faults_detected += 1
            res.detect_ms = round(
                (time.monotonic() - t_inj) * 1e3, 2
            )
        # survivor purge: the dead node's contribution swept (batched)
        purged = await eng.wait_for(
            lambda: not any(
                n == victim.node_id for _f, n in main._cluster_pairs
            ),
            timeout=5.0,
        )
        res.checks.append(
            Check(
                "survivor_purged_routes",
                purged is not None,
                f"{vpairs} routes swept",
            )
        )
        # heal + rejoin + reconverge. With autoheal on, the heal probes
        # re-admit the peers and the coordinator directs the victim's
        # rejoin on their own — wait for that convergence instead of
        # racing it with a manual join. The manual join stays as the
        # fallback for autoheal-off runs.
        main.rpc.heal()
        victim.rpc.heal()
        t_heal = time.monotonic()
        converged = await eng.wait_for(
            lambda: victim.node_id in main.membership.members
            and main.node_id in victim.membership.members
            and not victim.membership.needs_rejoin,
            timeout=budget + eng.settle_timeout + 60.0,
        )
        if converged is None:
            await victim.join(ma)
        reconv = await eng.wait_for(
            lambda: sum(
                1 for _f, n in main._cluster_pairs if n == victim.node_id
            )
            >= vpairs,
            timeout=eng.settle_timeout + 30.0,
        )
        res.checks.append(
            Check(
                "rejoin_reconverged",
                reconv is not None,
                f"{vpairs} routes restored in "
                f"{(time.monotonic() - t_heal):.1f}s"
                if reconv is not None
                else "routes did not reconverge",
            )
        )
        res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        # the forward leg flows again
        if eng.victim_fleet is not None and reconv is not None:
            v0 = victim.broker.metrics.val("messages.delivered")
            await eng.burst(
                [eng.victim_fleet.topic_of(0, "postheal")]
            )
            flowed = await eng.wait_for(
                lambda: victim.broker.metrics.val("messages.delivered")
                > v0,
                timeout=3.0,
            )
            res.checks.append(
                Check(
                    "forward_leg_restored",
                    flowed is not None,
                    "cross-node delivery after heal",
                )
            )
        return res


class SplitBrain(Scenario):
    """Symmetric split under the live storm: both planes black-holed
    both ways, conflicting writes land on BOTH halves — fresh routes on
    each side plus the same client id claimed on each half. Contract:
    the victim (losing the lowest-id tie-break) declares itself the
    minority — alarm up, flight bundle frozen, rejoin flagged — while
    the majority keeps serving; on heal, autoheal reconverges WITHOUT
    manual intervention: routes from both halves visible everywhere,
    the registry conflict resolved to exactly one live session with a
    deterministic winner, and the final all-nodes digest sweep equal —
    zero silent divergence."""

    name = "split_brain"
    reference = (
        "ekka_autoheal: network split under load, majority-side "
        "heal + minority rejoin"
    )
    needs_cluster = True

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        main, victim = eng.node, eng.victim
        ma, va = main.rpc.listen_addr, victim.rpc.listen_addr
        ms, vms = main.membership, victim.membership
        t0w = time.time()
        c0 = CLUSTER_METRICS.snapshot()
        # lend the victim the shared flight store for the window so its
        # partition-entry forensics land somewhere inspectable
        victim.flight = eng.flight
        eng.reset_flight_cooldown("cluster_partition")
        fires0 = _fires(eng, "cluster_partition")
        vpairs = len(victim._local_refs)
        eng.record_fault(
            "split_brain", {"victim": victim.node_id, "routes": vpairs}
        )
        main.rpc.partition(va)
        victim.rpc.partition(ma)
        t_inj = time.monotonic()
        budget = (
            (ms.heartbeat_interval + ms.ping_timeout)
            * (ms.miss_threshold + 2)
            + 3.0
        )
        try:
            split = await eng.wait_for(
                lambda: victim.node_id not in ms.members
                and main.node_id not in vms.members
                and vms.minority,
                timeout=budget,
            )
            res.checks.append(
                Check(
                    "split_detected",
                    split is not None,
                    f"{split:.2f}s (budget {budget:.1f}s)"
                    if split is not None
                    else f"not within {budget:.1f}s",
                )
            )
            if split is not None:
                eng.faults_detected += 1
                res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
            res.checks.append(
                Check(
                    "victim_declared_minority",
                    vms.minority and vms.needs_rejoin,
                    f"minority={vms.minority} "
                    f"needs_rejoin={vms.needs_rejoin}",
                )
            )
            res.checks.append(
                Check(
                    "majority_not_minority",
                    not ms.minority,
                    "lowest-id half keeps serving",
                )
            )
            res.checks.append(
                Check(
                    "partition_alarm",
                    eng.victim_obs.alarms.is_active("cluster_partition"),
                    "cluster_partition active on the minority",
                )
            )
            res.checks.append(
                Check(
                    "partition_bundle",
                    _fires(eng, "cluster_partition") > fires0,
                    "flight bundle frozen on partition entry",
                )
            )
            # conflicting writes on BOTH halves while split: a fresh
            # route on each side, and the same client id on each side
            s_m, _ = main.broker.open_session("sb-main", True)
            s_m.outgoing_sink = _sink
            main.broker.subscribe(s_m, "sb/main/+", SubOpts(qos=0))
            s_v, _ = victim.broker.open_session("sb-victim", True)
            s_v.outgoing_sink = _sink
            victim.broker.subscribe(s_v, "sb/victim/+", SubOpts(qos=0))
            cid = "sb-claimant"
            cm, _ = main.broker.open_session(cid, True)
            cm.outgoing_sink = _sink
            main.broker.subscribe(cm, "sb/claim/+", SubOpts(qos=0))
            cv, _ = victim.broker.open_session(cid, True)
            cv.outgoing_sink = _sink
            victim.broker.subscribe(cv, "sb/claim/+", SubOpts(qos=0))
            # the majority half keeps absorbing the storm
            d0 = eng.delivered
            await asyncio.sleep(1.0)
            res.checks.append(
                Check(
                    "majority_serving_during_split",
                    eng.delivered > d0,
                    f"+{eng.delivered - d0} deliveries",
                )
            )
            # heal the wire: autoheal must do the rest on its own
            main.rpc.heal()
            victim.rpc.heal()
            t_heal = time.monotonic()
            healed = await eng.wait_for(
                lambda: victim.node_id in ms.members
                and main.node_id in vms.members
                and not vms.needs_rejoin
                and not vms.minority,
                timeout=budget + eng.settle_timeout + 60.0,
            )
            res.checks.append(
                Check(
                    "autoheal_reconverged",
                    healed is not None,
                    f"directed rejoin in "
                    f"{(time.monotonic() - t_heal):.1f}s"
                    if healed is not None
                    else "minority never rejoined",
                )
            )
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
            res.checks.append(
                Check(
                    "partition_alarm_cleared",
                    not eng.victim_obs.alarms.is_active(
                        "cluster_partition"
                    ),
                    "alarm deactivated on exit",
                )
            )
            # the zero-silent-divergence sweep: every node's full
            # contribution-digest map must be byte-equal
            dig = await eng.wait_for(
                lambda: main.replica_digests() == victim.replica_digests(),
                timeout=30.0,
            )
            res.checks.append(
                Check(
                    "digests_equal_all_nodes",
                    dig is not None,
                    "route-table digests byte-equal"
                    if dig is not None
                    else f"main={main.replica_digests()} "
                    f"victim={victim.replica_digests()}",
                )
            )
            # both halves' split-era routes visible everywhere
            routes_merged = await eng.wait_for(
                lambda: any(
                    f == "sb/main/+" and n == main.node_id
                    for f, n in victim._cluster_pairs
                )
                and any(
                    f == "sb/victim/+" and n == victim.node_id
                    for f, n in main._cluster_pairs
                ),
                timeout=15.0,
            )
            res.checks.append(
                Check(
                    "split_writes_merged",
                    routes_merged is not None,
                    "both halves' routes replicated after heal",
                )
            )
            # registry conflict: deterministic winner (lowest node id),
            # exactly one live session, loser kicked with a takeover
            main_live = (
                cid in main.broker.sessions
                and main.broker.sessions[cid].connected
            )
            victim_live = (
                cid in victim.broker.sessions
                and victim.broker.sessions[cid].connected
            )
            res.checks.append(
                Check(
                    "registry_conflict_resolved",
                    main_live and not victim_live,
                    f"live: main={main_live} victim={victim_live} "
                    f"(winner must be {main.node_id})",
                )
            )
            res.checks.append(
                Check(
                    "registry_agreement",
                    main.registry.get(cid) == main.node_id
                    and victim.registry.get(cid) == main.node_id,
                    f"main->{main.registry.get(cid)} "
                    f"victim->{victim.registry.get(cid)}",
                )
            )
            c1 = CLUSTER_METRICS.snapshot()
            res.checks.append(
                Check(
                    "conflicts_counted",
                    c1.get("registry_conflicts_total", 0)
                    > c0.get("registry_conflicts_total", 0),
                    f"+{c1.get('registry_conflicts_total', 0) - c0.get('registry_conflicts_total', 0)}",
                )
            )
            res.checks.append(
                Check(
                    "autoheal_counted",
                    c1.get("autoheal_rejoin_total", 0)
                    > c0.get("autoheal_rejoin_total", 0)
                    and c1.get("heal_total", 0) > c0.get("heal_total", 0),
                    f"rejoins +{c1.get('autoheal_rejoin_total', 0) - c0.get('autoheal_rejoin_total', 0)}",
                )
            )
            res.checks.append(_slo_check(eng, t0w))
            res.extra["silent_divergences"] = 0 if dig is not None else 1
            # clean up the scenario's sessions (the loser is gone)
            for b, s in (
                (main.broker, s_m),
                (victim.broker, s_v),
                (main.broker, cm),
            ):
                if s.client_id in b.sessions:
                    b.close_session(s, discard=True)
        finally:
            victim.flight = None
        return res


class AsymmetricPartition(Scenario):
    """One-way blackhole: the majority node drops every frame the
    victim sends it, while its own calls to the victim still flow. The
    victim declares the unreachable peer down and goes minority; the
    majority — which never lost contact — learns of the asymmetry from
    the victim's piggybacked view in ping replies, counts it, and the
    autoheal coordinator directs the rejoin over the working direction
    after heal."""

    name = "asymmetric_partition"
    reference = (
        "ekka partition handling: asymmetric netsplit (one-way "
        "iptables DROP)"
    )
    needs_cluster = True

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        main, victim = eng.node, eng.victim
        ms, vms = main.membership, victim.membership
        va = victim.rpc.listen_addr
        t0w = time.time()
        c0 = CLUSTER_METRICS.snapshot()
        eng.record_fault(
            "asymmetric_partition", {"blackhole": "victim->main inbound"}
        )
        # main drops inbound frames FROM the victim; main->victim flows
        main.rpc.partition(va, direction="in")
        t_inj = time.monotonic()
        # box-scaled (boxcal.py): the detect/heal polling around the
        # heartbeat rounds is interpreter-bound, and this scenario
        # straddles its budget on 1-core boxes
        budget = eng.scaled_timeout(
            (vms.heartbeat_interval + vms.ping_timeout)
            * (vms.miss_threshold + 2)
            + 3.0
        )
        asym = await eng.wait_for(
            lambda: main.node_id not in vms.members
            and vms.minority
            and victim.node_id in ms.members,
            timeout=budget,
        )
        res.checks.append(
            Check(
                "asymmetry_established",
                asym is not None,
                "victim lost main; main kept victim"
                if asym is not None
                else f"not within {budget:.1f}s",
            )
        )
        if asym is not None:
            eng.faults_detected += 1
            res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        # the healthy side SEES the asymmetry in the victim's replies
        counted = await eng.wait_for(
            lambda: CLUSTER_METRICS.snapshot().get("asymmetry_total", 0)
            > c0.get("asymmetry_total", 0),
            timeout=budget,
        )
        res.checks.append(
            Check(
                "asymmetry_counted",
                counted is not None,
                f"asym peers on main: {sorted(ms.asym_peers)}",
            )
        )
        # heal the one-way drop; coordinator directs the rejoin
        main.rpc.heal()
        t_heal = time.monotonic()
        healed = await eng.wait_for(
            lambda: main.node_id in vms.members
            and not vms.needs_rejoin
            and not vms.minority,
            timeout=budget + eng.settle_timeout + 60.0,
        )
        res.checks.append(
            Check(
                "autoheal_reconverged",
                healed is not None,
                f"rejoined in {(time.monotonic() - t_heal):.1f}s"
                if healed is not None
                else "victim wedged in minority",
            )
        )
        res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        dig = await eng.wait_for(
            lambda: main.replica_digests() == victim.replica_digests(),
            timeout=eng.scaled_timeout(30.0),
        )
        res.checks.append(
            Check(
                "digests_equal_all_nodes",
                dig is not None,
                "replicas reconverged",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        return res


class ReplicaDrift(Scenario):
    """The genuinely silent fault: one op batch is ACKed by the
    replica but never applied — no failed RPC, no nodedown, no signal
    on the push path at all. Contract: the digest exchange riding the
    ping path detects the divergence within a bounded number of rounds,
    repairs it with a targeted paged resync, counts both, and never
    escalates to a nodedown."""

    name = "replica_drift"
    reference = (
        "mria shard replay / emqx_router_helper route-consistency "
        "purge"
    )
    needs_cluster = True

    async def run(self, eng) -> ScenarioResult:
        from .faults import ReplicaDriftInjector

        res = ScenarioResult(self.name)
        main, victim = eng.node, eng.victim
        ms = main.membership
        t0w = time.time()
        c0 = CLUSTER_METRICS.snapshot()
        # let any scheduled full resyncs (join/member_up leftovers)
        # drain first: they flow through the resync leg, not the
        # wrapped push, and would repair the drift without
        # anti-entropy ever seeing it
        await eng.wait_for(
            lambda: not main._resync and not victim._resync,
            timeout=(ms.heartbeat_interval + ms.ping_timeout) * 3 + 5.0,
        )
        inj = ReplicaDriftInjector(victim)
        s = None
        try:
            inj.drop_next(1)
            eng.record_fault("replica_drift", {"victim": victim.node_id})
            t_inj = time.monotonic()
            # a fresh route announced on main: the push is ACKed by the
            # victim and silently discarded there
            s, _ = main.broker.open_session("drift-writer", True)
            s.outgoing_sink = _sink
            flt = "drift/probe/+"
            main.broker.subscribe(s, flt, SubOpts(qos=0))
            # a storm-loaded loop can time the push out on the SENDER
            # side, rerouting the ops through resync (an honest repair,
            # not a silent drop) — and a stable fleet offers no further
            # batches. Nudge fresh route ops until one batch actually
            # lands through the push leg the injector wraps.
            dropped = None
            for attempt in range(5):
                dropped = await eng.wait_for(
                    lambda: inj.dropped_batches >= 1,
                    timeout=(ms.heartbeat_interval + ms.ping_timeout) * 2
                    + 5.0,
                )
                if dropped is not None:
                    break
                main.broker.subscribe(
                    s, f"drift/probe/nudge{attempt}/+", SubOpts(qos=0)
                )
            res.checks.append(
                Check(
                    "drift_injected",
                    dropped is not None and inj.dropped_ops >= 1,
                    f"{inj.dropped_batches} batches "
                    f"({inj.dropped_ops} ops) silently dropped",
                )
            )
        finally:
            inj.uninstall()  # only the injected batch drifts
        res.checks.append(
            Check(
                "replicas_diverged",
                victim.replica_digests().get(main.node_id, 0)
                != main.replica_digests().get(main.node_id, 0)
                or main.replica_digests()
                == victim.replica_digests(),  # already repaired: fine
                "victim's copy of main's contribution drifted",
            )
        )
        # detection within a bounded number of ping rounds (the digest
        # exchange rides every ping; 2 consecutive mismatches count)
        # ping rounds are wall-time, but the polling/settle work around
        # them is interpreter-bound — box-scale the whole budget so a
        # 1-core box doesn't straddle it (boxcal.py discipline)
        budget = eng.scaled_timeout(
            (ms.heartbeat_interval + ms.ping_timeout) * 6 + 5.0
        )
        detected = await eng.wait_for(
            lambda: CLUSTER_METRICS.snapshot().get(
                "antientropy_divergence_total", 0
            )
            > c0.get("antientropy_divergence_total", 0),
            timeout=budget,
        )
        res.checks.append(
            Check(
                "detected_bounded",
                detected is not None,
                f"{detected:.2f}s (budget {budget:.1f}s)"
                if detected is not None
                else f"not within {budget:.1f}s",
            )
        )
        # repair is a full-contribution paged resync: the time bound
        # scales with the table being replayed (1M routes under storm
        # is minutes of transfer, not ping rounds)
        repair_budget = budget + eng.scaled_timeout(
            eng.settle_timeout + max(
                30.0, len(main._cluster_pairs) / 5_000.0
            )
        )
        repaired = await eng.wait_for(
            lambda: main.replica_digests() == victim.replica_digests()
            and CLUSTER_METRICS.snapshot().get(
                "antientropy_repairs_total", 0
            )
            > c0.get("antientropy_repairs_total", 0),
            timeout=repair_budget,
        )
        res.checks.append(
            Check(
                "detected_and_repaired_bounded",
                repaired is not None,
                f"{repaired:.2f}s (budget {repair_budget:.1f}s)"
                if repaired is not None
                else f"not within {repair_budget:.1f}s",
            )
        )
        if repaired is not None:
            eng.faults_detected += 1
            res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
            res.recovery_ms = res.detect_ms
        c1 = CLUSTER_METRICS.snapshot()
        res.checks.append(
            Check(
                "divergence_counted",
                c1.get("antientropy_divergence_total", 0)
                > c0.get("antientropy_divergence_total", 0)
                and c1.get("antientropy_checks_total", 0)
                > c0.get("antientropy_checks_total", 0),
                f"checks +{c1.get('antientropy_checks_total', 0) - c0.get('antientropy_checks_total', 0)}, "
                f"divergences +{c1.get('antientropy_divergence_total', 0) - c0.get('antientropy_divergence_total', 0)}, "
                f"repairs +{c1.get('antientropy_repairs_total', 0) - c0.get('antientropy_repairs_total', 0)}",
            )
        )
        # the repaired route actually serves on the replica
        res.checks.append(
            Check(
                "route_repaired",
                any(
                    f == flt and n == main.node_id
                    for f, n in victim._cluster_pairs
                ),
                f"{flt} present on the victim",
            )
        )
        # a single drift incident must never escalate
        res.checks.append(
            Check(
                "no_nodedown",
                victim.node_id in ms.members
                and main.node_id in victim.membership.members
                and c1.get("nodedown_total", 0)
                == c0.get("nodedown_total", 0),
                "membership untouched by the repair",
            )
        )
        res.checks.append(
            Check(
                "no_divergence_alarm",
                not eng.alarms.is_active("cluster_antientropy_divergence"),
                "one incident stays below the alarm threshold",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        if s is not None and s.client_id in main.broker.sessions:
            main.broker.close_session(s, discard=True)
        return res


class HealStorm(Scenario):
    """Flapping partitions: the wire splits and heals repeatedly. The
    contract is symmetry — every trip is matched by a heal (trips ==
    heals on the flapping node), the minority flag never wedges, and
    after the last heal the cluster is whole with byte-equal digests."""

    name = "heal_storm"
    reference = "ekka_autoheal: repeated netsplit/heal cycles"
    needs_cluster = True

    def __init__(self, flaps: int = 2):
        self.flaps = flaps

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        main, victim = eng.node, eng.victim
        ma, va = main.rpc.listen_addr, victim.rpc.listen_addr
        ms, vms = main.membership, victim.membership
        t0w = time.time()
        trips0, heals0 = vms.partition_trips, vms.partition_heals
        budget = (
            (vms.heartbeat_interval + vms.ping_timeout)
            * (vms.miss_threshold + 2)
            + 3.0
        )
        t_inj = time.monotonic()
        completed = 0
        for flap in range(self.flaps):
            eng.record_fault("heal_storm_flap", {"flap": flap})
            main.rpc.partition(va)
            victim.rpc.partition(ma)
            tripped = await eng.wait_for(
                lambda: vms.minority, timeout=budget
            )
            if tripped is not None:
                # the trip IS the detection: membership declared the
                # flap, matching this iteration's recorded fault
                eng.faults_detected += 1
                if res.detect_ms is None:
                    res.detect_ms = round(
                        (time.monotonic() - t_inj) * 1e3, 2
                    )
            main.rpc.heal()
            victim.rpc.heal()
            healed = await eng.wait_for(
                lambda: victim.node_id in ms.members
                and main.node_id in vms.members
                and not vms.needs_rejoin
                and not vms.minority,
                timeout=budget + eng.settle_timeout + 60.0,
            )
            if tripped is not None and healed is not None:
                completed += 1
        res.checks.append(
            Check(
                "flaps_completed",
                completed == self.flaps,
                f"{completed}/{self.flaps} trip+heal cycles",
            )
        )
        trips = vms.partition_trips - trips0
        heals = vms.partition_heals - heals0
        res.checks.append(
            Check(
                "trips_match_heals",
                trips == heals and trips >= self.flaps,
                f"trips={trips} heals={heals}",
            )
        )
        res.checks.append(
            Check(
                "no_wedged_minority",
                not vms.minority
                and not vms.needs_rejoin
                and not ms.minority,
                "all flags clear after the storm",
            )
        )
        res.checks.append(
            Check(
                "membership_whole",
                victim.node_id in ms.members
                and main.node_id in vms.members,
                "full view on both nodes",
            )
        )
        dig = await eng.wait_for(
            lambda: main.replica_digests() == victim.replica_digests(),
            timeout=30.0,
        )
        res.checks.append(
            Check(
                "digests_equal_all_nodes",
                dig is not None,
                "replicas identical after the flap storm",
            )
        )
        res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        res.checks.append(_slo_check(eng, t0w))
        return res


class NodeEvacuation(Scenario):
    """Evacuation drain + cross-node takeover: the victim stops taking
    connections and sheds the fleet at a bounded rate (v5
    USE_ANOTHER_SERVER); a sample of the shed clients reconnects on the
    survivor, which imports their sessions over the takeover RPC."""

    name = "node_evacuation"
    reference = "emqx_node_rebalance_evacuation_SUITE"
    needs_cluster = True

    def __init__(self, takeover_sample: int = 200):
        self.takeover_sample = takeover_sample

    async def run(self, eng) -> ScenarioResult:
        from ..cluster.rebalance import NodeEvacuation as Evac

        res = ScenarioResult(self.name)
        victim = eng.victim
        vfleet = eng.victim_fleet
        n0 = victim.broker.connected_count()
        ev = Evac(
            victim.broker,
            conn_evict_rate=max(2000, n0),
            server_reference="chaos-main",
        )
        t0 = time.monotonic()
        await ev.start()
        drained = await eng.wait_for(
            lambda: ev.status == "drained", timeout=15.0
        )
        res.checks.append(
            Check(
                "evacuation_drained",
                drained is not None
                and victim.broker.connected_count() == 0,
                f"{n0} connections in {(time.monotonic() - t0):.1f}s",
            )
        )
        await ev.stop()
        # takeover: shed clients land on the survivor and import state
        sample = vfleet.clients[: min(self.takeover_sample, len(vfleet.clients))]
        b = eng.broker
        for cid in sample:
            s, _present = b.open_session(
                cid, clean_start=False, cfg=vfleet.cfg
            )
            s.outgoing_sink = vfleet.sink
        # box-scaled settle budget (SOAK_r19 takeover_imported red
        # check): the fixed 10s window is tuned wall time — a slow box
        # finishing the identical import in 11.4s is not a failure
        imported = await eng.wait_for(
            lambda: all(
                cid in b.sessions and b.sessions[cid].subscriptions
                for cid in sample
            ),
            timeout=eng.scaled_timeout(eng.settle_timeout),
        )
        res.checks.append(
            Check(
                "takeover_imported",
                imported is not None,
                f"{len(sample)} sessions moved with subscriptions",
            )
        )
        await eng.settle()
        gone = await eng.wait_for(
            lambda: all(
                cid not in victim.broker.sessions for cid in sample
            ),
            timeout=eng.scaled_timeout(eng.settle_timeout),
        )
        res.checks.append(
            Check(
                "old_owner_released",
                gone is not None,
                "victim discarded moved sessions",
            )
        )
        owned = sum(
            1
            for cid in sample
            if eng.node.registry.get(cid) == eng.node.node_id
        )
        res.checks.append(
            Check(
                "registry_moved",
                owned == len(sample),
                f"{owned}/{len(sample)} owned by survivor",
            )
        )
        res.recovery_ms = round((time.monotonic() - t0) * 1e3, 2)
        res.extra["evacuated"] = n0
        return res


class NodePurge(Scenario):
    """Maintenance purge of the victim: every session discarded at a
    bounded rate; the survivor's replicated tables must retract the
    victim's contribution as the purge announces the deletes."""

    name = "node_purge"
    reference = "emqx_node_rebalance_purge_SUITE"
    needs_cluster = True

    async def run(self, eng) -> ScenarioResult:
        from ..cluster.rebalance import NodePurge as Purge

        res = ScenarioResult(self.name)
        victim = eng.victim
        n0 = len(victim.broker.sessions)
        purge = Purge(victim.broker, purge_rate=5000)
        t0 = time.monotonic()
        await purge.start()
        done = await eng.wait_for(
            lambda: purge.status == "purged", timeout=30.0
        )
        res.checks.append(
            Check(
                "purge_completed",
                done is not None and not victim.broker.sessions,
                f"{purge.purged} sessions in {(time.monotonic() - t0):.1f}s",
            )
        )
        await eng.settle()
        retracted = await eng.wait_for(
            lambda: not any(
                n == victim.node_id for _f, n in eng.node._cluster_pairs
            ),
            timeout=eng.scaled_timeout(eng.settle_timeout),
        )
        res.checks.append(
            Check(
                "survivor_retracted_routes",
                retracted is not None,
                "victim contribution gone from survivor replica",
            )
        )
        res.recovery_ms = round((time.monotonic() - t0) * 1e3, 2)
        res.extra["purged"] = purge.purged
        res.extra["sessions_before"] = n0
        return res


class TornWal(Scenario):
    """Power cut mid-append: the WAL's last record is half-written
    (torn). Reboot recovery must truncate at the last CRC-verified
    record — counting the torn frame — serve every previously
    acked-durable message, and never surface the half record as
    data."""

    name = "torn_wal"
    reference = (
        "RocksDB WAL kPointInTimeRecovery truncation; ra log CRC "
        "checked replay"
    )
    needs_durable = True

    async def run(self, eng) -> ScenarioResult:
        import os

        from ..ds.metrics import DS_METRICS
        from .faults import DiskFaultInjector

        res = ScenarioResult(self.name)
        t0w = time.time()
        err0 = eng.storm_errors
        # acked-durable baseline: in the WAL, fsynced, unconsumed
        pre = await eng.durable_publish(10)
        snap0 = DS_METRICS.snapshot()
        n_shards = eng.durable_db.storage.n_shards
        eng.record_fault(self.name, {"torn_bytes": 7, "shards": n_shards})
        t_inj = time.monotonic()
        # the process dies mid-append: kill, then plant the torn tail
        # (7 bytes of a 12-byte record header) on every shard WAL —
        # the on-disk state replay must truncate, engine-independent
        eng.ds_kill()
        for i in range(n_shards):
            DiskFaultInjector.tear_tail(
                os.path.join(
                    eng.data_dir, "ds", "chaos-messages", f"shard_{i}.kv"
                )
            )
        ms = await eng.ds_reboot()
        res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        res.recovery_ms = round(ms, 2)
        snap1 = DS_METRICS.snapshot()
        torn = int(
            snap1["wal_torn_records_total"] - snap0["wal_torn_records_total"]
        )
        if torn >= n_shards:
            # replay counted every planted torn tail: the injection
            # was detected, not silently served as data
            eng.faults_detected += 1
        res.checks.append(
            Check(
                "torn_tail_detected",
                torn >= n_shards,
                f"+{torn} torn records counted at replay "
                f"(one per shard WAL)",
            )
        )
        res.checks.append(
            Check(
                "crc_clean",
                snap1["wal_crc_failures_total"]
                == snap0["wal_crc_failures_total"],
                "a torn tail is torn, not a checksum failure",
            )
        )
        res.checks.append(
            Check(
                "no_shard_failed",
                not eng.durable_db.failed_shards(),
                "replay recovered without fail-stop",
            )
        )
        after = await eng.durable_drain()
        lost = [p for p in pre if p not in after]
        res.checks.append(
            Check(
                "zero_acked_loss",
                not lost,
                f"{len(lost)}/{len(pre)} acked-durable messages lost",
            )
        )
        post = await eng.durable_publish(4)
        served = await eng.durable_drain()
        res.checks.append(
            Check(
                "post_recovery_serving",
                set(post) <= set(served),
                f"{len(served)} delivered after reboot",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["acked_before_crash"] = len(pre)
        return res


class DiskFull(Scenario):
    """The disk fills (sticky ENOSPC on WAL appends) under the live
    storm: the touched shard must FAIL-STOP — alarm paged, flight
    bundle frozen, writes refused — while reads keep serving the
    committed data. Healing the disk, probe-verified recovery reopens
    the shard and writes flow again."""

    name = "disk_full"
    reference = (
        "RocksDB ENOSPC fail-stop (no silent retry); emqx alarm "
        "`disk_full` discipline"
    )
    needs_durable = True

    async def run(self, eng) -> ScenarioResult:
        from ..ds.metrics import DS_METRICS

        res = ScenarioResult(self.name)
        t0w = time.time()
        err0 = eng.storm_errors
        dinj = eng.disk_injector
        fires0 = _fires(eng, "ds_shard_failed")
        eng.reset_flight_cooldown("ds_shard_failed")
        pre = await eng.durable_publish(8)  # acked before the disk fills
        r0 = DS_METRICS.snapshot()["shard_recoveries_total"]
        dinj.fail_sticky(
            "enospc", legs=("append",), paths=("chaos-messages",)
        )
        eng.record_fault(self.name, {"kind": "enospc"})
        t_inj = time.monotonic()
        blocked = 0
        for _ in range(8):
            try:
                await eng.durable_publish(4)
            except OSError:
                blocked += 1
            if eng.durable_db.failed_shards():
                break
        failed = list(eng.durable_db.failed_shards())
        res.checks.append(
            Check(
                "fail_stop_engaged",
                bool(failed) and blocked >= 1,
                f"shards {failed} read-only, {blocked} flushes refused",
            )
        )
        if failed:
            eng.faults_detected += 1
            res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        alarm = f"ds_shard_failed_{failed[0]}" if failed else ""
        res.checks.append(
            Check(
                "alarm_raised",
                bool(failed)
                and (
                    eng.alarms.is_active(alarm)
                    or alarm in eng.alarms.fired_since(t0w)
                ),
                alarm,
            )
        )
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "ds_shard_failed") > fires0,
                "ds_shard_failed trigger fired",
            )
        )
        # read-only degraded service: committed data still pumps
        served = await eng.durable_drain()
        res.checks.append(
            Check(
                "reads_serve_while_failed",
                set(pre) <= set(served),
                f"{len(served)} committed messages delivered read-only",
            )
        )
        # heal -> probe-verified recovery -> alarm clears
        dinj.heal()
        recovered = await eng.ds_recover()
        res.checks.append(
            Check(
                "probe_verified_recovery",
                sorted(recovered) == sorted(failed)
                and not eng.durable_db.failed_shards(),
                f"recovered {recovered}",
            )
        )
        if recovered:
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        res.checks.append(
            Check(
                "alarm_cleared",
                not any(
                    eng.alarms.is_active(f"ds_shard_failed_{s}")
                    for s in range(eng.durable_db.storage.n_shards)
                ),
                "all ds_shard_failed alarms deactivated",
            )
        )
        r1 = DS_METRICS.snapshot()["shard_recoveries_total"]
        res.checks.append(
            Check(
                "recovery_accounted",
                r1 - r0 >= len(recovered) and len(recovered) >= 1,
                f"shard_recoveries_total +{int(r1 - r0)}",
            )
        )
        post = await eng.durable_publish(6)
        served = await eng.durable_drain()
        res.checks.append(
            Check(
                "post_recovery_serving",
                set(post) <= set(served),
                f"{len(served)} delivered after recovery",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        return res


class FsyncFail(Scenario):
    """ONE transient fsync failure: the fsyncgate loss mode. The
    kernel may already have dropped the dirty pages, so the shard must
    fail-stop on the FIRST failed fsync and refuse writes even though
    the disk is healthy again one op later — never retry-and-continue.
    Recovery is only via the probe-verified reopen+replay path."""

    name = "fsync_fail"
    reference = (
        "fsyncgate (PostgreSQL 2018): a failed fsync cannot be "
        "retried; reopen-and-replay is the only safe continuation"
    )
    needs_durable = True

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        t0w = time.time()
        err0 = eng.storm_errors
        dinj = eng.disk_injector
        fires0 = _fires(eng, "ds_shard_failed")
        eng.reset_flight_cooldown("ds_shard_failed")
        pre = await eng.durable_publish(8)
        # exactly ONE fsync fails; the disk is healthy afterwards
        dinj.fail_transient(
            1, kind="fsync", legs=("fsync",), paths=("chaos-messages",)
        )
        eng.record_fault(self.name, {"kind": "fsync", "transient": 1})
        t_inj = time.monotonic()
        raised = False
        try:
            await eng.durable_publish(4)
        except OSError:
            raised = True
        failed = list(eng.durable_db.failed_shards())
        res.checks.append(
            Check(
                "fail_stop_on_first_fsync_failure",
                raised and bool(failed),
                f"shards {failed} fail-stopped on one transient fsync",
            )
        )
        if failed:
            eng.faults_detected += 1
            res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        # the forbidden continuation: disk is healthy NOW, but the
        # shard must still refuse writes until probe-verified recovery
        blocked = False
        try:
            await eng.durable_publish(2)
        except OSError:
            blocked = True
        res.checks.append(
            Check(
                "no_retry_and_continue",
                blocked and dinj.healthy,
                "writes refused on healthy disk until recover()",
            )
        )
        alarm = f"ds_shard_failed_{failed[0]}" if failed else ""
        res.checks.append(
            Check(
                "alarm_raised",
                bool(failed)
                and (
                    eng.alarms.is_active(alarm)
                    or alarm in eng.alarms.fired_since(t0w)
                ),
                alarm,
            )
        )
        res.checks.append(
            Check(
                "flight_bundle_captured",
                _fires(eng, "ds_shard_failed") > fires0,
                "ds_shard_failed trigger fired",
            )
        )
        recovered = await eng.ds_recover()
        res.checks.append(
            Check(
                "probe_verified_recovery",
                sorted(recovered) == sorted(failed)
                and not eng.durable_db.failed_shards(),
                f"recovered {recovered} via reopen+replay+probe",
            )
        )
        if recovered:
            res.recovery_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        after = await eng.durable_drain()
        lost = [p for p in pre if p not in after]
        res.checks.append(
            Check(
                "zero_acked_loss",
                not lost,
                f"{len(lost)}/{len(pre)} acked-durable messages lost",
            )
        )
        post = await eng.durable_publish(4)
        served = await eng.durable_drain()
        res.checks.append(
            Check(
                "post_recovery_serving",
                set(post) <= set(served),
                f"{len(served)} delivered after recovery",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        return res


class BrokerRestart(Scenario):
    """SIGKILL → reboot → recover of the durable tier under the live
    storm. Contract: acked-durable-but-unconsumed messages all survive
    (zero acked loss), already-consumed messages are NOT redelivered
    (sessions resume at committed positions), the session fleet and
    its ps-routes rebuild, and recovery wall-time stays bounded."""

    name = "broker_restart"
    reference = (
        "emqx_durable_storage restart recovery: ra log replay / "
        "RocksDB WAL recovery into emqx_persistent_session_ds resume"
    )
    needs_durable = True

    async def run(self, eng) -> ScenarioResult:
        res = ScenarioResult(self.name)
        t0w = time.time()
        err0 = eng.storm_errors
        # batch A: acked-durable, delivered AND pubacked — the
        # committed-position ledger the reboot must respect
        batch_a = await eng.durable_publish(10)
        consumed = await eng.durable_drain()
        res.checks.append(
            Check(
                "pre_crash_delivery",
                set(batch_a) <= set(consumed),
                f"{len(consumed)} delivered+acked before the crash",
            )
        )
        # batch B: acked-durable (WAL-fsynced) but never consumed —
        # exactly the set a crash must not lose
        batch_b = await eng.durable_publish(10)
        eng.record_fault(self.name, {"acked_unconsumed": len(batch_b)})
        t_inj = time.monotonic()
        eng.ds_kill()
        ms = await eng.ds_reboot()
        res.detect_ms = round((time.monotonic() - t_inj) * 1e3, 2)
        res.recovery_ms = round(ms, 2)
        rec = eng.ds_recovery
        res.checks.append(
            Check(
                "recovery_bounded",
                ms < 30_000,
                f"reboot replay+resume in {ms:.0f}ms",
            )
        )
        shards = rec["db"]["shards"]
        replayed_clean = sum(
            s["replayed_records"] for s in shards
        ) > 0 and not any(s["failed"] for s in shards)
        if replayed_clean:
            # reboot replay found and recovered the killed WAL state:
            # the crash injection was detected by the recovery path
            eng.faults_detected += 1
        res.checks.append(
            Check(
                "wal_replayed_clean",
                replayed_clean,
                f"{sum(s['replayed_records'] for s in shards)} records "
                f"replayed across {len(shards)} shards",
            )
        )
        res.checks.append(
            Check(
                "sessions_resumed",
                rec["sessions"]["sessions"] >= eng.durable_sessions
                and rec["sessions"]["ps_routes"] >= eng.durable_sessions,
                f"{rec['sessions']['sessions']} sessions, "
                f"{rec['sessions']['ps_routes']} ps-routes rebuilt",
            )
        )
        after = await eng.durable_drain()
        lost = [p for p in batch_b if p not in after]
        res.checks.append(
            Check(
                "zero_acked_loss",
                not lost,
                f"{len(lost)}/{len(batch_b)} acked-durable messages lost",
            )
        )
        redelivered = [p for p in batch_a if p in after]
        res.checks.append(
            Check(
                "resumed_at_committed_positions",
                not redelivered,
                f"{len(redelivered)} consumed messages redelivered",
            )
        )
        batch_c = await eng.durable_publish(6)
        served = await eng.durable_drain()
        res.checks.append(
            Check(
                "post_recovery_serving",
                set(batch_c) <= set(served),
                f"{len(served)} delivered after reboot",
            )
        )
        res.checks.append(
            Check(
                "no_failed_shards",
                not eng.durable_db.failed_shards(),
                "all shards writable after reboot",
            )
        )
        res.checks.append(
            Check(
                "zero_publisher_errors",
                eng.storm_errors == err0,
                f"{eng.storm_errors - err0} storm chunks failed",
            )
        )
        res.checks.append(_slo_check(eng, t0w))
        res.extra["acked_unconsumed"] = len(batch_b)
        return res


def scenario_catalog(cluster: bool = True) -> List[Scenario]:
    """The ordered soak catalog. Destructive cluster scenarios run
    LAST (evacuation/purge consume the victim fleet); corruption runs
    early while the fleet is pristine so fan expectations are exact."""
    cat: List[Scenario] = [
        StormBaseline(),
        RowCorruption(faults=2),
        DeviceLoss(),
        DeviceFlap(),
        ChipLoss(),
        ChipFlap(),
        ReshardChurn(),
        TornWal(),
        DiskFull(),
        FsyncFail(),
        BrokerRestart(),
        DisconnectTakeover(),
    ]
    if cluster:
        cat += [
            PartitionNodedown(),
            ReplicaDrift(),
            AsymmetricPartition(),
            SplitBrain(),
            HealStorm(),
            NodeEvacuation(),
            NodePurge(),
        ]
    cat.append(SlotDecay())
    return cat


CATALOG = [
    StormBaseline.name,
    RowCorruption.name,
    DeviceLoss.name,
    DeviceFlap.name,
    ChipLoss.name,
    ChipFlap.name,
    ReshardChurn.name,
    TornWal.name,
    DiskFull.name,
    FsyncFail.name,
    BrokerRestart.name,
    DisconnectTakeover.name,
    PartitionNodedown.name,
    ReplicaDrift.name,
    AsymmetricPartition.name,
    SplitBrain.name,
    HealStorm.name,
    NodeEvacuation.name,
    NodePurge.name,
    SlotDecay.name,
]
