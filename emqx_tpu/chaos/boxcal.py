"""Box-throughput calibration for chaos/soak deadlines.

The SOAK_r19 red check (`node_evacuation.takeover_imported`) was a
measurement artifact, not a regression: the 10s settle window is a
wall-clock constant tuned on a fast dev box, while a 1-core CI box
finishes the identical takeover work in 11.4s. The same family of
flakes straddles the 30s per-test wall (`ds_replication` split-brain,
chaos drift/asymmetry) — the work always completes, the fixed budget
just doesn't fit the box.

`box_scale()` measures how much slower THIS box runs interpreter-bound
work than the reference box the budgets were tuned on: a ~20ms
pure-Python busy loop (the chaos settle paths are interpreter-bound,
so it is the right proxy), best-of-3 so a scheduler preemption cannot
masquerade as a slow box, cached per process, clamped to [1, 16] —
a budget never shrinks below its tuned wall value and never stretches
into uselessness. `ChaosEngine.scaled_timeout` and the tests' poll
deadlines multiply through it, the same discipline the replica_drift
repair budget already applies via its pair-count term.

Deliberately dependency-free (stdlib `time` only): tests/conftest.py
imports it at collection time, before jax or the broker tree loads.
"""

from __future__ import annotations

import time
from typing import Optional

# busy-loop iterations/second the reference box sustains (measured
# where the 10s/30s budgets were tuned); boxes at or above it get
# scale 1.0
NOMINAL_RATE = 6.0e6

# never stretch a budget past this — a box >16x slower than reference
# has problems no deadline policy fixes
MAX_SCALE = 16.0

_cached: Optional[float] = None


def _measure_rate() -> float:
    """Iterations/second of a ~20ms pure-Python arithmetic loop."""
    t0 = time.perf_counter()
    acc = 0
    n = 0
    while True:
        for i in range(10_000):
            acc = (acc * 1103515245 + i) & 0xFFFFFFFF
        n += 10_000
        dt = time.perf_counter() - t0
        if dt >= 0.02:
            return n / dt


def box_scale() -> float:
    """Deadline multiplier for this box, >= 1.0, cached per process.
    1.0 on a reference-speed (or faster) box; proportionally larger on
    slower ones, clamped to MAX_SCALE."""
    global _cached
    if _cached is None:
        rate = max(_measure_rate() for _ in range(3))
        _cached = min(MAX_SCALE, max(1.0, NOMINAL_RATE / rate))
    return _cached


def scaled(base: float) -> float:
    """`base` tuned-wall seconds stretched by the box scale."""
    return base * box_scale()
