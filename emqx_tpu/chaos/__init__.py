"""Chaos scenario engine — million-session soak judged by the sentinel.

Everything PRs 1-6 built *detects* production failure: the sentinel's
shadow-oracle audit, SLO burn-rate alarms, the flight recorder, the
quarantine/clean-sync recovery loop. Nothing *generated* production
failure conditions at the scale the ROADMAP targets — so until now the
detect→quarantine→recover chain had only ever fired against unit-test
miniatures. This package is the proof layer: it sustains 1M+
lightweight sessions through the real broker (real Session objects,
real Router routes, the real pipelined dispatch engine) and drives the
production failure catalog against them —

  * connect/subscribe/publish storms with Zipf topic skew,
  * mass-disconnect + session-takeover waves,
  * node purge / evacuation through cluster/rebalance.py,
  * cluster partition through the RPC plane's black-hole seam,
  * injected device-table row corruption (Router.chaos_corrupt_rows),
  * device-link faults at the XLA boundary (chaos/faults.py): transient
    kernel failures, sticky device loss, and stalled transfers — the
    conditions the dispatch engine's circuit breaker + host failover
    (device_loss / device_flap scenarios) must absorb invisibly,
  * shard-scoped chip faults on the multi-chip mesh (chip_loss /
    chip_flap / reshard_churn): one sub-axis column dies, the shard
    breaker evacuates its slice onto the survivor mesh (N-1 device
    service), and recovery rebalances back to the full mesh

— while the sentinel, SLO tracker, and flight recorder judge the
outcome. Every scenario declares an expected response contract and the
engine asserts it: SLOs hold *or* burn-rate alarms fire; corruption is
detected within one audit window, quarantine engages and auto-clears
on the next clean sync; flight bundles capture the anomaly;
`emqx_xla_audit_divergence_total` accounts for every injected fault;
the final state is audit-clean with zero *silent* divergence.

This is the analog of the reference's cross-app takeover / rebalance /
purge suites (SURVEY L1/L2): storm generators asserting the broker's
*response*, not just its steady state.

Entry points: `bench.py --soak` (the committed SOAK row) and
`python -m emqx_tpu.chaos` (standalone driver).
"""

from .engine import (  # noqa: F401
    ChaosEngine,
    ContractViolation,
    SessionFleet,
    ZipfTopics,
    run_soak,
)
from .faults import (  # noqa: F401
    DeviceDeadlineExceeded,
    DeviceFaultInjector,
    DeviceLinkError,
    DeviceLostError,
    TransientDeviceError,
)
from .scenarios import (  # noqa: F401
    CATALOG,
    Check,
    ChipFlap,
    ChipLoss,
    ReshardChurn,
    Scenario,
    ScenarioResult,
    scenario_catalog,
)
