"""The broker-internal message record (emqx_message.erl analog:
apps/emqx/src/emqx_message.erl #message{} ctor/flags/headers)."""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class Message:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    from_client: str = ""
    id: str = field(default_factory=lambda: uuid.uuid4().hex)
    timestamp: float = field(default_factory=time.time)
    props: Dict[str, object] = field(default_factory=dict)
    headers: Dict[str, object] = field(default_factory=dict)

    def expired(self, now: Optional[float] = None) -> bool:
        exp = self.props.get("message_expiry_interval")
        if exp is None:
            return False
        return (now if now is not None else time.time()) > self.timestamp + exp
