"""The broker core: subscribe/publish/dispatch over the Router.

The single-node analog of the reference hot path
(apps/emqx/src/emqx_broker.erl): subscribe writes routes
(emqx_broker.erl:159-198), publish runs the 'message.publish' hook
fold, stores retained, matches routes, dedups destinations, and
dispatches to sessions (emqx_broker.erl:253-298, 726-760); shared
groups elect one member (emqx_shared_sub.erl:144-163).

Destinations in the Router are:
    client_id                 — a direct subscriber session
    ("$group", group, filter) — a shared-subscription group

Publish offers two paths, exactly the v2 split the survey flags
(SURVEY.md §7 hard parts):
  * publish()        — single-message cut-through via the host trie;
  * publish_batch()  — the TPU path: one device dispatch matches the
    whole inbound batch (emqx_tpu.models.router.match_batch).
"""

from __future__ import annotations

import asyncio
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..models.retainer import Retainer
from ..models.router import Router
from ..models.shared_sub import SharedSubs
from ..obs.profiler import STAGE_MARK
from ..ops import topic as topic_mod
from .. import framec
from .hooks import Hooks
from .message import Message
from .metrics import Metrics, Stats
from .packet import Publish, SubOpts
from .session import Session

GROUP_DEST = "$group"

# subscribers per dispatch shard (ref: emqx_broker_helper.erl:60 — ≤1024
# subscribers on one topic dispatch inline, beyond that they shard)
FANOUT_SHARD = 1024

# exclusive subscriptions (ref: emqx_topic.erl:396-401 strips the
# prefix and flags is_exclusive; emqx_exclusive_subscription.erl claims)
EXCLUSIVE_PREFIX = "$exclusive/"


class ExclusiveTaken(Exception):
    """Another client holds the exclusive claim (-> RC 0x97)."""

# route match results flow through dispatch as (filter, dests) pairs;
# dests is a Dest -> refcount map owned by the Router
Pairs = Iterable[Tuple[str, Dict]]


class Broker:
    def __init__(
        self,
        max_levels: int = 16,
        shared_strategy: str = "random",
        hooks: Optional[Hooks] = None,
        mesh=None,
        fanout_cache_size: int = 4096,
        mesh_min_rows_per_shard: int = 0,
    ):
        # mesh_min_rows_per_shard: admission floor for sharded serving
        # (broker.perf.tpu_mesh_min_rows_per_shard) — below it the mesh
        # degrades to its first device; see ShardedDeviceTable
        self.router = Router(
            max_levels=max_levels, mesh=mesh,
            mesh_min_rows_per_shard=mesh_min_rows_per_shard,
        )
        self.shared = SharedSubs(strategy=shared_strategy)
        self.retainer = Retainer()
        self.hooks = hooks or Hooks()
        self.metrics = Metrics()
        self.stats = Stats()
        self.sessions: Dict[str, Session] = {}
        # capability limits advertised/enforced (emqx_mqtt_caps)
        from .caps import MqttCaps

        self.caps = MqttCaps()
        # exclusive-subscription claims: topic -> owning client
        # (emqx_exclusive_subscription mria set table); the cluster
        # layer replicates transitions through these callbacks
        self.exclusive: Dict[str, str] = {}
        self.on_exclusive_claimed = None  # fn(topic, client)
        self.on_exclusive_released = None  # fn(topic, client)
        # live listeners (Server instances register on start)
        self.servers: list = []
        # external tracing seam (emqx_external_trace provider): None
        # costs one attribute check per publish
        self.tracer = None
        # fanout plans: matched-filter-set -> (build clock, prebuilt
        # deduped delivery lists) — the ?SUBSCRIBER-bag precomputation,
        # emqx_broker.erl:126-140. Invalidation is PER FILTER: every
        # session/subscription mutation stamps the touched filter with
        # the next clock tick, and a plan is stale only when one of ITS
        # matched filters carries a newer stamp — a subscribe on filter
        # A leaves every disjoint filter B's plan intact (the old
        # single global generation orphaned all 4096 plans broker-wide
        # on any mutation; under connect churn that meant continuous
        # 100k-entry rebuilds). Stamps persist for filters that leave —
        # deleting one would resurrect older plans referencing it.
        self._fanout_cache: Dict[tuple, tuple] = {}
        self._fanout_clock = 0
        self._filter_stamp: Dict[str, int] = {}
        self._fanout_cap = fanout_cache_size
        # device-resolved fanout (ops/fanout.py): plan misses above
        # _fanout_min_fan dedup on device via the CSR dest store; below
        # it (or for host-resident filters) the Python walk is cheaper.
        # Boot wires broker.perf.tpu_fanout_{enable,min_fan} here.
        self._fanout_device = True
        self._fanout_min_fan = 1024
        self.router.dest_store.mem_class = Session
        self.router.fanout_opts_lookup = self._fanout_opts_lookup
        # (filter, client) subopts — mirror of ?SUBOPTION
        self.suboptions: Dict[Tuple[str, str], SubOpts] = {}
        # durable-session manager (emqx_persistent_session_ds seam);
        # attach with enable_durable()
        self.durable = None
        # pipelined micro-batching dispatcher; attach with
        # enable_dispatch_engine() (broker/dispatch_engine.py)
        self.engine = None
        # publish sentinel (obs/sentinel.py): shadow-oracle audit +
        # per-stage latency attribution + SLO burn alarms. None is the
        # probe-free default — the engine pays one attribute read
        self.sentinel = None

    def enable_dispatch_engine(self, **kw):
        """Attach a DispatchEngine (pipelined async publish path):
        concurrent publishes coalesce into one kernel dispatch behind
        the generation-stamped match cache. Idempotent per broker —
        repeat calls replace the knobs by building a fresh engine."""
        from .dispatch_engine import DispatchEngine

        self.engine = DispatchEngine(self, **kw)
        return self.engine

    def enable_durable(self, manager) -> None:
        """Wire a DurableSessionManager: installs the persist gate and
        routes qualifying sessions through DS (emqx_broker.erl:294,
        300-311 persist path)."""
        self.durable = manager
        manager.broker = self
        manager.install(self.hooks)

    # --- session registry (emqx_cm-lite) --------------------------------

    def open_session(
        self, client_id: str, clean_start: bool, cfg=None
    ) -> Tuple[Session, bool]:
        """Returns (session, session_present). Clean start discards
        (emqx_cm:open_session:285-304). Sessions with a nonzero expiry
        become durable when a DS manager is attached."""
        if (
            self.durable is not None
            and cfg is not None
            and cfg.session_expiry_interval > 0
            and cfg.durable is not False
        ):
            # an existing LIVE session under this id must be torn down
            # first or its routes leak and deliveries double up (the
            # close touches every filter the old session held, staling
            # exactly the plans that embedded it)
            prev = self.sessions.get(client_id)
            if prev is not None and not self._is_durable(prev):
                self.close_session(prev, discard=True)
            session, present = self.durable.open_session(client_id, clean_start, cfg)
            self.sessions[client_id] = session
            self.router.dest_store.note_session(client_id, session)
            self.stats.set("sessions.count", len(self.sessions))
            self.hooks.run(
                "session.resumed" if present else "session.created", client_id
            )
            return session, present
        old = self.sessions.get(client_id)
        if clean_start or old is None or old.expired():
            if old is not None:
                self.close_session(old, discard=True)
            s = Session(client_id, cfg)
            self.sessions[client_id] = s
            self.router.dest_store.note_session(client_id, s)
            self.stats.set("sessions.count", len(self.sessions))
            self.hooks.run("session.created", client_id)
            return s, False
        old.connected = True
        self.hooks.run("session.resumed", client_id)
        return old, True

    def close_session(self, session: Session, discard: bool = False) -> None:
        """Drop a session and all its routes (emqx_broker:subscriber_down)."""
        # re-entrancy guard: an admin kick closes the transport, whose
        # teardown calls back in here — the second call must be a no-op
        # (no duplicate terminated/discarded hooks)
        if self.sessions.get(session.client_id) is not session:
            return
        # stale every plan that embeds this session: stamp each filter
        # it subscribed (per-filter, so unrelated plans survive)
        for flt in session.subscriptions:
            self._mark_fanout(topic_mod.parse_share(flt)[1])
        # sever the transport (admin kick / takeover); harmless if the
        # teardown originated from the connection itself
        closer = getattr(session, "closer", None)
        if closer is not None:
            try:
                closer()
            except Exception:
                pass
        if self.durable is not None and self._is_durable(session):
            # shared-group routes live in the live router — release them
            for flt in list(session.subscriptions):
                if topic_mod.parse_share(flt)[0] is not None:
                    self._unsubscribe_route(session.client_id, flt)
                self.suboptions.pop((flt, session.client_id), None)
                self._release_exclusive(session.client_id, flt)
                # observers (cluster link, plugins) must see the
                # subscription END even when the whole session goes
                self.hooks.run("session.unsubscribed", session.client_id, flt)
            self.durable.discard_session(session.client_id)
            self.sessions.pop(session.client_id, None)
            self.router.dest_store.note_session(session.client_id, None)
            self.stats.set("sessions.count", len(self.sessions))
            self.stats.set("subscriptions.count", len(self.suboptions))
            self.hooks.run(
                "session.discarded" if discard else "session.terminated",
                session.client_id,
            )
            return
        # batch the direct-route deletes through ONE native
        # del_routes_core pass (Router.delete_routes) — session close
        # IS the route-churn steady state at millions of users
        # (disconnect storms, expiry sweeps, rebalance purges); shared
        # legs keep the per-filter group election
        cid = session.client_id
        pend_dels: List[Tuple[str, str]] = []
        for flt in list(session.subscriptions):
            group, real = topic_mod.parse_share(flt)
            if group is not None:
                if self.shared.unsubscribe(group, real, cid):
                    self.router.delete_route(
                        real, (GROUP_DEST, group, real)
                    )
            else:
                pend_dels.append((real, cid))
            self._release_exclusive(cid, flt)
            self.hooks.run("session.unsubscribed", cid, flt)
        if pend_dels:
            self.router.delete_routes(pend_dels)
        session.subscriptions.clear()
        self.sessions.pop(session.client_id, None)
        self.router.dest_store.note_session(session.client_id, None)
        self.stats.set("sessions.count", len(self.sessions))
        self.hooks.run(
            "session.discarded" if discard else "session.terminated",
            session.client_id,
        )

    # --- subscribe path --------------------------------------------------

    def subscribe(
        self,
        session: Session,
        flt: str,
        opts: SubOpts,
        retained_reader=None,
    ) -> List[Message]:
        """Register a subscription; returns retained messages to
        deliver (per retain_handling). `$exclusive/T` claims T for this
        client (raises ExclusiveTaken if another client holds it) and
        subscribes to the stripped topic, like the reference parse
        (emqx_topic.erl:396-401). `retained_reader` (real -> messages)
        lets the channel serve a whole SUBSCRIBE packet's retained
        lookups from ONE batched device dispatch (retained_read_begin
        launched before the subscribe loop)."""
        exclusive = flt.startswith(EXCLUSIVE_PREFIX)
        if exclusive:
            if not self.caps.exclusive_subscription:
                raise ValueError("exclusive subscriptions disabled")
            flt = flt[len(EXCLUSIVE_PREFIX):]
            if not flt:
                raise ValueError("empty exclusive topic")
        group, real = topic_mod.parse_share(flt)
        topic_mod.validate_filter(real)
        if exclusive:
            # claim only AFTER validation — a rejected subscribe must
            # not leave a claim nothing will ever release
            owner = self.exclusive.get(flt)
            if owner is not None and owner != session.client_id:
                raise ExclusiveTaken(flt)
            self.exclusive[flt] = session.client_id
            if self.on_exclusive_claimed is not None:
                # fire on RE-claims too: a client that moved nodes must
                # transfer claim OWNERSHIP to its new node (dup xadds
                # are idempotent on the cluster side)
                self.on_exclusive_claimed(flt, session.client_id)
        # durable sessions route through the ps-router + DS scheduler,
        # never the live router (emqx_persistent_session_ds model)
        if self.durable is not None and self._is_durable(session) and group is None:
            existed = self.durable.subscribe(session, flt, opts)
            self.suboptions[(flt, session.client_id)] = opts
            self._mark_fanout(real)
            self.stats.set("subscriptions.count", len(self.suboptions))
            self.hooks.run("session.subscribed", session.client_id, flt, opts)
            if opts.retain_handling == 2 or (opts.retain_handling == 1 and existed):
                return []
            return self._read_retained(real, retained_reader)
        existed = flt in session.subscriptions
        session.subscriptions[flt] = opts
        self.suboptions[(flt, session.client_id)] = opts
        self._mark_fanout(real)
        if group is not None:
            if self.shared.subscribe(group, real, session.client_id):
                self.router.add_route(real, (GROUP_DEST, group, real))
        else:
            if not existed:
                self.router.add_route(real, session.client_id)
            # stamp the CSR edge with the live suboption (covers
            # resubscribe-with-new-QoS, which has no route transition)
            self.router.fanout_note_opts(real, session.client_id, opts, session)
        self.stats.set("subscriptions.count", len(self.suboptions))
        self.hooks.run("session.subscribed", session.client_id, flt, opts)
        # retained delivery: never for shared subs (MQTT-5 §4.8.2)
        if group is not None:
            return []
        if opts.retain_handling == 2 or (opts.retain_handling == 1 and existed):
            return []
        return self._read_retained(real, retained_reader)

    def _read_retained(self, real: str, reader=None) -> List[Message]:
        """Retained lookup for one just-registered filter: the
        channel's batched reader when a SUBSCRIBE-packet window is
        open, else the device halves at B=1, else the host trie."""
        if reader is not None:
            return reader(real)
        retainer = self.retainer
        if retainer.device_enabled:
            begun = retainer.retained_read_begin([real])
            return retainer.retained_read_finish(begun)[0]
        return retainer.read(real)

    def unsubscribe(self, session: Session, flt: str) -> bool:
        if flt.startswith(EXCLUSIVE_PREFIX):
            flt = flt[len(EXCLUSIVE_PREFIX):]
        if flt not in session.subscriptions:
            return False
        group, real = topic_mod.parse_share(flt)
        self._mark_fanout(real)
        self._release_exclusive(session.client_id, flt)
        # shared subs always live in the live router, even for durable
        # sessions (the durable subscribe branch requires group None)
        is_shared = group is not None
        if self.durable is not None and self._is_durable(session) and not is_shared:
            self.durable.unsubscribe(session, flt)
            self.suboptions.pop((flt, session.client_id), None)
            self.stats.set("subscriptions.count", len(self.suboptions))
            self.hooks.run("session.unsubscribed", session.client_id, flt)
            return True
        del session.subscriptions[flt]
        self.suboptions.pop((flt, session.client_id), None)
        self._unsubscribe_route(session.client_id, flt)
        self.stats.set("subscriptions.count", len(self.suboptions))
        self.hooks.run("session.unsubscribed", session.client_id, flt)
        return True

    def connected_count(self) -> int:
        """Sessions with a live transport — ONE definition, shared by
        eviction, rebalance RPC, and telemetry."""
        return sum(
            1 for s in self.sessions.values() if getattr(s, "connected", False)
        )

    def _release_exclusive(self, client_id: str, flt: str) -> None:
        if self.exclusive.get(flt) == client_id:
            del self.exclusive[flt]
            if self.on_exclusive_released is not None:
                self.on_exclusive_released(flt, client_id)

    @staticmethod
    def _is_durable(session: Session) -> bool:
        from ..ds.session_ds import DurableSession

        return isinstance(session, DurableSession)

    def _unsubscribe_route(self, client_id: str, flt: str) -> None:
        group, real = topic_mod.parse_share(flt)
        if group is not None:
            if self.shared.unsubscribe(group, real, client_id):
                self.router.delete_route(real, (GROUP_DEST, group, real))
        else:
            self.router.delete_route(real, client_id)

    # --- publish path -----------------------------------------------------

    def publish(self, msg: Message) -> int:
        """Single-message cut-through (host trie). Returns deliveries."""
        if self.tracer is not None:
            return self._publish_traced(msg)
        # publish sentinel seam: the sync path matches host-side, but
        # the fanout PLAN it executes may be device-resolved — sampled
        # publishes audit that plan (and feed deliver-stage/SLO
        # attribution). Unsampled cost: one attribute read; one
        # counter tick when a sentinel is attached.
        st = self.sentinel
        span = st.maybe_span(msg) if st is not None else None
        msg = self._pre_publish(msg)
        if msg is None:
            return 0
        if span is None:
            return self._dispatch(msg, self.router.match_pairs(msg.topic))
        clock = self.router.telemetry.clock
        gen = self.router.generation
        pairs = self.router.match_pairs(msg.topic)
        t0 = clock()
        n = self._dispatch(msg, pairs, span=span)
        span.add("deliver", clock() - t0)
        st.finish_span(span)
        st.capture_audit(
            msg.topic, tuple(f for f, _ in pairs), pairs, gen,
            span.trace_id,
        )
        return n

    def _publish_traced(self, msg: Message) -> int:
        """The external-trace leg (emqx_external_trace.erl:29-123 /
        emqx_otel_trace spans around route + dispatch); lives off the
        None-tracer hot path entirely."""
        from ..obs.otel import trace_id_of

        tr = self.tracer
        tid = trace_id_of(msg)
        root = tr.start_span("mqtt.publish", tid, None)
        root.set("mqtt.topic", msg.topic).set("mqtt.qos", msg.qos)
        if msg.from_client:
            root.set("mqtt.clientid", msg.from_client)
        try:
            out = self._pre_publish(msg)
            if out is None:
                root.set("mqtt.dropped", True)
                return 0
            rs = tr.start_span("broker.route", tid, root)
            pairs = self.router.match_pairs(out.topic)
            rs.set("broker.matched_filters", len(pairs))
            tr.finish(rs)
            ds = tr.start_span("broker.dispatch", tid, root)
            out.headers["trace_root"] = root  # cluster leg parents here
            try:
                n = self._dispatch(out, pairs)
            finally:
                out.headers.pop("trace_root", None)
            ds.set("broker.deliveries", n)
            tr.finish(ds)
            root.set("mqtt.deliveries", n)
            return n
        finally:
            tr.finish(root)

    def publish_batch(self, msgs: Sequence[Message]) -> List[int]:
        """The TPU hot path: one batched device dispatch for the whole
        inbound publish batch. A device fault mid-batch fails over to
        the host walk (oracle-identical) instead of failing every
        coalesced publisher — the same failure-domain contract as the
        pipelined engine, for the synchronous surface (server
        PublishBatcher, cluster forward legs, bench)."""
        rb = getattr(self, "rule_batcher", None)
        if rb is not None and rb.batch_where_enabled:
            # batched-WHERE window: rule predicates hit in the publish
            # hooks defer into one columnar drain at window close
            with rb.batch_window():
                live = [self._pre_publish(m) for m in msgs]
        else:
            live = [self._pre_publish(m) for m in msgs]
        topics = [m.topic for m in live if m is not None]
        router = self.router
        try:
            filter_lists = router.match_filters_batch(topics)
            eng = self.engine
            if eng is not None:
                eng.note_device_success()
        except Exception as e:
            tel = router.telemetry
            if tel.enabled:
                tel.count("breaker_fallback_total", len(topics))
            eng = self.engine
            if eng is not None:
                eng.note_device_failure(e)
            filter_lists = [router.match_filters(t) for t in topics]
        results, _meta = self.dispatch_window(live, filter_lists)
        return results

    def dispatch_window(
        self,
        lives: Sequence[Optional[Message]],
        filter_lists,
        spans: Optional[Sequence] = None,
        capture_errors: bool = False,
    ):
        """Batch-at-a-time dispatch of one coalesced window — the
        delivery half of the vectorized publish path (the engine's ring
        collect and publish_batch both land here):

          * ONE matched-filter resolution and ONE fanout-plan probe per
            unique filter set in the window, not per publish;
          * publishes sharing a plan deliver through the grouped window
            walk (_deliver_plan_window): shared-buffer writes grouped
            per SESSION across the window's messages, and each
            session's QoS bookkeeping batched into one ledger call
            (Session.deliver_many);
          * sampled publishes (spans[i] not None) take the per-publish
            timed walk at their window position, so the stage
            decomposition contract survives batching; per-topic
            delivery order is preserved either way.

        `filter_lists` carries one matched-filter list per non-None
        live, in order (the match_filters_finish shape).  Returns
        (results, meta): results[i] is lives[i]'s delivery count (0
        where the hooks dropped it) or, when capture_errors, the
        exception that publish's future should fail with; meta[i] is
        (key, pairs) for the audit, shared across publishes that
        matched the same filter set."""
        fd = self.router.filter_dests
        results: List = [0] * len(lives)
        meta: List = [None] * len(lives)
        groups: Dict[tuple, List[int]] = {}
        pairs_by_key: Dict[tuple, list] = {}
        it = iter(filter_lists)
        for i, live in enumerate(lives):
            if live is None:
                continue
            flts = next(it)
            key = tuple(flts)
            g = groups.get(key)
            if g is None:
                pairs_by_key[key] = [(f, fd(f)) for f in key]
                groups[key] = g = []
            g.append(i)
            meta[i] = (key, pairs_by_key[key])
        clock = self.router.telemetry.clock
        for key, idxs in groups.items():
            pairs = pairs_by_key[key]
            # contiguous span-free publishes batch; a sampled publish
            # breaks the run so per-topic order survives
            runs: List[tuple] = []
            for i in idxs:
                if spans is not None and spans[i] is not None:
                    runs.append(("one", i))
                elif runs and runs[-1][0] == "batch":
                    runs[-1][1].append(i)
                else:
                    runs.append(("batch", [i]))
            for kind, val in runs:
                if kind == "one":
                    i = val
                    span = spans[i]
                    t0 = clock()
                    try:
                        n = self._dispatch(lives[i], pairs, span=span)
                    except Exception as e:
                        if not capture_errors:
                            raise
                        results[i] = e
                        continue
                    span.add("deliver", clock() - t0)
                    results[i] = n
                elif len(val) == 1:
                    i = val[0]
                    try:
                        results[i] = self._dispatch(lives[i], pairs)
                    except Exception as e:
                        if not capture_errors:
                            raise
                        results[i] = e
                else:
                    try:
                        self._dispatch_window_group(
                            [lives[i] for i in val], val, pairs, key,
                            results,
                        )
                    except Exception as e:
                        if not capture_errors:
                            raise
                        for i in val:
                            results[i] = e
        return results, meta

    def _dispatch_window_group(
        self,
        msgs: List[Message],
        idxs: List[int],
        pairs: Pairs,
        key: tuple,
        results: List,
    ) -> None:
        """Deliver a run of window publishes that share one matched
        filter set: shared-group election stays per message (each
        message elects its own member), the fanout plan resolves ONCE,
        and the direct fan walks the window grouped by session."""
        tel = self.router.telemetry
        shared_counts = [
            self._window_shared_leg(m, pairs, key) for m in msgs
        ]
        entry = self._fanout_cache.get(key)
        if entry is not None and self._plan_entry_fresh(entry, key):
            if tel.enabled:
                tel.count("fanout_plan_hits", len(msgs))
            try:
                fast = entry[2]
            except IndexError:
                fast = self._split_plan(entry[1])
        else:
            # the first publish pays the miss; the rest of the window
            # would have hit — keep the counters per-publish-equivalent
            if tel.enabled:
                tel.count(
                    "fanout_plan_stale" if entry is not None
                    else "fanout_plan_misses"
                )
                if len(msgs) > 1:
                    tel.count("fanout_plan_hits", len(msgs) - 1)
            clock = self._fanout_clock
            plan = self._resolve_plan(key, pairs)
            fast = self._split_plan(plan)
            self._fanout_cache_put(key, entry, clock, plan, fast)
        counts = [0] * len(msgs)
        self._fanout_window(msgs, fast, counts)
        nd_total = 0
        for j, i in enumerate(idxs):
            nd = counts[j]
            nd_total += nd
            self._account_dispatch(msgs[j], shared_counts[j] + nd)
            results[i] = shared_counts[j] + nd
        if nd_total:
            self.metrics.inc("messages.delivered", nd_total)

    def _window_shared_leg(self, msg: Message, pairs: Pairs, key: tuple) -> int:
        """The per-message leg a window group cannot batch: shared-group
        election here; ClusterBroker overrides this with its remote
        route (election is per message in both worlds)."""
        return self._dispatch_shared_local(msg, pairs, key)

    def _pre_publish(self, msg: Message) -> Optional[Message]:
        self.metrics.inc("messages.received")
        out = self.hooks.run_fold("message.publish", (), msg)
        if out is None or out.headers.get("allow_publish") is False:
            # a hook that intercepted the message (delayed-publish
            # store) is not a drop — it re-enters publish later
            if out is None or not out.headers.get("intercepted"):
                self.metrics.inc("messages.dropped")
                self.hooks.run("message.dropped", msg, "publish_denied")
            return None
        if out.retain:
            self.retainer.retain(out)
        return out

    def _dispatch(self, msg: Message, pairs: Pairs, span=None) -> int:
        # the matched-filter key is the cache identity for BOTH plan
        # families (shared legs + direct plan); build it once per
        # dispatch instead of once per consumer. A sampled publish
        # carries its StageSpan through here so the delivery walk
        # decomposes into DELIVERY_STAGES sub-stages; the span=None
        # path is byte-for-byte the old hot path.
        pairs = pairs if isinstance(pairs, list) else list(pairs)
        key = tuple(flt for flt, _ in pairs)
        if span is None:
            n = self._dispatch_shared_local(msg, pairs, key)
        else:
            clock = self.router.telemetry.clock
            t0 = clock()
            n = self._dispatch_shared_local(msg, pairs, key)
            # shared-group election rides the generic fan walk bucket
            span.add_sub("dispatch_loop", clock() - t0)
        nd = self._dispatch_direct(msg, pairs, key, span)
        if nd:
            self.metrics.inc("messages.delivered", nd)
        self._account_dispatch(msg, n + nd)
        return n + nd

    # --- fanout-plan cache (per-filter stamp invalidation) ---------------

    @property
    def _fanout_gen(self) -> int:
        """The monotonic mutation clock (kept under the historical name
        for introspection: it still bumps on every plan-relevant
        mutation, but plans no longer stale on it globally)."""
        return self._fanout_clock

    def _mark_fanout(self, real: str) -> None:
        """Stamp one (share-stripped) filter with the next clock tick:
        every cached plan whose matched set contains it is now stale;
        every other plan stays live."""
        self._fanout_clock += 1
        self._filter_stamp[real] = self._fanout_clock

    def _plan_entry_fresh(self, entry: tuple, filters) -> bool:
        """A plan built at entry's clock is stale only if one of ITS
        matched filters mutated since — len(filters) dict probes, not a
        global compare, so disjoint-filter churn never orphans it."""
        clock = entry[0]
        stamp = self._filter_stamp
        for f in filters:
            s = stamp.get(f)
            if s is not None and s > clock:
                return False
        return True

    def _plan_fresh(self, key: tuple) -> bool:
        """True when a current plan is cached for this filter set (the
        dispatch engine's probe before launching a device resolve)."""
        entry = self._fanout_cache.get(key)
        return entry is not None and self._plan_entry_fresh(entry, key)

    def _store_plan(self, key: tuple, clock: int, plan) -> None:
        self._fanout_cache_put(
            key, self._fanout_cache.get(key), clock, plan,
            self._split_plan(plan),
        )

    def _shared_group_dests(self, pairs: Pairs, key: tuple):
        """(group, real) legs in a match result. Cached per filter-set:
        scanning a 100k-dest fan for the (rare) group tuples on every
        publish cost more than the whole delivery loop."""
        skey = ("$shared", key)
        entry = self._fanout_cache.get(skey)
        if entry is not None and self._plan_entry_fresh(entry, key):
            return entry[1]
        clock = self._fanout_clock
        groups = []
        for _flt, dests in pairs:
            for dest in dests:
                if (
                    isinstance(dest, tuple)
                    and dest
                    and dest[0] == GROUP_DEST
                ):
                    groups.append((dest[1], dest[2]))
        self._fanout_cache_put(skey, entry, clock, groups)
        return groups

    def _fanout_cache_put(self, key, entry, clock, value, fast=None) -> None:
        """Insert a clock-stamped plan. A stale entry overwrites in
        place; at capacity ONE oldest-inserted entry evicts (O(1)
        FIFO) — never a wholesale clear. Direct-plan entries carry
        their derived broadcast split as a third element; shared-leg
        entries stay (clock, value)."""
        cache = self._fanout_cache
        if entry is None and len(cache) >= self._fanout_cap:
            del cache[next(iter(cache))]
        cache[key] = (clock, value) if fast is None else (clock, value, fast)

    def _account_dispatch(self, msg: Message, n: int) -> None:
        if n == 0:
            # a durable-only audience isn't a drop: the persist gate
            # stored the message and the DS pump will deliver it
            if self.durable is None or not self.durable.needs_persist(msg.topic):
                self.metrics.inc("messages.dropped.no_subscribers")
                self.hooks.run("message.dropped", msg, "no_subscribers")

    def _dispatch_shared_local(
        self, msg: Message, pairs: Pairs, key: tuple
    ) -> int:
        # snapshot via the cached plan: delivery hooks/sinks below may
        # (un)subscribe mid-iteration, which stamps the plan's filters
        # but leaves this list intact
        n = 0
        for group, real in self._shared_group_dests(pairs, key):
            # redispatch loop: a stale member (session gone) must not
            # eat the message — re-elect excluding it
            # (emqx_shared_sub:dispatch/4 retry + redispatch,
            # emqx_shared_sub.erl:149-163,217-244)
            tried: tuple = ()
            while True:
                member = self.shared.pick(
                    group,
                    real,
                    msg.topic,
                    from_client=msg.from_client,
                    exclude=tried,
                )
                if member is None:
                    break
                got = self._deliver_to(member, f"$share/{group}/{real}", msg)
                if got:
                    self.metrics.inc("messages.delivered", got)
                    n += got
                    break
                tried = tried + (member,)
        return n

    def _dispatch_direct(
        self, msg: Message, pairs: Pairs, key: tuple, span=None
    ) -> int:
        """Dedup direct destinations across matched filters (aggre/1,
        emqx_broker.erl:408-424): one delivery per client, max granted
        QoS wins — then execute a cached fanout PLAN. Identical
        filter-sets share one plan (keyed by matched filters, not the
        topic: a wildcard's whole topic space reuses it), stamped with
        the build clock and rebuilt lazily when one of ITS filters
        mutates — the precomputed ?SUBSCRIBER-bag read of
        emqx_broker.erl:726-760 rather than a per-publish suboption
        scan. Rebuilds above `_fanout_min_fan` run the device
        dedup/max-QoS kernel (ops/fanout.py); host-resident filter sets
        and small fans take the Python walk. Direct-plan cache entries
        carry a derived BROADCAST SPLIT (see _split_plan) built once
        per plan so the per-subscriber hot loop skips every
        per-delivery option test the plan already answers."""
        tel = self.router.telemetry
        t0 = tel.clock() if span is not None else 0.0
        entry = self._fanout_cache.get(key)
        if entry is not None and self._plan_entry_fresh(entry, key):
            if tel.enabled:
                tel.count("fanout_plan_hits")
            try:
                fast = entry[2]
            except IndexError:
                # legacy 2-tuple entry (chaos/sentinel tests overwrite
                # plans in place to inject divergence): derive the
                # split from the plan actually installed — the served
                # deliveries must follow the corrupted plan for the
                # audit to judge it
                fast = self._split_plan(entry[1])
            if span is not None:
                span.add_sub("plan_resolve", tel.clock() - t0)
            return self._fanout(msg, fast, span)
        if tel.enabled:
            tel.count("fanout_plan_stale" if entry is not None
                      else "fanout_plan_misses")
        clock = self._fanout_clock
        plan = self._resolve_plan(key, pairs)
        fast = self._split_plan(plan)
        self._fanout_cache_put(key, entry, clock, plan, fast)
        if span is not None:
            span.add_sub("plan_resolve", tel.clock() - t0)
        return self._fanout(msg, fast, span)

    @staticmethod
    def _split_plan(plan: tuple) -> tuple:
        """(bcast, rest, other): partition a plan's mem entries ONCE at
        build time into the trivially-broadcastable set — QoS 0 grant,
        no no_local, no retain-as-published, no QoS upgrade: their
        delivery is connected-check + shared-buffer write regardless of
        the message — and the rest, which keep the full per-delivery
        option walk. Everything that can invalidate the split
        (subscription/session mutations) already stamps the plan's
        filters, so the split lives exactly as long as its plan. The
        plan itself stays the oracle (mem, other) shape — audits and
        device/host equality checks never see the split."""
        mem, other = plan
        bcast = []
        rest = []
        for e in mem:
            opts = e[2]
            if (
                opts.qos == 0
                and not opts.no_local
                and not opts.retain_as_published
                and not e[1].cfg.upgrade_qos
            ):
                bcast.append(e)
            else:
                rest.append(e)
        return bcast, rest, other

    def _fanout_opts_lookup(self, flt: str, dest):
        """The CSR store's live-suboption seam (lazy segment rebuild):
        same reads as the oracle — suboptions for the word, sessions
        for the registry note."""
        opts = self.suboptions.get((flt, dest))
        if opts is None:
            return None
        return opts, self.sessions.get(dest)

    def _resolve_plan(self, key: tuple, pairs: Pairs) -> tuple:
        """Build the (mem, other) plan for a matched filter set —
        device kernel when eligible, else the host oracle walk. The
        two are bit-identical by contract (churn-oracle-tested)."""
        if self._fanout_device:
            router = self.router
            try:
                handle = router.resolve_fanout_begin(
                    key, min_fan=self._fanout_min_fan
                )
                if handle is not None:
                    plan = router.resolve_fanout_finish(handle)
                    eng = self.engine
                    if eng is not None:
                        eng.note_device_success()
                    return plan
            except Exception as e:
                # device fault on the synchronous resolve leg: the
                # host walk below is the oracle the kernel is
                # bit-identical to — serve it, count it, and let the
                # engine's breaker hear about the link
                tel = router.telemetry
                if tel.enabled:
                    tel.count("fanout_host_fallback_total")
                eng = self.engine
                if eng is not None:
                    eng.note_device_failure(e)
        return self._build_fanout_plan(pairs)

    def _build_fanout_plan(self, pairs: Pairs) -> tuple:
        """(mem_entries, other_entries): mem = live in-memory sessions
        eligible for the shared-packet QoS0 fast loop; other = durable
        or exotic sessions that always take session.deliver. Entries
        carry the session OBJECT — any mutation that could stale it
        bumps the fanout generation, orphaning every older stamp."""
        best: Dict[str, Tuple[str, SubOpts]] = {}
        subopts = self.suboptions
        for flt, dests in pairs:
            for dest in tuple(dests):
                if isinstance(dest, tuple) and dest and dest[0] == GROUP_DEST:
                    continue  # shared legs handled by group election
                opts = subopts.get((flt, dest))
                if opts is None:
                    continue
                cur = best.get(dest)
                if cur is None or opts.qos > cur[1].qos:
                    best[dest] = (flt, opts)
        mem: list = []
        other: list = []
        for client, (flt, opts) in best.items():
            session = self.sessions.get(client)
            if session is None:
                continue
            if session.__class__ is Session:
                mem.append((client, session, opts))
            else:
                other.append((client, flt, opts))
        return mem, other

    def _fanout(self, msg: Message, fast: tuple, span=None) -> int:
        """Wide-fanout sharding (the 1024 rule) over a split plan
        (_split_plan's (bcast, rest, other)): shard 0 delivers inline;
        later shards are scheduled as separate event-loop turns so a
        100k-subscriber topic cannot stall the loop for one long
        dispatch (the reference parallelizes shards across broker-pool
        workers, emqx_broker.erl:643-672,753-760). Returns deliveries
        INITIATED — deferred shards count at plan time.

        A sampled publish (span) takes the TIMED inline shard
        (_deliver_plan_timed — delivery-identical, sub-stage
        accounting added) and stamps its fan size; deferred shards
        always run the plain loop (they execute outside the span's
        deliver wall, so timing them would break sum-to-wall)."""
        bcast, rest, other = fast
        total = len(bcast) + len(rest) + len(other)
        if span is not None:
            span.fan += total
        pkt_cache: Dict[bool, tuple] = {}  # retain -> (pkt, (pkt,))
        if total <= FANOUT_SHARD:
            if span is not None:
                return self._deliver_plan_timed(
                    msg, fast, 0, total, pkt_cache, span
                )
            return self._deliver_plan(msg, fast, 0, total, pkt_cache)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if span is not None:
            n = self._deliver_plan_timed(
                msg, fast, 0, FANOUT_SHARD, pkt_cache, span
            )
        else:
            n = self._deliver_plan(msg, fast, 0, FANOUT_SHARD, pkt_cache)
        for i in range(FANOUT_SHARD, total, FANOUT_SHARD):
            hi = min(i + FANOUT_SHARD, total)
            if loop is None:
                n += self._deliver_plan(msg, fast, i, hi, pkt_cache)
            else:
                loop.call_soon(
                    self._deliver_plan, msg, fast, i, hi, pkt_cache
                )
                n += hi - i
        return n

    def _fanout_window(
        self, msgs: List[Message], fast: tuple, counts: List[int]
    ) -> None:
        """_fanout's window twin: shard the SESSION axis — each shard
        delivers the whole window's messages to a slice of the fan, so
        shard size shrinks with window width to keep per-turn delivery
        work bounded by the same ~FANOUT_SHARD write budget. counts[j]
        accumulates msgs[j]'s deliveries; deferred shards credit at
        plan time, exactly like _fanout's `hi - i`."""
        bcast, rest, other = fast
        total = len(bcast) + len(rest) + len(other)
        W = len(msgs)
        wctx: dict = {}
        per_shard = max(1, FANOUT_SHARD // W)
        if total <= per_shard:
            self._deliver_plan_window(msgs, fast, 0, total, wctx, counts)
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        self._deliver_plan_window(msgs, fast, 0, per_shard, wctx, counts)
        for i in range(per_shard, total, per_shard):
            hi = min(i + per_shard, total)
            if loop is None:
                self._deliver_plan_window(msgs, fast, i, hi, wctx, counts)
            else:
                loop.call_soon(
                    self._deliver_plan_window, msgs, fast, i, hi, wctx
                )
                step = hi - i
                for j in range(W):
                    counts[j] += step

    def _deliver_plan_window(
        self,
        msgs: List[Message],
        fast: tuple,
        lo: int,
        hi: int,
        wctx: dict,
        counts: Optional[List[int]] = None,
    ) -> None:
        """_deliver_plan's window twin: deliver a WINDOW of messages to
        split-plan slice [lo, hi), grouped by session instead of by
        message. The broadcast leg serializes the whole window into ONE
        joined buffer per protocol version and lands it with ONE socket
        write per subscriber; sessions that need real QoS bookkeeping
        take ONE Session.deliver_many (one batched ledger reserve) for
        the window instead of W deliver calls. Per-session packet order
        is submission order — the same per-topic ordering contract as W
        sequential _deliver_plan walks. counts is None on deferred
        shards (already credited at plan time)."""
        bcast, rest, other = fast
        mark = STAGE_MARK
        mark.stage = "dispatch_loop"
        run_hook = self.hooks.has("message.delivered")
        hooks_run = self.hooks.run_unobserved
        W = len(msgs)
        nb = len(bcast)
        if lo < nb:
            mark.stage = "session_write"
            pkts0 = wctx.get("pkts0")
            if pkts0 is None:
                pkts0 = []
                for m in msgs:
                    p = Publish(
                        topic=m.topic,
                        payload=m.payload,
                        qos=0,
                        retain=False,
                        packet_id=None,
                        props=dict(m.props),
                    )
                    p._wire = {}  # opt into serialize memoization
                    pkts0.append(p)
                wctx["pkts0"] = pkts0
                wctx["ptuple0"] = tuple(pkts0)
            ptuple0 = wctx["ptuple0"]
            wget = wctx.get
            last_ver = None
            data = None
            hit = 0
            for client, s, opts in bcast[lo:min(hi, nb)]:
                if s.connected:
                    sb = s.outgoing_sink_bytes
                    if sb is not None:
                        ver = s.sink_proto_ver
                        if ver is not last_ver:
                            data = wget(("b0", ver))
                            if data is None:
                                data = b"".join(
                                    framec.serialize(p, ver) for p in pkts0
                                )
                                wctx[("b0", ver)] = data
                            last_ver = ver
                        if run_hook:
                            for m in msgs:
                                hooks_run("message.delivered", client, m)
                        sb(data)
                        hit += 1
                        continue
                    if run_hook:
                        for m in msgs:
                            hooks_run("message.delivered", client, m)
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(ptuple0)
                    hit += 1
                    continue
                # disconnected broadcast subscriber: one batched
                # offline-queue decision for the whole window
                packets = s.deliver_many([(m, opts) for m in msgs])
                if run_hook:
                    for m in msgs:
                        hooks_run("message.delivered", client, m)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(packets)
                hit += 1
            if counts is not None and hit:
                for j in range(W):
                    counts[j] += hit
            mark.stage = "dispatch_loop"
        m_end = nb + len(rest)
        if hi > nb and lo < m_end:
            for client, s, opts in rest[max(lo - nb, 0):min(hi, m_end) - nb]:
                nl = opts.no_local
                items = []
                idx_js = []
                for j, m in enumerate(msgs):
                    if nl and m.from_client == client:
                        continue
                    items.append((m, opts))
                    idx_js.append(j)
                if not items:
                    continue
                packets = s.deliver_many(items)
                if run_hook:
                    for m, _o in items:
                        hooks_run("message.delivered", client, m)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(packets)
                if counts is not None:
                    for j in idx_js:
                        counts[j] += 1
        if hi > m_end:
            sessions_get = self.sessions.get
            for client, _flt, opts in other[max(lo - m_end, 0):hi - m_end]:
                session = sessions_get(client)
                if session is None:
                    continue
                nl = opts.no_local
                for j, m in enumerate(msgs):
                    if nl and m.from_client == client:
                        continue
                    # durable/exotic sessions keep the per-message
                    # deliver: subclasses override it (persist gates)
                    packets = session.deliver(m, opts)
                    if run_hook:
                        hooks_run("message.delivered", client, m)
                    if packets:
                        sink = getattr(session, "outgoing_sink", None)
                        if sink is not None:
                            sink(packets)
                    if counts is not None:
                        counts[j] += 1
        mark.stage = ""

    def _shared_pkt(self, msg: Message, retain: bool, pkt_cache) -> tuple:
        pkt = Publish(
            topic=msg.topic,
            payload=msg.payload,
            qos=0,
            retain=retain,
            packet_id=None,
            props=dict(msg.props),
        )
        pkt._wire = {}  # opt into serialize memoization
        cached = (pkt, (pkt,))
        pkt_cache[retain] = cached
        return cached

    def _deliver_plan(
        self,
        msg: Message,
        fast: tuple,
        lo: int,
        hi: int,
        pkt_cache: Dict[bool, tuple],
    ) -> int:
        """Deliver split-plan slice [lo, hi). The broadcast leg is THE
        delivery hot loop at scale (fanout_100k: every delivery is a
        plain QoS0 subscriber) so it carries nothing per-subscriber
        but: connected check, sink read, shared-buffer write — the
        option tests (no_local/QoS/upgrade/retain-as-published) were
        answered once at plan-split time, and the wire bytes serialize
        once per protocol version for the WHOLE fanout
        (frame.serialize memoizes on the shared packet)."""
        bcast, rest, other = fast
        n = 0
        # profiler stage marks (obs/profiler.STAGE_MARK): one store per
        # LEG, read by the sampling thread to bucket stacks. The bcast
        # leg is serialize+socket-write by construction, so it samples
        # as session_write; the mixed legs sample as dispatch_loop.
        mark = STAGE_MARK
        mark.stage = "dispatch_loop"
        run_hook = self.hooks.has("message.delivered")
        # per-delivery hookpoints are untimed by contract (obs/
        # flight_recorder UNTIMED_HOOKPOINTS): the probe-free runner
        # keeps the recorder's cost off the per-subscriber loop
        hooks_run = self.hooks.run_unobserved
        fr = msg.from_client
        mq = msg.qos
        nb = len(bcast)
        if lo < nb:
            mark.stage = "session_write"
            cached = pkt_cache.get(False)
            if cached is None:
                cached = self._shared_pkt(msg, False, pkt_cache)
            pkt_tuple = cached[1]
            cache_get = pkt_cache.get
            last_ver = None
            data = None
            for client, s, opts in bcast[lo:min(hi, nb)]:
                if s.connected:
                    sb = s.outgoing_sink_bytes
                    if sb is not None:
                        # bytes fast path: one buffer per proto
                        # version, written to every socket; version
                        # runs are contiguous in practice so the
                        # common case is two attribute reads + a call
                        ver = s.sink_proto_ver
                        if ver is not last_ver:
                            data = cache_get((ver, False))
                            if data is None:
                                data = framec.serialize(cached[0], ver)
                                pkt_cache[(ver, False)] = data
                            last_ver = ver
                        if run_hook:
                            hooks_run("message.delivered", client, msg)
                        sb(data)
                        n += 1
                        continue
                    if run_hook:
                        hooks_run("message.delivered", client, msg)
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(pkt_tuple)
                    n += 1
                    continue
                # disconnected broadcast subscriber: the session's own
                # deliver decides (offline queue / expiry), same as the
                # generic leg
                packets = s.deliver(msg, opts)
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(packets)
                n += 1
            mark.stage = "dispatch_loop"
        m = nb + len(rest)
        if hi > nb and lo < m:
            for client, s, opts in rest[max(lo - nb, 0):min(hi, m) - nb]:
                if opts.no_local and fr == client:
                    continue
                if (
                    s.connected
                    and (mq == 0 or opts.qos == 0)
                    and not s.cfg.upgrade_qos
                ):
                    retain = msg.retain if opts.retain_as_published else False
                    cached = pkt_cache.get(retain)
                    if cached is None:
                        cached = self._shared_pkt(msg, retain, pkt_cache)
                    if run_hook:
                        hooks_run("message.delivered", client, msg)
                    sb = s.outgoing_sink_bytes
                    if sb is not None:
                        ver = s.sink_proto_ver
                        data = pkt_cache.get((ver, retain))
                        if data is None:
                            data = framec.serialize(cached[0], ver)
                            pkt_cache[(ver, retain)] = data
                        sb(data)
                    else:
                        sink = s.outgoing_sink
                        if sink is not None:
                            sink(cached[1])
                    n += 1
                    continue
                packets = s.deliver(msg, opts)
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        sink(packets)
                n += 1
        if hi > m:
            for client, flt, opts in other[max(lo - m, 0):hi - m]:
                session = self.sessions.get(client)
                if session is None:
                    continue
                if opts.no_local and fr == client:
                    continue
                packets = session.deliver(msg, opts)
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = getattr(session, "outgoing_sink", None)
                    if sink is not None:
                        sink(packets)
                n += 1
        mark.stage = ""
        return n

    def _deliver_plan_timed(
        self,
        msg: Message,
        fast: tuple,
        lo: int,
        hi: int,
        pkt_cache: Dict[bool, tuple],
        span,
    ) -> int:
        """_deliver_plan with sub-stage accounting, run ONLY for the
        inline shard of a sampled publish (1/sample_n) — the unsampled
        hot loop above stays untouched. Delivery semantics are
        mirror-identical by contract (tests/test_delivery_stages.py
        drives both against the same plan and asserts identical sink
        output); the additions are clock pairs around the write calls
        (session_write: serialize + sink/socket writes) and the
        session.deliver calls (ack_sweep: QoS1/2 inflight
        bookkeeping), with dispatch_loop taking the residual of the
        measured leg wall — so the three sub-stages sum to this
        shard's wall exactly."""
        clock = self.router.telemetry.clock
        t_leg = clock()
        sw = 0.0  # session_write accumulator
        ack = 0.0  # ack_sweep accumulator
        bcast, rest, other = fast
        n = 0
        run_hook = self.hooks.has("message.delivered")
        hooks_run = self.hooks.run_unobserved
        fr = msg.from_client
        mq = msg.qos
        nb = len(bcast)
        if lo < nb:
            cached = pkt_cache.get(False)
            if cached is None:
                cached = self._shared_pkt(msg, False, pkt_cache)
            pkt_tuple = cached[1]
            cache_get = pkt_cache.get
            last_ver = None
            data = None
            for client, s, opts in bcast[lo:min(hi, nb)]:
                if s.connected:
                    sb = s.outgoing_sink_bytes
                    if sb is not None:
                        if run_hook:
                            hooks_run("message.delivered", client, msg)
                        t0 = clock()
                        ver = s.sink_proto_ver
                        if ver is not last_ver:
                            data = cache_get((ver, False))
                            if data is None:
                                data = framec.serialize(cached[0], ver)
                                pkt_cache[(ver, False)] = data
                            last_ver = ver
                        sb(data)
                        sw += clock() - t0
                        n += 1
                        continue
                    if run_hook:
                        hooks_run("message.delivered", client, msg)
                    sink = s.outgoing_sink
                    if sink is not None:
                        t0 = clock()
                        sink(pkt_tuple)
                        sw += clock() - t0
                    n += 1
                    continue
                t0 = clock()
                packets = s.deliver(msg, opts)
                ack += clock() - t0
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        t0 = clock()
                        sink(packets)
                        sw += clock() - t0
                n += 1
        m = nb + len(rest)
        if hi > nb and lo < m:
            for client, s, opts in rest[max(lo - nb, 0):min(hi, m) - nb]:
                if opts.no_local and fr == client:
                    continue
                if (
                    s.connected
                    and (mq == 0 or opts.qos == 0)
                    and not s.cfg.upgrade_qos
                ):
                    retain = msg.retain if opts.retain_as_published else False
                    cached = pkt_cache.get(retain)
                    if cached is None:
                        cached = self._shared_pkt(msg, retain, pkt_cache)
                    if run_hook:
                        hooks_run("message.delivered", client, msg)
                    t0 = clock()
                    sb = s.outgoing_sink_bytes
                    if sb is not None:
                        ver = s.sink_proto_ver
                        data = pkt_cache.get((ver, retain))
                        if data is None:
                            data = framec.serialize(cached[0], ver)
                            pkt_cache[(ver, retain)] = data
                        sb(data)
                    else:
                        sink = s.outgoing_sink
                        if sink is not None:
                            sink(cached[1])
                    sw += clock() - t0
                    n += 1
                    continue
                t0 = clock()
                packets = s.deliver(msg, opts)
                ack += clock() - t0
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = s.outgoing_sink
                    if sink is not None:
                        t0 = clock()
                        sink(packets)
                        sw += clock() - t0
                n += 1
        if hi > m:
            for client, flt, opts in other[max(lo - m, 0):hi - m]:
                session = self.sessions.get(client)
                if session is None:
                    continue
                if opts.no_local and fr == client:
                    continue
                t0 = clock()
                packets = session.deliver(msg, opts)
                ack += clock() - t0
                if run_hook:
                    hooks_run("message.delivered", client, msg)
                if packets:
                    sink = getattr(session, "outgoing_sink", None)
                    if sink is not None:
                        t0 = clock()
                        sink(packets)
                        sw += clock() - t0
                n += 1
        span.add_sub("session_write", sw)
        span.add_sub("ack_sweep", ack)
        span.add_sub(
            "dispatch_loop", max(0.0, clock() - t_leg - sw - ack)
        )
        return n

    def _deliver_shard(
        self,
        msg: Message,
        entries: List[Tuple[str, Tuple[str, SubOpts]]],
        pkt_cache: Optional[Dict[bool, Publish]] = None,
    ) -> int:
        """Deliver one shard. Trivial-QoS0 deliveries (connected mem
        session, effective QoS 0) share ONE Publish packet per retain
        flag, carried in pkt_cache ACROSS shards; its wire form is
        serialized once per protocol version (frame.serialize memoizes
        on the packet) — the fanout hot loop writes the same bytes to
        every socket instead of re-serializing per subscriber."""
        n = 0
        if pkt_cache is None:
            pkt_cache = {}
        for client, (flt, opts) in entries:
            session = self.sessions.get(client)
            if session is None:
                continue
            if (
                session.__class__ is Session
                and session.connected
                and min(msg.qos, opts.qos) == 0
                and not session.cfg.upgrade_qos
            ):
                if opts.no_local and msg.from_client == client:
                    continue
                n += 1
                self.hooks.run_unobserved("message.delivered", client, msg)
                retain = msg.retain if opts.retain_as_published else False
                shared_pkt = pkt_cache.get(retain)
                if shared_pkt is None:
                    shared_pkt = Publish(
                        topic=msg.topic,
                        payload=msg.payload,
                        qos=0,
                        retain=retain,
                        packet_id=None,
                        props=dict(msg.props),
                    )
                    shared_pkt._wire = {}  # opt into serialize memoization
                    pkt_cache[retain] = shared_pkt
                sink = getattr(session, "outgoing_sink", None)
                if sink is not None:
                    sink([shared_pkt])
                continue
            if opts.no_local and msg.from_client == client:
                continue
            packets = session.deliver(msg, opts)
            self.hooks.run_unobserved("message.delivered", client, msg)
            if packets:
                sink = getattr(session, "outgoing_sink", None)
                if sink is not None:
                    sink(packets)
            n += 1
        return n

    def deliver_replayed(self, client_id: str, msg: Message) -> int:
        """Deliver one replayed message to a specific client by
        re-matching its own subscriptions (takeover import: the message
        was already matched on the old owner, so this is a per-client
        re-match, not a route lookup; max granted QoS wins)."""
        session = self.sessions.get(client_id)
        if session is None:
            return 0
        best: Optional[SubOpts] = None
        tw = topic_mod.words(msg.topic)
        for flt, opts in session.subscriptions.items():
            group, real = topic_mod.parse_share(flt)
            if topic_mod.match(tw, topic_mod.words(real)):
                if best is None or opts.qos > best.qos:
                    best = opts
        if best is None:
            return 0
        packets = session.deliver(msg, best)
        self.hooks.run_unobserved("message.delivered", client_id, msg)
        if packets:
            sink = getattr(session, "outgoing_sink", None)
            if sink is not None:
                sink(packets)
        return 1

    def _deliver_to(
        self, client_id: str, share_filter: str, msg: Message
    ) -> int:
        """Shared-group leg: subopts key is the full $share filter."""
        session = self.sessions.get(client_id)
        if session is None:
            return 0
        opts = session.subscriptions.get(share_filter)
        if opts is None:
            return 0
        packets = session.deliver(msg, opts)
        self.hooks.run_unobserved("message.delivered", client_id, msg)
        if packets:
            sink = getattr(session, "outgoing_sink", None)
            if sink is not None:
                sink(packets)
        return 1
