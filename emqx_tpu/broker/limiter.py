"""Rate limiting + overload protection.

The reference enforces hierarchical token-bucket limits at two choke
points — connection accept (esockd limiter + `max_conn_rate`) and the
channel publish path (`emqx_channel.erl:751-768` `ensure_quota` /
?LIMITER_ROUTING, buckets from apps/emqx/src/emqx_limiter/src/
emqx_htb_limiter.erl) — and sheds load under scheduler pressure via
emqx_olp.erl (lc runq flagman backing off new connections).

The asyncio-era design here:

* `TokenBucket` — pure, monotonic-time token bucket.  `rate` is
  tokens/second, `burst` extra capacity on top of one second's worth
  (matching the reference's `rate`/`burst` bucket schema fields).
* `Limiter` — a chain of buckets consumed atomically (client tier →
  listener tier → node tier, the htb hierarchy flattened: a consume
  succeeds only if every tier grants, else reports the longest wait).
  Failed consumes do NOT debit any tier (no partial takes).
* Connections `await limiter.acquire(...)` before processing inbound
  PUBLISH frames — backpressure pauses the socket read loop, which is
  exactly the reference semantics of a rate-limited connection process
  hibernating (emqx_connection.erl activeN/rate-limit).
* `LoadShedder` — event-loop-lag flagman.  A sampler task measures
  scheduling drift; while the EWMA exceeds the threshold, new
  connections are refused at accept (emqx_olp's new-conn backoff) —
  never established flows, which keeps existing service degradation
  graceful.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence

INF = float("inf")


def _rate(v) -> float:
    """Schema value -> tokens/s ('infinity' | number)."""
    if v in (None, "infinity"):
        return INF
    return float(v)


class TokenBucket:
    """Monotonic-clock token bucket: capacity = rate*1s + burst."""

    __slots__ = ("rate", "capacity", "tokens", "_last")

    def __init__(self, rate: float, burst: float = 0.0) -> None:
        self.rate = rate
        self.capacity = INF if rate == INF else rate + (burst or 0.0)
        self.tokens = self.capacity
        self._last = time.monotonic()

    def _refill(self, now: float) -> None:
        if self.rate == INF:
            return
        self.tokens = min(self.capacity, self.tokens + (now - self._last) * self.rate)
        self._last = now

    def peek(self, n: float, now: Optional[float] = None) -> float:
        """0.0 if n tokens are available now, else seconds to wait."""
        if self.rate == INF:
            return 0.0
        self._refill(now if now is not None else time.monotonic())
        if self.tokens >= n:
            return 0.0
        if self.rate <= 0:
            return INF
        return (n - self.tokens) / self.rate

    def take(self, n: float) -> None:
        """Debit unconditionally (caller peeked first)."""
        if self.rate != INF:
            self.tokens -= n


class Limiter:
    """An atomically-consumed chain of buckets (htb tiers flattened)."""

    def __init__(self, buckets: Sequence[TokenBucket]) -> None:
        self.buckets = [b for b in buckets if b.rate != INF]

    def check(self, n: float = 1.0) -> float:
        """0.0 and debit if every tier grants; else the wait in
        seconds with nothing debited.  INF means unsatisfiable: n
        exceeds some tier's capacity, so no amount of waiting helps."""
        if not self.buckets:
            return 0.0
        now = time.monotonic()
        wait = 0.0
        for b in self.buckets:
            if n > b.capacity:
                return INF
            wait = max(wait, b.peek(n, now))
        if wait > 0.0:
            return wait
        for b in self.buckets:
            b.take(n)
        return 0.0

    async def acquire(self, n: float = 1.0, max_wait: float = 60.0) -> bool:
        """Await until n tokens are granted (pausing the caller — the
        socket read loop), or return False immediately for an
        unsatisfiable request / once max_wait is exceeded."""
        waited = 0.0
        while True:
            w = self.check(n)
            if w == 0.0:
                return True
            if w == INF or waited + w > max_wait:
                return False
            await asyncio.sleep(min(w, 1.0))
            waited += min(w, 1.0)


class ListenerLimits:
    """Per-listener enforcement state built from the config's limiter
    section.  Node-wide tiers are caller-provided shared buckets: the
    boot layer builds one {"messages_rate": TokenBucket, ...} dict and
    passes the SAME dict as `node_tier` to every listener's limits so
    the node quota is consumed jointly."""

    def __init__(
        self,
        max_conn_rate=None,
        messages_rate=None,
        bytes_rate=None,
        client: Optional[dict] = None,
        node_tier: Optional[Dict[str, TokenBucket]] = None,
    ) -> None:
        self.conn_bucket = TokenBucket(_rate(max_conn_rate))
        self.msg_bucket = TokenBucket(_rate(messages_rate))
        self.byte_bucket = TokenBucket(_rate(bytes_rate))
        self.client_cfg = client or {}
        self.node_tier = node_tier or {}

    @classmethod
    def from_config(
        cls, cfg: dict, node_tier: Optional[Dict[str, TokenBucket]] = None
    ) -> "ListenerLimits":
        """cfg = the checked `limiter` section of the broker schema;
        node_tier = the node-wide shared buckets (one dict per node)."""
        cfg = cfg or {}
        return cls(
            max_conn_rate=cfg.get("max_conn_rate"),
            messages_rate=cfg.get("messages_rate"),
            bytes_rate=cfg.get("bytes_rate"),
            client=cfg.get("client"),
            node_tier=node_tier,
        )

    def accept_allowed(self) -> bool:
        """Connection-accept gate (esockd max_conn_rate analog)."""
        if self.conn_bucket.peek(1.0) > 0.0:
            return False
        self.conn_bucket.take(1.0)
        return True

    def publish_limiter(self) -> Limiter:
        """Message-count limiter chain for one connection."""
        tiers: List[TokenBucket] = []
        c = self.client_cfg.get("messages_rate")
        if c:
            tiers.append(TokenBucket(_rate(c.get("rate")), c.get("burst") or 0.0))
        tiers.append(self.msg_bucket)
        nb = self.node_tier.get("messages_rate")
        if nb is not None:
            tiers.append(nb)
        return Limiter(tiers)

    def bytes_limiter(self) -> Limiter:
        tiers: List[TokenBucket] = []
        c = self.client_cfg.get("bytes_rate")
        if c:
            tiers.append(TokenBucket(_rate(c.get("rate")), c.get("burst") or 0.0))
        tiers.append(self.byte_bucket)
        nb = self.node_tier.get("bytes_rate")
        if nb is not None:
            tiers.append(nb)
        return Limiter(tiers)


class LoadShedder:
    """Event-loop-lag flagman (emqx_olp analog).

    Samples scheduling drift: asks the loop to wake after `interval`
    and measures how late the wakeup lands.  EWMA above `threshold`
    sets `overloaded`; the server then refuses NEW connections while
    established ones keep full service."""

    def __init__(
        self,
        threshold: float = 0.05,
        interval: float = 0.1,
        alpha: float = 0.3,
    ) -> None:
        self.threshold = threshold
        self.interval = interval
        self.alpha = alpha
        self.lag_ewma = 0.0
        self.shed_count = 0
        self._task: Optional[asyncio.Task] = None
        self._forced: Optional[bool] = None  # tests pin the state

    @property
    def overloaded(self) -> bool:
        if self._forced is not None:
            return self._forced
        return self.lag_ewma > self.threshold

    def force(self, state: Optional[bool]) -> None:
        self._forced = state

    async def _sample(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            lag = max(0.0, loop.time() - t0 - self.interval)
            self.lag_ewma = self.alpha * lag + (1 - self.alpha) * self.lag_ewma

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._sample())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
