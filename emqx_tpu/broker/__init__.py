"""The MQTT broker runtime: wire codec, channel state machine,
sessions, pubsub dispatch, asyncio server."""
