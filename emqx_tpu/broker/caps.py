"""MQTT capability negotiation: broker-side limits advertised in the
v5 CONNACK and enforced on PUBLISH/SUBSCRIBE.

Parity with apps/emqx/src/emqx_mqtt_caps.erl: check_pub (retain
available, max QoS, topic levels, :75-101) and check_sub (levels,
wildcard/shared availability, exclusive claim, :103-146), plus the
CONNACK property advertisement the channel emits.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..ops import topic as topic_mod
from .packet import RC


class CapError(Exception):
    def __init__(self, code: int):
        super().__init__(hex(code))
        self.code = code


@dataclass
class MqttCaps:
    # defaults mirror emqx_mqtt_caps ?DEFAULT_CAPS / emqx_schema zone mqtt
    max_packet_size: int = 1024 * 1024
    max_clientid_len: int = 65535
    max_topic_levels: int = 128
    max_qos_allowed: int = 2
    max_topic_alias: int = 65535
    retain_available: bool = True
    wildcard_subscription: bool = True
    subscription_identifiers: bool = True
    shared_subscription: bool = True
    exclusive_subscription: bool = False  # reference default: disabled

    def connack_props(
        self, receive_maximum: int, max_packet_size: "int | None" = None
    ) -> dict:
        props = {
            "receive_maximum": receive_maximum,
            "maximum_packet_size": (
                min(self.max_packet_size, max_packet_size)
                if max_packet_size
                else self.max_packet_size
            ),
            "topic_alias_maximum": self.max_topic_alias,
            "retain_available": 1 if self.retain_available else 0,
            "wildcard_subscription_available": (
                1 if self.wildcard_subscription else 0
            ),
            "shared_subscription_available": 1 if self.shared_subscription else 0,
            "subscription_identifier_available": (
                1 if self.subscription_identifiers else 0
            ),
        }
        # Maximum QoS property is only legal as 0 or 1; absence means
        # QoS 2 supported (MQTT-5 §3.2.2.3.4)
        if self.max_qos_allowed < 2:
            props["maximum_qos"] = self.max_qos_allowed
        return props

    def check_pub(self, qos: int, retain: bool) -> None:
        if qos > self.max_qos_allowed:
            raise CapError(RC.QOS_NOT_SUPPORTED)
        if retain and not self.retain_available:
            raise CapError(RC.RETAIN_NOT_SUPPORTED)

    def check_sub(self, flt: str) -> None:
        """flt is the real filter (share/exclusive prefixes handled by
        the caller; this checks shape limits)."""
        group, real = topic_mod.parse_share(flt)
        if group is not None and not self.shared_subscription:
            raise CapError(RC.SHARED_SUBSCRIPTIONS_NOT_SUPPORTED)
        if len(topic_mod.words(real)) > self.max_topic_levels:
            raise CapError(RC.TOPIC_FILTER_INVALID)
        if topic_mod.is_wildcard(real) and not self.wildcard_subscription:
            raise CapError(RC.WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED)
