"""Broker metrics & stats — counters and gauges.

Counter names mirror the reference's fixed metric set
(apps/emqx/src/emqx_metrics.erl bytes/packets/messages/delivery
domains); stats gauges mirror emqx_stats.erl (current/max pairs).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict


class Metrics:
    def __init__(self) -> None:
        self._c: Dict[str, int] = defaultdict(int)

    def inc(self, name: str, n: int = 1) -> None:
        self._c[name] += n

    def val(self, name: str) -> int:
        return self._c.get(name, 0)

    def all(self) -> Dict[str, int]:
        return dict(self._c)


class Stats:
    """current/max gauges (emqx_stats.erl:setstat current+max pairs)."""

    def __init__(self) -> None:
        self._cur: Dict[str, int] = defaultdict(int)
        self._max: Dict[str, int] = defaultdict(int)

    def set(self, name: str, v: int) -> None:
        self._cur[name] = v
        if v > self._max[name]:
            self._max[name] = v

    def incr(self, name: str, n: int = 1) -> None:
        self.set(name, self._cur[name] + n)

    def decr(self, name: str, n: int = 1) -> None:
        self._cur[name] = max(0, self._cur[name] - n)

    def val(self, name: str) -> int:
        return self._cur.get(name, 0)

    def max(self, name: str) -> int:
        return self._max.get(name, 0)

    def all(self) -> Dict[str, int]:
        out = dict(self._cur)
        out.update({k + ".max": v for k, v in self._max.items()})
        return out
