"""Asyncio TCP front end: the esockd/emqx_connection analog.

One Connection task per client socket (the reference runs one Erlang
process per connection, emqx_connection.erl:315); inbound bytes flow
through the incremental Parser into the Channel; deliveries from other
sessions arrive via the session's outgoing sink. An optional publish
micro-batcher aggregates concurrent publishes into one TPU match
dispatch (the batching window the survey calls out, SURVEY.md §7).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional

from .. import framec
from . import frame
from .channel import Channel, ProtocolError
from .limiter import ListenerLimits, LoadShedder
from .message import Message
from .packet import Disconnect, MQTT_V5, Publish, RC, Subscribe
from .pubsub import Broker
from .transport import TcpTransport, WsTransport

log = logging.getLogger("emqx_tpu.server")


class PublishBatcher:
    """Aggregate publishes across connections into one router batch
    (mirrors emqx_router_syncer's batching, applied to the read path).
    Flushes when `max_batch` is reached or `max_delay` elapses."""

    def __init__(self, broker: Broker, max_batch: int = 256, max_delay: float = 0.002):
        self.broker = broker
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._pending: List[Message] = []
        self._flusher: Optional[asyncio.TimerHandle] = None
        self._loop = None

    def submit(self, msg: Message) -> None:
        self._pending.append(msg)
        if len(self._pending) >= self.max_batch:
            self.flush()
        elif self._flusher is None:
            if self._loop is None:
                self._loop = asyncio.get_event_loop()
            self._flusher = self._loop.call_later(self.max_delay, self.flush)

    def flush(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        self.broker.publish_batch(batch)


class Connection:
    def __init__(self, server: "Server", transport):
        self.server = server
        self.transport = transport
        peer = transport.peername()
        # normalize to "ip:port" (banned/flapping/trace match on the ip)
        if isinstance(peer, (tuple, list)) and len(peer) >= 2:
            peer = f"{peer[0]}:{peer[1]}"
        self.channel = Channel(
            server.broker,
            peer=str(peer),
            mountpoint=server.mountpoint,
            max_packet_size=server.max_packet_size,
            mqtt_conf=server.mqtt_conf,
        )
        self.parser = framec.Parser(max_packet_size=server.max_packet_size)
        # per-connection limiter chains (client tier -> listener tier ->
        # node tier; the ?LIMITER_ROUTING check of emqx_channel.erl:751)
        self.pub_limiter = server.limits.publish_limiter()
        self.byte_limiter = server.limits.bytes_limiter()

    def _wire_sink(self) -> None:
        sess = self.channel.session
        if sess is not None:
            sess.outgoing_sink = self._send_packets
            if not self.channel.mountpoint:
                # bytes fast path: valid only when no mountpoint strip
                # rewrites delivered topics (bytes differ per client)
                sess.outgoing_sink_bytes = self._send_bytes
                sess.sink_proto_ver = self.channel.proto_ver
            else:
                # a takeover from a mountpoint-free listener must not
                # leave the PREVIOUS connection's bytes sink installed
                sess.outgoing_sink_bytes = None
            # admin kick severs the socket through this
            sess.closer = self.transport.close
            # background producers (DS pump) must hop onto this loop
            # before touching the session or transport
            sess.event_loop = asyncio.get_running_loop()

    def _send_bytes(self, data: bytes) -> None:
        """Fanout fast path: one shared QoS0 PUBLISH, serialized once
        per (proto version, retain) by the broker, written verbatim."""
        try:
            limit = self.channel.client_max_packet
            if limit is not None and len(data) > limit:
                self.server.broker.metrics.inc("delivery.dropped.too_large")
                return
            self.transport.write(data)
        except Exception:  # connection already gone
            pass

    def _send_packets(self, pkts) -> None:
        try:
            ver = self.channel.proto_ver
            mp = self.channel.mountpoint
            if mp:
                # strip the listener mountpoint from delivered topics —
                # copies, never mutation: a wide-fanout PUBLISH object
                # is shared across subscribers (emqx_mountpoint:unmount)
                pkts = [
                    Publish(
                        topic=p.topic[len(mp):],
                        payload=p.payload,
                        qos=p.qos,
                        retain=p.retain,
                        dup=p.dup,
                        packet_id=p.packet_id,
                        props=p.props,
                    )
                    if isinstance(p, Publish) and p.topic.startswith(mp)
                    else p
                    for p in pkts
                ]
            chunks = []
            limit = self.channel.client_max_packet
            for p in pkts:
                wire = framec.serialize(p, ver)
                # client's maximum_packet_size: drop, don't send
                # (MQTT-5 §3.1.2.11.4; the reference counts
                # 'delivery.dropped.too_large')
                if (
                    limit is not None
                    and len(wire) > limit
                    and isinstance(p, Publish)
                ):
                    self.server.broker.metrics.inc("delivery.dropped.too_large")
                    # release the inflight slot or the window shrinks
                    # permanently — the client will never ack a packet
                    # it never received
                    sess = self.channel.session
                    if p.packet_id is not None and sess is not None:
                        sess.forget_inflight(p.packet_id)
                    continue
                chunks.append(wire)
            self.transport.write(b"".join(chunks))
        except Exception:  # connection already gone; session keeps state
            pass

    async def run(self) -> None:
        try:
            while True:
                timeout = None
                if self.channel.keepalive:
                    timeout = (
                        self.channel.keepalive
                        * self.channel.keepalive_multiplier
                    )
                elif not self.channel.connected:
                    timeout = self.server.connect_timeout
                try:
                    data = await asyncio.wait_for(
                        self.transport.read(), timeout=timeout
                    )
                except asyncio.TimeoutError:
                    break  # keepalive/connect timeout
                if not data:
                    break
                try:
                    pkts = self.parser.feed(data)
                except frame.FrameError as e:
                    if self.channel.proto_ver == MQTT_V5 and self.channel.connected:
                        self._send_packets([Disconnect(e.code)])
                    break
                for pkt in pkts:
                    from .packet import Connect

                    if isinstance(pkt, Connect) and not self.channel.connected:
                        hooks = self.server.broker.hooks
                        # 'client.connect' gate (license quota, exhook
                        # OnClientConnect) runs FIRST — a shed CONNECT
                        # must not cost an auth-backend round trip. Run
                        # it off-loop when a slow (out-of-proc) hook is
                        # registered, same posture as authenticate.
                        cinfo = dict(
                            client_id=pkt.client_id,
                            username=pkt.username,
                            proto_ver=pkt.proto_ver,
                            keepalive=pkt.keepalive,
                            clean_start=pkt.clean_start,
                            peer=self.channel.peer,
                        )
                        if hooks.has_slow("client.connect"):
                            cverdict = await (
                                asyncio.get_running_loop().run_in_executor(
                                    None,
                                    lambda: hooks.run_fold(
                                        "client.connect", (cinfo,), True
                                    ),
                                )
                            )
                        elif hooks.has("client.connect"):
                            cverdict = hooks.run_fold(
                                "client.connect", (cinfo,), True
                            )
                        else:
                            cverdict = True
                        self.channel.preconnect = (pkt.client_id, cverdict)
                        if cverdict is not True:
                            # shed before the auth fold runs at all
                            self.channel.preauth = (pkt.client_id, True)
                        else:
                            # run the authenticate fold OFF-loop:
                            # providers doing network IO (HTTP authn)
                            # block for up to their timeout, and that
                            # must stall only THIS connection — never
                            # the whole broker loop
                            info = dict(
                                client_id=pkt.client_id,
                                username=pkt.username,
                                password=pkt.password,
                                peer=self.channel.peer,
                            )
                            verdict = await (
                                asyncio.get_running_loop().run_in_executor(
                                    None,
                                    lambda: hooks.run_fold(
                                        "client.authenticate", (info,), True
                                    ),
                                )
                            )
                            self.channel.preauth = (pkt.client_id, verdict)
                    if isinstance(pkt, Publish):
                        # backpressure: pausing here stops reading the
                        # socket, which pushes back on the publisher's
                        # TCP window (the reference hibernates the
                        # connection process the same way)
                        ok = await self.pub_limiter.acquire(1.0)
                        ok = ok and await self.byte_limiter.acquire(
                            float(len(pkt.payload))
                        )
                        if not ok:
                            self.server.broker.metrics.inc(
                                "messages.dropped.quota_exceeded"
                            )
                            if self.channel.proto_ver == MQTT_V5:
                                self._send_packets(
                                    [Disconnect(RC.QUOTA_EXCEEDED)]
                                )
                            return
                    if self.channel.connected and isinstance(
                        pkt, (Publish, Subscribe)
                    ):
                        # verdicts are scoped to THIS packet: always
                        # reset so nothing stale survives a has_slow
                        # flip or an unconsumed rewrite miss
                        self.channel.preauthz = {}
                        self.channel.presub_filters = None
                    if self.channel.connected and isinstance(
                        pkt, (Publish, Subscribe)
                    ) and self.server.broker.hooks.has_slow("client.authorize"):
                        # a network-backed authz source (or exhook) is
                        # installed: pre-resolve the verdicts OFF-loop so
                        # a backend stall pushes back on this connection
                        # only, never the broker loop (same pattern as
                        # the authenticate fold above)
                        cid = self.channel.client_id
                        hooks = self.server.broker.hooks
                        if isinstance(pkt, Publish):
                            t = pkt.topic or self.channel.topic_aliases.get(
                                pkt.props.get("topic_alias")
                            )
                            if t:
                                self.channel.preauthz = (
                                    await asyncio.get_running_loop().run_in_executor(
                                        None,
                                        lambda: {
                                            ("publish", t): hooks.run_fold(
                                                "client.authorize",
                                                (cid, "publish", t),
                                                True,
                                            )
                                        },
                                    )
                                )
                        else:
                            # run the client.subscribe fold HERE (once,
                            # off-loop) so rewritten filters get their
                            # verdicts pre-resolved too; the channel
                            # consumes the folded list instead of re-
                            # running the chain (presub)
                            def _presub(pkt=pkt):
                                acc = hooks.run_fold(
                                    "client.subscribe", (cid,), pkt.filters
                                )
                                filters = (
                                    acc if acc is not None else pkt.filters
                                )
                                verdicts = {
                                    ("subscribe", f): hooks.run_fold(
                                        "client.authorize",
                                        (cid, "subscribe", f),
                                        True,
                                    )
                                    for f, _o in filters
                                }
                                return filters, verdicts
                            (
                                self.channel.presub_filters,
                                self.channel.preauthz,
                            ) = await asyncio.get_running_loop().run_in_executor(
                                None, _presub
                            )
                    try:
                        out = self.channel.handle_packet(pkt)
                    except ProtocolError as e:
                        if self.channel.proto_ver == MQTT_V5:
                            self._send_packets([Disconnect(e.code)])
                        raise
                    if out:
                        self._send_packets(out)
                    self._wire_sink()
                await self.drain()
        except (ProtocolError, ConnectionError):
            pass
        except Exception:
            log.exception("connection crashed")
        finally:
            sess = self.channel.session
            if sess is not None and getattr(sess, "outgoing_sink", None) is self._send_packets:
                sess.outgoing_sink = None
                sess.outgoing_sink_bytes = None
                sess.closer = None
            self.channel.on_close()
            self.transport.close()

    async def drain(self) -> None:
        try:
            await self.transport.drain()
        except ConnectionError:
            pass


class Server:
    """One listener. `ssl_context` upgrades it to ssl:// (or wss://
    when `websocket` is set); the reference's four listener types
    tcp/ssl/ws/wss (emqx_listeners.erl:444-455,657) map onto these two
    flags over the same connection runtime."""

    def __init__(
        self,
        broker: Optional[Broker] = None,
        host: str = "127.0.0.1",
        port: int = 1883,
        max_packet_size: int = frame.DEFAULT_MAX_PACKET_SIZE,
        connect_timeout: float = 10.0,
        limits: Optional[ListenerLimits] = None,
        shedder: Optional[LoadShedder] = None,
        ssl_context=None,
        websocket: bool = False,
        ws_path: str = "/mqtt",
        name: Optional[str] = None,
        mountpoint: str = "",
        mqtt_conf: Optional[dict] = None,
    ):
        self.broker = broker or Broker()
        self.host = host
        self.port = port
        self.max_packet_size = max_packet_size
        self.connect_timeout = connect_timeout
        self.limits = limits or ListenerLimits()
        self.shedder = shedder
        self.ssl_context = ssl_context
        self.websocket = websocket
        self.ws_path = ws_path
        proto = ("wss" if ssl_context else "ws") if websocket else (
            "ssl" if ssl_context else "tcp"
        )
        self.proto = proto
        self.name = name or f"{proto}:default"
        self.mountpoint = mountpoint
        self.mqtt_conf = mqtt_conf or {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._pending: set = set()  # transports still in ws handshake
        self.listen_addr = None
        # eviction holds: multiple agents (evacuation + rebalance) may
        # gate accepts concurrently; last-writer-wins booleans would
        # let one agent's disable reopen another's drain
        self._evict_holds = 0

    @property
    def evicting(self) -> bool:
        return self._evict_holds > 0

    def evict_hold(self) -> None:
        self._evict_holds += 1

    def evict_release(self) -> None:
        self._evict_holds = max(0, self._evict_holds - 1)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_client, self.host, self.port, ssl=self.ssl_context
        )
        addr = self._server.sockets[0].getsockname()
        self.listen_addr = addr[:2]
        # live-listener registry: the mgmt listeners view walks this
        if self not in self.broker.servers:
            self.broker.servers.append(self)
        if self.shedder is not None:
            self.shedder.start()
        log.info("listening on %s", addr)

    async def _on_client(self, reader, writer) -> None:
        # accept gates: OLP shed (emqx_olp new-conn backoff) first,
        # then the listener's connection-rate bucket (max_conn_rate)
        if self.evicting:
            self.broker.metrics.inc("eviction.conn_rejected")
            writer.close()
            return
        if self.shedder is not None and self.shedder.overloaded:
            self.shedder.shed_count += 1
            self.broker.metrics.inc("olp.new_conn_shed")
            writer.close()
            return
        if not self.limits.accept_allowed():
            self.broker.metrics.inc("listener.conn_rate_limited")
            writer.close()
            return
        if self.websocket:
            # bound + track the handshake: a client that connects and
            # sends nothing must not hold the fd forever, and stop()
            # must be able to kick a socket still mid-handshake
            raw = TcpTransport(reader, writer)
            self._pending.add(raw)
            try:
                t = await asyncio.wait_for(
                    WsTransport.handshake(reader, writer, path=self.ws_path),
                    timeout=self.connect_timeout,
                )
            except (asyncio.TimeoutError, ConnectionError):
                t = None
            finally:
                self._pending.discard(raw)
            if t is None:
                raw.close()
                return
        else:
            t = TcpTransport(reader, writer)
        conn = Connection(self, t)
        self._conns.add(conn)
        try:
            await conn.run()
        finally:
            self._conns.discard(conn)

    async def stop(self) -> None:
        if self in self.broker.servers:
            self.broker.servers.remove(self)
        if self.shedder is not None:
            self.shedder.stop()
        if self._server is not None:
            self._server.close()
            # kick live connections so wait_closed() cannot hang on them
            for conn in list(self._conns):
                try:
                    conn.transport.close()
                except Exception:
                    pass
            for raw in list(self._pending):
                raw.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="emqx_tpu MQTT broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=1883)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    asyncio.run(Server(host=args.host, port=args.port).serve_forever())


if __name__ == "__main__":
    main()
