"""Pipelined async dispatch engine for the publish hot path.

BENCH_r05 put the chip-resident match kernel at ~0.09-0.39 ms/batch
while end-to-end publish sat at 250 ms p50: the synchronous
encode → dispatch → device-to-host walk pays the full link round trip
per publish, so the kernel win evaporates before it reaches a socket.
This module is the host-side dispatch discipline that closes that gap,
the emqx_broker pool-worker batching analog re-shaped for an
accelerator link:

  * **Micro-batching queue** — concurrent publishes coalesce into one
    kernel dispatch. The batch closes adaptively: flush when
    `queue_depth` topics are waiting OR when the oldest enqueued
    publish has waited `deadline_ms` (sub-millisecond by default),
    whichever comes first — bounded added latency, unbounded
    coalescing win under load.

  * **Pipelining** — a flush only LAUNCHES the batch
    (Router.match_filters_begin: cache probe, encode, host-to-device
    transfer, kernel dispatch); the device-to-host fetch + fanout
    (match_filters_finish) happens on a later event-loop turn, or when
    the in-flight window exceeds `pipeline_depth`. JAX dispatch is
    asynchronous and the device tables update in place through donated
    buffers, so while batch N executes on the device the host encodes
    and uploads batch N+1 and drains the result pairs of batch N-1 —
    the classic double-buffer, for both DeviceTable and
    ShardedDeviceTable (both sit behind the same begin/finish seam).

  * **Generation-stamped match cache** — in front of the queue,
    Router's GenMatchCache (ops/match.py) resolves hot topics with one
    dict probe and no kernel at all; route mutations bump the router
    generation and stale entries lazily rebuild, so churn never does
    an O(n) clear.

  * **Fanout-resolve overlap** — topics the match cache answers at
    begin time have known filter sets before the kernel fetch: their
    stale/missing fanout plans launch `Router.resolve_fanout_begin`
    (the device dedup/max-QoS kernel, ops/fanout.py) in the same
    flush, so the deduped plan materializes on device while the match
    hash fetch for the uncached remainder is still in flight; plans
    install stamped with the begin-time clock (stale-on-arrival if a
    mutation landed mid-flight).

Exactness contract: every result is produced by the same
begin/finish code path the synchronous `Broker.publish_batch` →
`Router.match_filters_batch` composes, and delivery runs through the
same `Broker._pre_publish`/`Broker._dispatch` — pipelined + cached
results are bit-identical to the synchronous path (oracle-checked in
tests/test_dispatch_engine.py and bench.py's pipeline exactness
stage).

Telemetry (obs/kernel_telemetry, scraped as `emqx_xla_*`): queue-wait
histogram family `pipeline_queue_wait_seconds`, gauges
`pipeline_depth` / `pipeline_coalesce`, and the cache's
hits/misses/evictions counters recorded by the Router.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Deque, List, Optional, Tuple

from .message import Message


class _AggregateCount:
    """Future-compatible shim folding N per-publish delivery counts
    into ONE awaitable — the storm surface (submit_many) enqueues a
    whole chunk against a single future instead of paying a Future
    allocation + callback wake per publish. Only the three methods
    _flush/_collect_one actually touch are implemented."""

    __slots__ = ("_fut", "_left", "_total")

    def __init__(self, fut: "asyncio.Future", n: int) -> None:
        self._fut = fut
        self._left = n
        self._total = 0

    def done(self) -> bool:
        return self._fut.done()

    def set_result(self, n: int) -> None:
        self._total += n
        self._left -= 1
        if self._left <= 0 and not self._fut.done():
            self._fut.set_result(self._total)

    def set_exception(self, exc: BaseException) -> None:
        self._left -= 1
        if not self._fut.done():
            self._fut.set_exception(exc)


class DispatchEngine:
    """One engine per Broker. All entry points must run on the
    broker's event loop; the engine holds no locks — ordering comes
    from the loop plus the FIFO in-flight window (begin/finish pairs
    complete strictly in begin order, the Router contract)."""

    def __init__(
        self,
        broker,
        queue_depth: int = 64,
        deadline_ms: float = 0.5,
        pipeline_depth: int = 2,
        match_cache_size: int = 8192,
    ) -> None:
        self.broker = broker
        self.router = broker.router
        if match_cache_size:
            self.router.enable_match_cache(match_cache_size)
        self.telemetry = self.router.telemetry
        self.queue_depth = max(1, queue_depth)
        self.deadline_s = max(0.0, deadline_ms) / 1e3
        self.pipeline_depth = max(1, pipeline_depth)
        self._queue: List[tuple] = []  # (msg, future, enqueue clock)
        # dispatched-but-unfetched batches: (pending match, entries)
        self._inflight: Deque[tuple] = deque()
        self._timer = None
        self._drain_scheduled = False
        self.batches_total = 0
        self.publishes_total = 0
        self.closed = False

    # --- async publish surface -------------------------------------------

    async def publish(self, msg: Message) -> int:
        """Enqueue one publish and await its delivery count. The
        pipelined analog of Broker.publish — identical hooks, identical
        match results, identical dispatch."""
        return await self.submit(msg)

    def submit(self, msg: Message) -> "asyncio.Future":
        """Enqueue without awaiting; returns the delivery-count future.
        Flushes immediately at queue_depth, else arms the sub-ms
        deadline timer for the batch the first enqueue opened."""
        assert not self.closed, "dispatch engine stopped"
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # publish sentinel (obs/sentinel.py): a 1/sample_n publish gets
        # a stage span + a deferred shadow-oracle audit; every other
        # publish pays one attribute read + one counter increment
        st = self.broker.sentinel
        span = st.maybe_span(msg) if st is not None else None
        self._queue.append((msg, fut, self.telemetry.clock(), span))
        if len(self._queue) >= self.queue_depth:
            self._flush()
        elif self._timer is None:
            self._timer = loop.call_later(self.deadline_s, self._on_deadline)
        return fut

    def submit_many(self, msgs) -> "asyncio.Future":
        """Storm surface: enqueue a chunk of publishes as one unit and
        return ONE future resolving to the summed delivery count. Same
        hooks, same match path, same sentinel sampling per message as
        submit() — only the per-publish Future ceremony is amortized,
        which is what lets a million-session soak generator saturate
        the pipeline from a single driver task."""
        assert not self.closed, "dispatch engine stopped"
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if not msgs:
            fut.set_result(0)
            return fut
        agg = _AggregateCount(fut, len(msgs))
        st = self.broker.sentinel
        clock = self.telemetry.clock
        for msg in msgs:
            span = st.maybe_span(msg) if st is not None else None
            # _flush REPLACES self._queue with a fresh list — re-read
            # it each append rather than holding a stale binding
            self._queue.append((msg, agg, clock(), span))
            if len(self._queue) >= self.queue_depth:
                self._flush()
        if self._queue and self._timer is None:
            self._timer = loop.call_later(self.deadline_s, self._on_deadline)
        return fut

    def _on_deadline(self) -> None:
        self._timer = None
        if self._queue:
            self._flush()

    # --- batch close + pipeline ------------------------------------------

    def _flush(self) -> None:
        """Close the current batch: run the publish hooks, LAUNCH the
        match kernels (no device->host fetch), and push the pending
        batch onto the in-flight window. Collection happens on a later
        loop turn (_drain) or immediately for whatever exceeds the
        pipeline depth."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._queue = self._queue, []
        tel = self.telemetry
        broker = self.broker
        st = broker.sentinel
        now = tel.clock()
        entries = []
        topics = []
        bspan = None
        for msg, fut, t_in, span in batch:
            tel.observe_family("pipeline_queue_wait_seconds", now - t_in)
            if span is not None:
                span.add("queue", now - t_in)
                if bspan is None and st is not None:
                    bspan = st.batch_span()
            live = broker._pre_publish(msg)
            entries.append((live, fut, span))
            if live is not None:
                topics.append(live.topic)
        self.batches_total += 1
        self.publishes_total += len(batch)
        pending = self.router.match_filters_begin(topics, span=bspan)
        # device-resolved fanout overlap: topics the match cache
        # answered at begin time have known filter sets NOW — launch
        # their plan resolves immediately so the deduped plan
        # materializes on device while the match hash fetch for the
        # uncached remainder is still in flight
        fanout_pending = None
        if broker._fanout_device and pending.full_out is not None:
            seen = set()
            for flts in pending.full_out:
                if flts is None:
                    continue
                fkey = tuple(flts)
                if fkey in seen:
                    continue
                seen.add(fkey)
                if broker._plan_fresh(fkey):
                    continue
                h = self.router.resolve_fanout_begin(
                    fkey, min_fan=broker._fanout_min_fan
                )
                if h is not None:
                    if fanout_pending is None:
                        fanout_pending = []
                    fanout_pending.append(
                        (fkey, broker._fanout_clock, h)
                    )
        self._inflight.append((pending, entries, fanout_pending, bspan))
        tel.set_gauge("pipeline_depth", len(self._inflight))
        tel.set_gauge("pipeline_coalesce", len(batch))
        while len(self._inflight) > self.pipeline_depth:
            self._collect_one()
        if self._inflight and not self._drain_scheduled:
            self._drain_scheduled = True
            asyncio.get_running_loop().call_soon(self._drain)

    def _drain(self) -> None:
        self._drain_scheduled = False
        while self._inflight:
            self._collect_one()
        self.telemetry.set_gauge("pipeline_depth", 0)

    def _collect_one(self) -> None:
        """Fetch + deliver the OLDEST in-flight batch (begin order)."""
        pending, entries, fanout_pending, bspan = self._inflight.popleft()
        broker = self.broker
        router = self.router
        st = broker.sentinel
        tclock = self.telemetry.clock
        try:
            filter_lists = router.match_filters_finish(pending)
        except Exception as e:  # a failed batch fails its publishers,
            for _live, fut, _span in entries:  # never wedges the pipeline
                if not fut.done():
                    fut.set_exception(e)
            return
        if fanout_pending is not None:
            # install the overlapped plans before delivering: stamped
            # with the clock captured at begin, so a mutation that
            # landed mid-flight leaves them stale-on-arrival and the
            # dispatch below rebuilds — exactness over hit ratio
            t_res = tclock() if bspan is not None else 0.0
            for fkey, clock, h in fanout_pending:
                try:
                    plan = router.resolve_fanout_finish(h)
                except Exception:
                    continue  # the dispatch path rebuilds host-side
                broker._store_plan(fkey, clock, plan)
            if bspan is not None:
                bspan.add("resolve", tclock() - t_res)
        fd = router.filter_dests
        it = iter(filter_lists)
        for live, fut, span in entries:
            if live is None:
                n = 0  # hook-denied / intercepted: same 0 as publish()
            else:
                flts = next(it)
                pairs = [(f, fd(f)) for f in flts]
                t_del = tclock() if span is not None else 0.0
                try:
                    n = broker._dispatch(live, pairs)
                except Exception as e:
                    if not fut.done():
                        fut.set_exception(e)
                    continue
                if span is not None and st is not None:
                    span.add("deliver", tclock() - t_del)
                    if bspan is not None:
                        span.merge(bspan)
                    st.finish_span(span)
                    # shadow-oracle audit of exactly what was served:
                    # the matched filter set + the (filter, dests)
                    # pairs, stamped with the begin generation so churn
                    # mid-flight skips rather than false-positives
                    st.capture_audit(
                        live.topic, tuple(flts), pairs, pending.gen,
                        span.trace_id,
                    )
            if not fut.done():
                fut.set_result(n)

    # --- lifecycle --------------------------------------------------------

    async def drain(self) -> None:
        """Flush the open batch and collect everything in flight."""
        if self._queue:
            self._flush()
        while self._inflight:
            self._collect_one()
        await asyncio.sleep(0)  # let resolved futures' awaiters run

    async def stop(self) -> None:
        await self.drain()
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def status(self) -> dict:
        cache = self.router.match_cache
        return {
            "queue_depth": self.queue_depth,
            "deadline_ms": self.deadline_s * 1e3,
            "pipeline_depth": self.pipeline_depth,
            "queued": len(self._queue),
            "inflight": len(self._inflight),
            "batches_total": self.batches_total,
            "publishes_total": self.publishes_total,
            "coalesce_factor": round(
                self.publishes_total / self.batches_total, 3
            ) if self.batches_total else 0.0,
            "match_cache": None if cache is None else {
                "capacity": cache.capacity,
                "entries": len(cache),
                "hits": cache.hits,
                "misses": cache.misses,
                "evictions": cache.evictions,
                "hit_ratio": round(cache.hit_ratio(), 6),
            },
        }
