"""Pipelined async dispatch engine for the publish hot path.

BENCH_r05 put the chip-resident match kernel at ~0.09-0.39 ms/batch
while end-to-end publish sat at 250 ms p50: the synchronous
encode → dispatch → device-to-host walk pays the full link round trip
per publish, so the kernel win evaporates before it reaches a socket.
This module is the host-side dispatch discipline that closes that gap,
the emqx_broker pool-worker batching analog re-shaped for an
accelerator link:

  * **Micro-batching queue** — concurrent publishes coalesce into one
    kernel dispatch. The batch closes adaptively: flush when
    `queue_depth` topics are waiting OR when the oldest enqueued
    publish has waited `deadline_ms` (sub-millisecond by default),
    whichever comes first — bounded added latency, unbounded
    coalescing win under load.

  * **Pipelining** — a flush only LAUNCHES the batch
    (Router.match_filters_begin: cache probe, encode, host-to-device
    transfer, kernel dispatch); the device-to-host fetch + fanout
    (match_filters_finish) happens on a later event-loop turn, or when
    the in-flight window exceeds `pipeline_depth`. JAX dispatch is
    asynchronous and the device tables update in place through donated
    buffers, so while batch N executes on the device the host encodes
    and uploads batch N+1 and drains the result pairs of batch N-1 —
    the classic double-buffer, for both DeviceTable and
    ShardedDeviceTable (both sit behind the same begin/finish seam).

  * **Generation-stamped match cache** — in front of the queue,
    Router's GenMatchCache (ops/match.py) resolves hot topics with one
    dict probe and no kernel at all; route mutations bump the router
    generation and stale entries lazily rebuild, so churn never does
    an O(n) clear.

  * **Fanout-resolve overlap** — topics the match cache answers at
    begin time have known filter sets before the kernel fetch: their
    stale/missing fanout plans launch `Router.resolve_fanout_begin`
    (the device dedup/max-QoS kernel, ops/fanout.py) in the same
    flush, so the deduped plan materializes on device while the match
    hash fetch for the uncached remainder is still in flight; plans
    install stamped with the begin-time clock (stale-on-arrival if a
    mutation landed mid-flight).

Exactness contract: every result is produced by the same
begin/finish code path the synchronous `Broker.publish_batch` →
`Router.match_filters_batch` composes, and delivery runs through the
same `Broker._pre_publish`/`Broker._dispatch` — pipelined + cached
results are bit-identical to the synchronous path (oracle-checked in
tests/test_dispatch_engine.py and bench.py's pipeline exactness
stage).

**Device failure domain** (the emqx_olp / emqx_limiter analog for the
accelerator link — see PARITY.md):

  * **Failover** — a device batch that fails (XlaRuntimeError-class,
    injected or real) or blows the per-batch `breaker_deadline_ms` is
    transparently re-served through the host match walk
    (`Router.match_filters_host` — bit-identical by the oracle
    contract), so publishers never see a transient device fault.

  * **Circuit breaker** — `breaker_threshold` CONSECUTIVE device
    failures trip the breaker: `Router.suspend_device()` routes ALL
    match + fanout traffic host-side (degraded-but-correct), the
    `xla_device_breaker` alarm raises, and the flight recorder
    freezes a `device_breaker_trip` bundle.

  * **Recovery** — a background canary probe with bounded exponential
    backoff re-dispatches a sentinel batch through the real kernels;
    on success it re-uploads FULL device state (the quarantine
    clean-sync machinery: `Router.device_resync`) and verifies a
    second canary against the host oracle before closing the breaker
    and clearing the alarm — the recovered device re-earns trust
    under the sentinel's shadow audit, never by assumption.

  * **Admission control** — the dispatch queue is bounded
    (`queue_max_depth` outstanding publishes). Overload either SHEDS
    (fail fast with `QueueOverloadError`, counted, `xla_queue_overload`
    alarm at the high watermark, cleared at the low watermark) or
    BLOCKS (publishers park on a waiter list drained as capacity
    frees) per `queue_policy`; blocked publishers carry a
    `queue_deadline_ms` so a wedged device can never hang them
    indefinitely. The emqx_olp load-shed / emqx_limiter token-bucket
    analog for the device link.

Telemetry (obs/kernel_telemetry, scraped as `emqx_xla_*`): queue-wait
histogram family `pipeline_queue_wait_seconds`, gauges
`pipeline_depth` / `pipeline_coalesce`, the cache's
hits/misses/evictions counters recorded by the Router, plus the
failure-domain families `emqx_xla_breaker_*` / `emqx_xla_queue_*`
(state, trips, recoveries, fallbacks, probes, sheds, blocks,
deadline expiries — all transitions counted).
"""

from __future__ import annotations

import asyncio
import contextlib
import gc
import logging
from collections import deque
from contextlib import nullcontext
from typing import Deque, Dict, List, Optional, Set, Tuple

from ..obs.profiler import STAGE_MARK
from .message import Message

log = logging.getLogger("emqx_tpu.broker.dispatch_engine")

ALARM_BREAKER = "xla_device_breaker"
ALARM_OVERLOAD = "xla_queue_overload"

# breaker_state gauge encoding
_STATE_GAUGE = {"closed": 0, "open": 1, "half_open": 2}


class EngineStopped(RuntimeError):
    """The dispatch engine stopped; queued publishers fail
    deterministically instead of hanging."""


class QueueOverloadError(RuntimeError):
    """Admission control shed this publish (queue at high watermark
    under the `shed` policy) — fail fast, counted, alarmed."""


class QueueDeadlineExceeded(RuntimeError):
    """A blocked publish waited past `queue_deadline_ms` for queue
    capacity — the engine fails it rather than hanging the publisher
    on a wedged device."""


class _AggregateCount:
    """Future-compatible shim folding N per-publish delivery counts
    into ONE awaitable — the storm surface (submit_many) enqueues a
    whole chunk against a single future instead of paying a Future
    allocation + callback wake per publish. Only the three methods
    _flush/_collect_one actually touch are implemented."""

    __slots__ = ("_fut", "_left", "_total")

    def __init__(self, fut: "asyncio.Future", n: int) -> None:
        self._fut = fut
        self._left = n
        self._total = 0

    def done(self) -> bool:
        return self._fut.done()

    def set_result(self, n: int) -> None:
        self._total += n
        self._left -= 1
        if self._left <= 0 and not self._fut.done():
            self._fut.set_result(self._total)

    def set_exception(self, exc: BaseException) -> None:
        self._left -= 1
        if not self._fut.done():
            self._fut.set_exception(exc)

    def add_many(self, total: int, k: int) -> None:
        """Fold k publishes' combined count in ONE call — the window
        dispatch completes a whole submit_many chunk per collect
        instead of ticking set_result per publish."""
        self._total += total
        self._left -= k
        if self._left <= 0 and not self._fut.done():
            self._fut.set_result(self._total)


class DispatchEngine:
    """One engine per Broker. All entry points must run on the
    broker's event loop; the engine holds no locks — ordering comes
    from the loop plus the FIFO in-flight window (begin/finish pairs
    complete strictly in begin order, the Router contract)."""

    def __init__(
        self,
        broker,
        queue_depth: int = 64,
        deadline_ms: float = 0.5,
        pipeline_depth: int = 2,
        match_cache_size: int = 8192,
        breaker_enable: bool = True,
        breaker_threshold: int = 4,
        breaker_deadline_ms: float = 250.0,
        probe_backoff_ms: float = 100.0,
        probe_backoff_max_ms: float = 5000.0,
        queue_max_depth: int = 8192,
        queue_policy: str = "shed",
        queue_deadline_ms: float = 1000.0,
        queue_low_watermark: int = 0,
        transfer_chunk_kb: float = 0.0,
        aot_warm: bool = True,
        gc_guard: bool = True,
        alarms=None,
        flight=None,
    ) -> None:
        self.broker = broker
        self.router = broker.router
        if match_cache_size:
            self.router.enable_match_cache(match_cache_size)
        self.telemetry = self.router.telemetry
        self.queue_depth = max(1, queue_depth)
        self.deadline_s = max(0.0, deadline_ms) / 1e3
        self.pipeline_depth = max(1, pipeline_depth)
        # --- device failure domain (breaker) knobs
        self.breaker_enabled = bool(breaker_enable)
        self.breaker_threshold = max(1, breaker_threshold)
        self.breaker_deadline_s = max(0.0, breaker_deadline_ms) / 1e3
        self.probe_backoff_s = max(0.001, probe_backoff_ms) / 1e3
        self.probe_backoff_max_s = max(
            self.probe_backoff_s, probe_backoff_max_ms / 1e3
        )
        # --- admission control knobs
        self.queue_max_depth = max(1, queue_max_depth)
        assert queue_policy in ("shed", "block"), queue_policy
        self.queue_policy = queue_policy
        self.queue_deadline_s = max(0.001, queue_deadline_ms) / 1e3
        self.queue_low_watermark = (
            queue_low_watermark
            if queue_low_watermark
            else max(1, self.queue_max_depth // 2)
        )
        # --- transfer pipeline knobs (ops/transfer.py)
        # chunk_kb: bound on a ring slot's compacted-result buffer;
        # 0 = auto-size from the link probe at warmup (BDP). aot_warm:
        # pre-trace every kernel shape bucket at warmup so production
        # dispatches never pay an XLA retrace. gc_guard: keep
        # collector pauses out of launch/collect critical sections
        # (gc.freeze of steady state at warmup + per-flush pause).
        self.transfer_chunk_kb = float(transfer_chunk_kb)
        self.aot_warm = bool(aot_warm)
        self.gc_guard = bool(gc_guard)
        self.warmed = False
        # alarms/flight: explicit wiring wins; otherwise resolved
        # lazily through the attached sentinel (boot order attaches
        # the engine first and the obs bundle later — or vice versa in
        # tests — so neither order may lose the surfaces)
        self.alarms = alarms
        self.flight = flight
        self._queue: List[tuple] = []  # (msg, future, enqueue clock, span)
        # dispatched-but-unfetched batches: (pending match, entries)
        self._inflight: Deque[tuple] = deque()
        self._inflight_pubs = 0  # publishes inside _inflight entries
        self._waiters: Deque[tuple] = deque()  # block-policy parked items
        self._timer = None
        self._waiter_timer = None
        self._drain_scheduled = False
        self._pumping = False
        self._overloaded = False
        self.batches_total = 0
        self.publishes_total = 0
        self.closed = False
        # --- breaker state machine: closed -> open -> half_open -> closed
        self.breaker_state = "closed"
        self._consecutive_failures = 0
        self._probe_task: Optional[asyncio.Task] = None
        # --- shard breaker (ShardedDeviceTable chip loss): failures
        # whose exception carries a `shard` attribute are accounted
        # here PER SHARD and never feed _consecutive_failures — one
        # sick chip must not forfeit the whole mesh
        self._shard_failures: Dict[int, int] = {}
        self._shard_open: Set[int] = set()
        self._shard_probe_tasks: Dict[int, asyncio.Task] = {}
        self.last_device_error: Optional[str] = None
        # canary topics: the most recent distinct batch heads, so the
        # recovery probe dispatches realistic traffic, not synthetics
        self._recent_topics: Deque[str] = deque(maxlen=8)
        # --- device-occupancy timeline (ISSUE 17): launch->land spans
        # per ring slot, busy-time integral over empty->nonempty
        # transitions of _inflight, and the idle gaps between lands —
        # "the device is idle 97% of the time" as a committed number
        self._ring_track_since: Optional[float] = None
        self._ring_busy_since: Optional[float] = None
        self._ring_last_land: Optional[float] = None
        self._ring_busy_accum = 0.0
        self._ring_slots_total = 0
        self._ring_timeline: Deque[Dict] = deque(maxlen=64)
        tel = self.telemetry
        if tel.enabled:
            tel.set_gauge("breaker_state", 0)
            tel.set_gauge("breaker_consecutive_failures", 0)
            tel.set_gauge("queue_depth", 0)
            tel.set_gauge("queue_waiters", 0)
            tel.set_gauge("queue_overloaded", 0)

    # --- obs wiring -------------------------------------------------------

    def _get_alarms(self):
        if self.alarms is not None:
            return self.alarms
        st = self.broker.sentinel
        return st.alarms if st is not None else None

    def _get_flight(self):
        if self.flight is not None:
            return self.flight
        st = self.broker.sentinel
        return st.flight if st is not None else None

    # --- warmup: chunk sizing + AOT shape pre-trace + GC discipline ------

    def warmup(self) -> dict:
        """One-time serve-readiness pass (boot calls it after attach;
        bench calls it before timed windows; idempotent):

          1. size the transfer chunk — `transfer_chunk_kb` as given, or
             auto from a link probe (RTT floor x fetch bandwidth, the
             BDP) — and push it into the device table;
          2. AOT-warm every kernel shape bucket the engine can dispatch
             (pow2 batch ladder up to queue_depth through the REAL
             begin/finish halves), then flip the telemetry to serving:
             any later retrace counts as `recompiles_at_serve_total`;
          3. freeze the now-steady object graph out of the cyclic
             collector (gc.freeze) so gen-2 passes never scan the
             table/session bulk from inside a timed launch — paired
             with the per-flush collector pause in _flush/_collect_one.

        Returns a summary dict (also merged into status())."""
        router = self.router
        tel = self.telemetry
        info: dict = {}
        chunk_kb = self.transfer_chunk_kb
        if not chunk_kb:
            from ..ops import transfer as transfer_ops

            try:
                rtt_s, bw = transfer_ops.probe_link()
                chunk_kb = transfer_ops.auto_chunk_kb(rtt_s, bw)
                info["link_rtt_ms"] = round(rtt_s * 1e3, 3)
                info["link_mb_per_s"] = round(bw / 1e6, 1)
            except Exception as e:
                # a dead link at boot is the breaker's business, not
                # warmup's — leave the chunk unbounded, note it
                tel.count("warmup_probe_failures_total")
                log.warning("link probe failed during warmup: %r", e)
                chunk_kb = 0
        if chunk_kb:
            router.set_transfer_chunk(chunk_kb)
        self.transfer_chunk_kb = chunk_kb
        info["transfer_chunk_kb"] = chunk_kb
        if self.aot_warm:
            try:
                info["aot_shapes"] = router.warmup_shapes(self.queue_depth)
            except Exception as e:
                # a device that cannot even warm up is the breaker's
                # business — boot comes up degraded, never dead
                tel.count("warmup_failures_total")
                log.warning("AOT warmup failed: %r", e)
                self._device_failure(e)
        dt = router.device_table
        if getattr(dt, "mesh", None) is not None:
            # mesh serve state at readiness: shard count and whether
            # the admission knob degraded to single-device (small
            # table at warmup — the mesh kernels are then warmed on
            # the upgrade resync, not here)
            info["mesh_shards"] = dt.n_shards
            info["mesh_degraded"] = bool(dt.degraded)
        tel.mark_serving()
        if self.gc_guard and not self.warmed:
            gc.collect()
            gc.freeze()
        self.warmed = True
        return info

    def _gc_pause(self) -> bool:
        """Suspend the cyclic collector for a launch/collect critical
        section; returns whether it was running (restore token)."""
        if not self.gc_guard:
            return False
        was = gc.isenabled()
        if was:
            gc.disable()
        return was

    @staticmethod
    def _gc_resume(was: bool) -> None:
        if was:
            gc.enable()

    # --- async publish surface -------------------------------------------

    async def publish(self, msg: Message) -> int:
        """Enqueue one publish and await its delivery count. The
        pipelined analog of Broker.publish — identical hooks, identical
        match results, identical dispatch."""
        return await self.submit(msg)

    def _check_open(self) -> None:
        if self.closed:
            raise EngineStopped("dispatch engine stopped")

    def submit(self, msg: Message) -> "asyncio.Future":
        """Enqueue without awaiting; returns the delivery-count future.
        Flushes immediately at queue_depth, else arms the sub-ms
        deadline timer for the batch the first enqueue opened."""
        self._check_open()
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # publish sentinel (obs/sentinel.py): a 1/sample_n publish gets
        # a stage span + a deferred shadow-oracle audit; every other
        # publish pays one attribute read + one counter increment
        st = self.broker.sentinel
        span = st.maybe_span(msg) if st is not None else None
        if self._admit((msg, fut, self.telemetry.clock(), span), loop):
            if len(self._queue) >= self.queue_depth:
                self._flush()
            elif self._timer is None:
                self._timer = loop.call_later(
                    self.deadline_s, self._on_deadline
                )
        return fut

    def submit_many(self, msgs) -> "asyncio.Future":
        """Storm surface: enqueue a chunk of publishes as one unit and
        return ONE future resolving to the summed delivery count. Same
        hooks, same match path, same sentinel sampling per message as
        submit() — only the per-publish Future ceremony is amortized,
        which is what lets a million-session soak generator saturate
        the pipeline from a single driver task. Admission control
        applies per message: a shed message fails the aggregate."""
        self._check_open()
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if not msgs:
            fut.set_result(0)
            return fut
        agg = _AggregateCount(fut, len(msgs))
        st = self.broker.sentinel
        clock = self.telemetry.clock
        for msg in msgs:
            span = st.maybe_span(msg) if st is not None else None
            # _flush REPLACES self._queue with a fresh list — re-read
            # it each append rather than holding a stale binding
            if self._admit((msg, agg, clock(), span), loop):
                if len(self._queue) >= self.queue_depth:
                    self._flush()
        if self._queue and self._timer is None:
            self._timer = loop.call_later(self.deadline_s, self._on_deadline)
        return fut

    # --- admission control (the emqx_olp analog) --------------------------

    def outstanding(self) -> int:
        """Publishes the engine currently owns: batched + in flight.
        Blocked waiters are excluded — they ARE the backpressure."""
        return len(self._queue) + self._inflight_pubs

    def _admit(self, item: tuple, loop) -> bool:
        """True when the item entered the batch queue; False when it
        was shed (future failed) or parked on the waiter list."""
        tel = self.telemetry
        if self.outstanding() < self.queue_max_depth:
            self._queue.append(item)
            return True
        self._overload(tel)
        if self.queue_policy == "block":
            tel.count("queue_blocked_total")
            self._waiters.append(item)
            tel.set_gauge("queue_waiters", len(self._waiters))
            if self._waiter_timer is None:
                self._waiter_timer = loop.call_later(
                    self.queue_deadline_s / 2, self._expire_waiters
                )
            return False
        tel.count("queue_shed_total")
        _msg, fut, _t, _span = item
        if not fut.done():
            fut.set_exception(
                QueueOverloadError(
                    f"dispatch queue overloaded "
                    f"({self.outstanding()}/{self.queue_max_depth} "
                    f"outstanding, policy=shed)"
                )
            )
        return False

    def _overload(self, tel) -> None:
        if self._overloaded:
            return
        self._overloaded = True
        tel.set_gauge("queue_overloaded", 1)
        alarms = self._get_alarms()
        if alarms is not None:
            try:
                alarms.ensure(
                    ALARM_OVERLOAD,
                    details={
                        "outstanding": self.outstanding(),
                        "max_depth": self.queue_max_depth,
                        "policy": self.queue_policy,
                    },
                    message=(
                        f"dispatch queue overloaded "
                        f"({self.queue_policy} policy engaged)"
                    ),
                )
            except Exception:
                tel.count("queue_alarm_failures_total")
                log.exception("overload alarm failed")

    def _maybe_clear_overload(self) -> None:
        if not self._overloaded:
            return
        if self.outstanding() > self.queue_low_watermark or self._waiters:
            return
        self._overloaded = False
        tel = self.telemetry
        tel.set_gauge("queue_overloaded", 0)
        alarms = self._get_alarms()
        if alarms is not None:
            alarms.ensure_deactivated(ALARM_OVERLOAD)

    def _pump_waiters(self) -> None:
        """Admit parked publishers as capacity frees (block policy).
        Re-entrancy guarded: pumping flushes, flushes collect, and a
        collect completion calls back in here."""
        if self._pumping or not self._waiters:
            return
        self._pumping = True
        tel = self.telemetry
        now = tel.clock()
        try:
            while self._waiters and (
                self.outstanding() < self.queue_max_depth
            ):
                item = self._waiters.popleft()
                _msg, fut, t_in, _span = item
                if fut.done():
                    continue
                if now - t_in > self.queue_deadline_s:
                    tel.count("queue_deadline_expired_total")
                    fut.set_exception(
                        QueueDeadlineExceeded(
                            f"waited {now - t_in:.3f}s for queue capacity "
                            f"(deadline {self.queue_deadline_s:.3f}s)"
                        )
                    )
                    continue
                self._queue.append(item)
                if len(self._queue) >= self.queue_depth:
                    self._flush()
        finally:
            self._pumping = False
            tel.set_gauge("queue_waiters", len(self._waiters))
        self._maybe_clear_overload()

    def _expire_waiters(self) -> None:
        """Waiter-deadline sweep: a blocked publisher past its queue
        deadline fails deterministically — a wedged device can slow
        the broker, never hang its publishers."""
        self._waiter_timer = None
        tel = self.telemetry
        now = tel.clock()
        keep: Deque[tuple] = deque()
        expired = 0
        while self._waiters:
            item = self._waiters.popleft()
            _msg, fut, t_in, _span = item
            if fut.done():
                continue
            if now - t_in > self.queue_deadline_s:
                expired += 1
                fut.set_exception(
                    QueueDeadlineExceeded(
                        f"waited {now - t_in:.3f}s for queue capacity "
                        f"(deadline {self.queue_deadline_s:.3f}s)"
                    )
                )
            else:
                keep.append(item)
        self._waiters = keep
        if expired:
            tel.count("queue_deadline_expired_total", expired)
        tel.set_gauge("queue_waiters", len(self._waiters))
        if self._waiters and not self.closed:
            self._waiter_timer = asyncio.get_running_loop().call_later(
                self.queue_deadline_s / 2, self._expire_waiters
            )
        else:
            self._maybe_clear_overload()

    def _on_deadline(self) -> None:
        self._timer = None
        if self._queue:
            self._flush()

    # --- batch close + pipeline ------------------------------------------

    def _flush(self) -> None:
        """Close the current batch: run the publish hooks, LAUNCH the
        match kernels (no device->host fetch), and push the pending
        batch onto the in-flight window. Collection happens on a later
        loop turn (_drain) or immediately for whatever exceeds the
        pipeline depth. A device fault at launch fails over to a
        host-mode batch — publishers never see it."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._queue = self._queue, []
        # collector pauses must not land inside the launch window (the
        # gen-2-pass-in-a-timed-batch outlier PERF_NOTES r5/r6 chased);
        # the pause spans launch + any forced over-depth collects and
        # restores on exit, so collection happens BETWEEN batches
        gc_tok = self._gc_pause()
        try:
            tel = self.telemetry
            broker = self.broker
            router = self.router
            st = broker.sentinel
            now = tel.clock()
            entries = []
            topics = []
            bspan = None
            # batched-WHERE window: rule predicates hit inside the
            # publish-hook fold defer into one columnar drain when the
            # window closes — the whole coalesced batch shares one
            # column extraction per referenced path
            rb = getattr(broker, "rule_batcher", None)
            win = (
                rb.batch_window()
                if rb is not None and rb.batch_where_enabled
                else nullcontext()
            )
            STAGE_MARK.stage = "coalesce"
            with win:
                for msg, fut, t_in, span in batch:
                    tel.observe_family(
                        "pipeline_queue_wait_seconds", now - t_in
                    )
                    if span is not None and bspan is None and st is not None:
                        bspan = st.batch_span()
                    live = broker._pre_publish(msg)
                    if span is not None:
                        # queue sub-decomposition: submit_wait is
                        # submit()->flush fire; coalesce is this
                        # publish's wait inside the flush fold (its own
                        # hook walk included). submit_wait + coalesce
                        # == queue exactly, by construction — the
                        # sum-to-wall contract starts here.
                        t_end = tel.clock()
                        span.add("queue", t_end - t_in)
                        span.add_sub("submit_wait", now - t_in)
                        span.add_sub("coalesce", t_end - now)
                    entries.append((live, fut, span))
                    if live is not None:
                        topics.append(live.topic)
            STAGE_MARK.stage = ""
            self.batches_total += 1
            self.publishes_total += len(batch)
            if topics:
                self._recent_topics.append(topics[0])
            # match_launch mark: topic encode + kernel dispatch — the
            # submit-path cost the profiler used to file under `other`
            STAGE_MARK.stage = "match_launch"
            try:
                pending = router.match_filters_begin(topics, span=bspan)
            except Exception as e:
                # launch-side device fault (encode/sync/kernel dispatch):
                # re-begin in host mode — the cache probe re-runs (cheap,
                # correct) and finish serves from host truth
                tel.count("breaker_begin_failures_total")
                self._device_failure(e)
                pending = self._host_begin(topics, bspan)
            # device-resolved fanout overlap: topics the match cache
            # answered at begin time have known filter sets NOW — launch
            # their plan resolves immediately so the deduped plan
            # materializes on device while the match hash fetch for the
            # uncached remainder is still in flight
            fanout_pending = None
            STAGE_MARK.stage = "plan_resolve"
            if (
                broker._fanout_device
                and pending.full_out is not None
                and not router.device_suspended
            ):
                seen = set()
                for flts in pending.full_out:
                    if flts is None:
                        continue
                    fkey = tuple(flts)
                    if fkey in seen:
                        continue
                    seen.add(fkey)
                    if broker._plan_fresh(fkey):
                        continue
                    try:
                        h = router.resolve_fanout_begin(
                            fkey, min_fan=broker._fanout_min_fan
                        )
                    except Exception as e:
                        # fanout launch fault: the dispatch path rebuilds
                        # plans host-side — skip the overlap, note the link
                        tel.count("fanout_host_fallback_total")
                        self._device_failure(e)
                        break
                    if h is not None:
                        if fanout_pending is None:
                            fanout_pending = []
                        fanout_pending.append(
                            (fkey, broker._fanout_clock, h)
                        )
            STAGE_MARK.stage = ""
            t_launch = tel.clock()
            if self._ring_track_since is None:
                self._ring_track_since = t_launch
            if self._ring_busy_since is None:
                # empty->nonempty transition: the gap since the last
                # land is device idle time — the timeline's blank space
                self._ring_busy_since = t_launch
                if self._ring_last_land is not None:
                    tel.observe_family(
                        "ring_gap_seconds", t_launch - self._ring_last_land
                    )
            self._inflight.append(
                (pending, entries, fanout_pending, bspan, t_launch)
            )
            self._inflight_pubs += len(entries)
            tel.set_gauge("pipeline_depth", len(self._inflight))
            tel.set_gauge("pipeline_coalesce", len(batch))
            tel.set_gauge("queue_depth", self.outstanding())
            while len(self._inflight) > self.pipeline_depth:
                self._collect_one()
        finally:
            self._gc_resume(gc_tok)
        if self._inflight and not self._drain_scheduled:
            self._drain_scheduled = True
            asyncio.get_running_loop().call_soon(self._drain)

    def _host_begin(self, topics, bspan):
        """Begin a batch with the device forced out of the loop (the
        failover path when match_filters_begin itself raised)."""
        router = self.router
        prev = router.device_suspended
        router.device_suspended = True
        try:
            return router.match_filters_begin(topics, span=bspan)
        finally:
            router.device_suspended = prev

    # seconds between readiness re-probes while the ring head's
    # transfer is still in flight (the loop is yielded, not blocked)
    _RING_POLL_S = 0.0002

    def _head_ready(self) -> bool:
        """True when collecting the ring head will not block: the
        match legs' AND any overlapped fanout resolves' transfer
        tickets have all landed host-side."""
        pending, _entries, fanout_pending, _bspan, _t = self._inflight[0]
        if not self.router.match_finish_ready(pending):
            return False
        if fanout_pending is not None:
            for _fkey, _clock, h in fanout_pending:
                if not h[0].ready():
                    return False
        return True

    def _drain(self) -> None:
        """Collect ring slots in COMPLETION order without ever
        blocking the event loop on a transfer still in flight:
        delivery order stays strictly begin order (the Router's
        finish contract — bit-exactness depends on it), but a head
        whose transfer has not landed yields the loop and re-probes,
        so the host keeps encoding/launching instead of stalling in
        np.asarray. Over-depth slots still force-collect (the ring is
        the backpressure bound)."""
        self._drain_scheduled = False
        while self._inflight:
            if (
                len(self._inflight) > self.pipeline_depth
                or self._head_ready()
            ):
                self._collect_one()
                continue
            self._drain_scheduled = True
            asyncio.get_running_loop().call_later(
                self._RING_POLL_S, self._drain
            )
            return
        self.telemetry.set_gauge("pipeline_depth", 0)

    def _collect_one(self) -> None:
        """Fetch + deliver the OLDEST in-flight batch (begin order).
        A device fault here re-serves the whole batch through the host
        walk; a slow-but-successful device batch past the breaker
        deadline counts toward the breaker without being re-served
        (its results are already correct)."""
        pending, entries, fanout_pending, bspan, t_launch = (
            self._inflight.popleft()
        )
        broker = self.broker
        router = self.router
        st = broker.sentinel
        tel = self.telemetry
        tclock = tel.clock
        device_batch = pending.mode not in ("cached", "host")
        gc_tok = self._gc_pause()
        try:
            # match_fetch mark: device->host transfer + unpack of the
            # match result — the drain-path cost the profiler used to
            # file under `other`
            STAGE_MARK.stage = "match_fetch"
            t0 = tclock()
            try:
                filter_lists = router.match_filters_finish(pending)
            except Exception as e:
                # transient device fault: re-serve the WHOLE batch from
                # host truth — bit-identical by the oracle contract, so
                # publishers never see it; the failure still counts toward
                # the breaker
                tel.count("breaker_fallback_total", len(entries))
                self._device_failure(e)
                fanout_pending = None  # overlapped resolves died with it
                try:
                    filter_lists = router.match_filters_host(pending)
                except Exception as e2:  # host truth failed: nothing left
                    STAGE_MARK.stage = ""
                    tel.count("publish_failures_total", len(entries))
                    for _live, fut, _span in entries:
                        if not fut.done():
                            fut.set_exception(e2)
                    self._ring_land(tclock(), t_launch, "failed", len(entries))
                    self._batch_done(len(entries))
                    return
            else:
                if device_batch and self.breaker_enabled:
                    if (
                        self.breaker_deadline_s
                        and tclock() - t0 > self.breaker_deadline_s
                    ):
                        # slow is a fault even when it is not wrong: the
                        # results serve, the breaker still hears about it
                        tel.count("breaker_deadline_exceeded_total")
                        self._device_failure(None)
                    else:
                        self._device_success()
            STAGE_MARK.stage = ""
            if fanout_pending is not None:
                # install the overlapped plans before delivering: stamped
                # with the clock captured at begin, so a mutation that
                # landed mid-flight leaves them stale-on-arrival and the
                # dispatch below rebuilds — exactness over hit ratio
                STAGE_MARK.stage = "plan_resolve"
                t_res = tclock() if bspan is not None else 0.0
                for fkey, clock, h in fanout_pending:
                    try:
                        plan = router.resolve_fanout_finish(h)
                    except Exception as e:
                        # the dispatch path rebuilds host-side; counted so
                        # a dying link can't fail resolves silently
                        tel.count("fanout_host_fallback_total")
                        self._device_failure(e)
                        continue
                    broker._store_plan(fkey, clock, plan)
                if bspan is not None:
                    bspan.add("resolve", tclock() - t_res)
                STAGE_MARK.stage = ""
            self._ring_land(tclock(), t_launch, pending.mode, len(entries))
            # the vectorized delivery half: ONE window dispatch for the
            # whole collected batch (plan resolution per unique filter
            # set, session-grouped writes) instead of a per-publish
            # _dispatch loop — see Broker.dispatch_window
            results, meta = broker.dispatch_window(
                [e[0] for e in entries],
                filter_lists,
                spans=[e[2] for e in entries],
                capture_errors=True,
            )
            # aggregate completion: consecutive publishes sharing a
            # submit_many aggregate fold into one add_many instead of a
            # per-publish set_result tick
            pend_fut = None
            pend_total = 0
            pend_k = 0

            def _flush_agg() -> None:
                nonlocal pend_fut, pend_total, pend_k
                if pend_fut is None:
                    return
                if type(pend_fut) is _AggregateCount:
                    pend_fut.add_many(pend_total, pend_k)
                elif not pend_fut.done():
                    pend_fut.set_result(pend_total)
                pend_fut = None
                pend_total = 0
                pend_k = 0

            for idx, (live, fut, span) in enumerate(entries):
                n = results[idx]
                if isinstance(n, BaseException):
                    # a delivery-side failure is the publisher's to
                    # see (host bug, not a device fault) — counted,
                    # then propagated
                    _flush_agg()
                    tel.count("publish_failures_total")
                    if not fut.done():
                        fut.set_exception(n)
                    continue
                if live is not None and span is not None and st is not None:
                    if bspan is not None:
                        span.merge(bspan)
                    st.finish_span(span)
                    # shadow-oracle audit of exactly what was served:
                    # the matched filter set + the (filter, dests)
                    # pairs, stamped with the begin generation so churn
                    # mid-flight skips rather than false-positives
                    key, pairs = meta[idx]
                    st.capture_audit(
                        live.topic, key, pairs, pending.gen,
                        span.trace_id,
                    )
                if fut is pend_fut:
                    pend_total += n
                    pend_k += 1
                else:
                    _flush_agg()
                    pend_fut = fut
                    pend_total = n
                    pend_k = 1
            _flush_agg()
            self._batch_done(len(entries))
        finally:
            self._gc_resume(gc_tok)

    def _batch_done(self, n_pubs: int) -> None:
        self._inflight_pubs -= n_pubs
        if self._waiters:
            self._pump_waiters()
        else:
            self._maybe_clear_overload()

    # --- device-occupancy timeline ---------------------------------------

    def _ring_land(
        self, t_land: float, t_launch: float, mode: str, n_pubs: int
    ) -> None:
        """One ring slot landed: record its launch->land span, stamp
        the timeline, and close the busy segment when the ring just
        went empty (the occupancy integral only advances on
        transitions — zero cost while the ring stays busy)."""
        tel = self.telemetry
        self._ring_slots_total += 1
        self._ring_last_land = t_land
        tel.observe_family("ring_slot_span_seconds", t_land - t_launch)
        self._ring_timeline.append(
            {
                "launch": round(t_launch, 6),
                "land": round(t_land, 6),
                "span_ms": round((t_land - t_launch) * 1e3, 4),
                "mode": mode,
                "publishes": n_pubs,
            }
        )
        if not self._inflight and self._ring_busy_since is not None:
            self._ring_busy_accum += t_land - self._ring_busy_since
            self._ring_busy_since = None
            tel.set_gauge("ring_occupancy_ratio", self._ring_occupancy())

    def _ring_occupancy(self) -> float:
        """Busy-time fraction since tracking began: the committed
        answer to 'how idle is the device, really'."""
        since = self._ring_track_since
        if since is None:
            return 0.0
        now = self.telemetry.clock()
        busy = self._ring_busy_accum
        if self._ring_busy_since is not None:
            busy += now - self._ring_busy_since
        elapsed = now - since
        return min(1.0, busy / elapsed) if elapsed > 0 else 0.0

    def ring_status(self) -> Dict:
        out = {
            "slots_total": self._ring_slots_total,
            "occupancy_ratio": round(self._ring_occupancy(), 6),
            "busy_seconds": round(self._ring_busy_accum, 6),
            "timeline": list(self._ring_timeline),
        }
        # mesh microscope: per-chip generalization of the ring ledger
        # (launch→land spans per serving chip + the stage decomposition)
        scope = getattr(
            getattr(self.broker.router, "device_table", None), "scope", None
        )
        if scope is not None:
            out["mesh_scope"] = scope.status()
        return out

    # --- circuit breaker (trip -> degrade -> probe -> resync -> close) ----

    def note_device_failure(self, exc: Optional[BaseException]) -> None:
        """Seam for device faults observed OUTSIDE the engine's own
        batches (the broker's synchronous match/fanout legs): they
        count toward the same breaker."""
        self._device_failure(exc)

    def note_device_success(self) -> None:
        """Sync-path counterpart: a successful device leg resets the
        consecutive-failure count, so sparse transient faults spread
        over hours can never accumulate into a spurious trip."""
        self._device_success()

    def _device_failure(self, exc: Optional[BaseException]) -> None:
        tel = self.telemetry
        tel.count("breaker_device_failures_total")
        if exc is not None:
            self.last_device_error = repr(exc)
        if not self.breaker_enabled:
            return
        shard = getattr(exc, "shard", None)
        if shard is not None:
            # chip-granular fault: per-shard ledger, whole-device
            # breaker untouched (the other shards are fine)
            n = self._shard_failures.get(shard, 0) + 1
            self._shard_failures[shard] = n
            if shard not in self._shard_open and n >= self.breaker_threshold:
                self._trip_shard(int(shard), exc)
            return
        self._consecutive_failures += 1
        tel.set_gauge(
            "breaker_consecutive_failures", self._consecutive_failures
        )
        if (
            self.breaker_state == "closed"
            and self._consecutive_failures >= self.breaker_threshold
        ):
            self._trip_breaker(exc)

    def _device_success(self) -> None:
        if self._consecutive_failures:
            self._consecutive_failures = 0
            self.telemetry.set_gauge("breaker_consecutive_failures", 0)
        # a clean mesh-wide dispatch clears the ledgers of shards that
        # have NOT tripped (sparse transients can't accumulate); open
        # shards stay open — their probe loop owns recovery
        if self._shard_failures:
            for s in list(self._shard_failures):
                if s not in self._shard_open:
                    del self._shard_failures[s]

    def _set_state(self, state: str) -> None:
        self.breaker_state = state
        self.telemetry.set_gauge("breaker_state", _STATE_GAUGE[state])

    def _trip_breaker(self, exc: Optional[BaseException]) -> None:
        """closed -> open: all traffic host-side (degraded-but-
        correct), alarm raised, flight bundle frozen, probe armed."""
        tel = self.telemetry
        self._set_state("open")
        self.router.suspend_device()
        tel.count("breaker_trips_total")
        details = {
            "consecutive_failures": self._consecutive_failures,
            "threshold": self.breaker_threshold,
            "last_error": self.last_device_error,
        }
        log.error(
            "device breaker TRIPPED after %d consecutive failures "
            "(last: %s) — all publish traffic degraded to the host "
            "walk; canary probe armed",
            self._consecutive_failures, self.last_device_error,
        )
        alarms = self._get_alarms()
        if alarms is not None:
            try:
                alarms.ensure(
                    ALARM_BREAKER,
                    details=details,
                    message="XLA device breaker open: publish path "
                            "degraded to host walk",
                )
            except Exception:
                tel.count("breaker_alarm_failures_total")
                log.exception("breaker alarm failed")
        fl = self._get_flight()
        if fl is not None:
            fl.recorder.record("breaker.trip", "", details)
            fl.maybe_trigger("device_breaker_trip", details)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (offline/bench sync path): recovery happens on
            # the next probe_once() a caller drives explicitly
            return
        t = loop.create_task(self._probe_loop())
        self._probe_task = t
        t.add_done_callback(self._probe_done)

    def _probe_done(self, task: "asyncio.Task") -> None:
        if self._probe_task is task:
            self._probe_task = None
        if not task.cancelled() and task.exception() is not None:
            self.telemetry.count("breaker_probe_crashes_total")
            log.error(
                "breaker probe loop died", exc_info=task.exception()
            )

    async def _probe_loop(self) -> None:
        """Bounded-exponential-backoff canary: re-dispatch a sentinel
        batch through the real kernels; on success, full clean resync
        then a VERIFIED canary before closing."""
        backoff = self.probe_backoff_s
        while not self.closed and self.breaker_state == "open":
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2.0, self.probe_backoff_max_s)
            if self.closed or self.breaker_state != "open":
                return
            if self.probe_once():
                return

    def probe_once(self) -> bool:
        """One canary attempt (also the offline/bench entry): link
        canary -> full state resync -> oracle-verified canary ->
        close. Returns True when the breaker closed."""
        tel = self.telemetry
        router = self.router
        tel.count("breaker_probe_total")
        self._set_state("half_open")
        topics = list(self._recent_topics) or ["$breaker/canary"]
        try:
            # step 1: does the link dispatch at all? (stale state OK)
            router.canary_match(topics)
            # step 2: the outage dropped the delta stream — re-upload
            # FULL device state from host truth (quarantine clean-sync
            # machinery), then verify the device answers the oracle
            router.device_resync()
            served = router.canary_match(topics)
            oracle = [sorted(router.match_filters(t)) for t in topics]
            if [sorted(x) for x in served] != oracle:
                raise RuntimeError(
                    "post-resync canary diverged from host oracle"
                )
        except Exception as e:
            tel.count("breaker_probe_failures_total")
            self.last_device_error = repr(e)
            self._set_state("open")
            return False
        self._close_breaker(topics)
        return True

    def _close_breaker(self, canary_topics) -> None:
        tel = self.telemetry
        self._consecutive_failures = 0
        tel.set_gauge("breaker_consecutive_failures", 0)
        self._set_state("closed")
        self.router.resume_device()
        tel.count("breaker_recoveries_total")
        log.warning(
            "device breaker CLOSED: full state re-uploaded, canary "
            "verified against host oracle on %d topics",
            len(canary_topics),
        )
        alarms = self._get_alarms()
        if alarms is not None:
            alarms.ensure_deactivated(ALARM_BREAKER)
        fl = self._get_flight()
        if fl is not None:
            fl.recorder.record(
                "breaker.close", "", {"canary_topics": len(canary_topics)}
            )

    # --- shard breaker (chip-granular failure domain) ---------------------

    @property
    def open_shards(self) -> Set[int]:
        return set(self._shard_open)

    def _trip_shard(self, shard: int, exc: Optional[BaseException]) -> None:
        """One chip crossed the threshold: suspend ONLY its slice
        (host overlay), then evacuate its row/bucket range onto the
        survivor mesh so service returns to full device speed at N-1,
        and arm a per-shard recovery probe. The whole-device breaker
        stays closed — the other chips never stop serving."""
        tel = self.telemetry
        self._shard_open.add(shard)
        tel.count("breaker_shard_trips_total")
        tel.set_gauge("breaker_open_shards", len(self._shard_open))
        self.router.suspend_shard(shard)
        details = {
            "shard": shard,
            "failures": self._shard_failures.get(shard, 0),
            "threshold": self.breaker_threshold,
            "last_error": self.last_device_error,
        }
        log.error(
            "shard breaker TRIPPED for shard %d (last: %s) — slice "
            "host-overlaid, evacuating onto survivor mesh",
            shard, self.last_device_error,
        )
        alarms = self._get_alarms()
        if alarms is not None:
            try:
                alarms.ensure(
                    ALARM_BREAKER,
                    details=details,
                    message=f"XLA shard breaker open: shard {shard} "
                            "slice degraded, evacuating",
                )
            except Exception:
                tel.count("breaker_alarm_failures_total")
                log.exception("shard breaker alarm failed")
        fl = self._get_flight()
        if fl is not None:
            fl.recorder.record("breaker.shard_trip", "", details)
            fl.maybe_trigger("device_breaker_trip", details)
        try:
            # live evacuation: re-shard over survivors + full re-upload
            # from host truth; on failure the host overlay stays as the
            # degraded-but-correct fallback until the probe heals it
            if self.router.evacuate_shard(shard):
                tel.count("breaker_shard_evacuations_total")
                # recompile the survivor-mesh kernel shapes off the
                # deadline-gated serving path
                self.router.warmup_shapes(max_batch=64)
        except Exception:
            tel.count("breaker_shard_evacuation_failures_total")
            log.exception(
                "shard %d evacuation failed; slice stays host-overlaid",
                shard,
            )
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # offline path: caller drives probe_shard_once()
        t = loop.create_task(self._shard_probe_loop(shard))
        self._shard_probe_tasks[shard] = t
        t.add_done_callback(
            lambda task, s=shard: self._shard_probe_done(s, task)
        )

    def _shard_probe_done(self, shard: int, task: "asyncio.Task") -> None:
        if self._shard_probe_tasks.get(shard) is task:
            del self._shard_probe_tasks[shard]
        if not task.cancelled() and task.exception() is not None:
            self.telemetry.count("breaker_probe_crashes_total")
            log.error(
                "shard %d probe loop died", shard,
                exc_info=task.exception(),
            )

    async def _shard_probe_loop(self, shard: int) -> None:
        backoff = self.probe_backoff_s
        while not self.closed and shard in self._shard_open:
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2.0, self.probe_backoff_max_s)
            if self.closed or shard not in self._shard_open:
                return
            if self.probe_shard_once(shard):
                return

    def probe_shard_once(self, shard: int) -> bool:
        """One recovery attempt for an evacuated chip: direct link
        probe -> rebalance back to the full mesh (full state re-upload)
        -> oracle-verified canary -> close. On canary divergence the
        chip is re-evacuated — it re-earns trust, never gets it."""
        tel = self.telemetry
        router = self.router
        tel.count("breaker_probe_total")
        topics = list(self._recent_topics) or ["$breaker/canary"]
        try:
            # step 1: is the chip's link back? (raises while sticky)
            router.probe_shard(shard)
            # step 2: rebalance back to N and verify against the oracle
            router.rebalance_shard(shard)
            served = router.canary_match(topics)
            oracle = [sorted(router.match_filters(t)) for t in topics]
            if [sorted(x) for x in served] != oracle:
                raise RuntimeError(
                    f"post-rebalance canary diverged on shard {shard}"
                )
        except Exception as e:
            tel.count("breaker_probe_failures_total")
            self.last_device_error = repr(e)
            dt = router.device_table
            if shard not in getattr(dt, "lost_shards", set()):
                # rebalance half-landed or canary diverged: evacuate
                # again so serving stays on the verified survivor mesh
                with contextlib.suppress(Exception):
                    router.evacuate_shard(shard)
            return False
        self._close_shard(shard, topics)
        return True

    def _close_shard(self, shard: int, canary_topics) -> None:
        tel = self.telemetry
        self._shard_open.discard(shard)
        self._shard_failures.pop(shard, None)
        tel.set_gauge("breaker_open_shards", len(self._shard_open))
        tel.count("breaker_shard_recoveries_total")
        log.warning(
            "shard breaker CLOSED for shard %d: rebalanced back to "
            "full mesh, canary verified on %d topics",
            shard, len(canary_topics),
        )
        if not self._shard_open and self.breaker_state == "closed":
            alarms = self._get_alarms()
            if alarms is not None:
                alarms.ensure_deactivated(ALARM_BREAKER)
        fl = self._get_flight()
        if fl is not None:
            fl.recorder.record(
                "breaker.shard_close", "",
                {"shard": shard, "canary_topics": len(canary_topics)},
            )

    # --- lifecycle --------------------------------------------------------

    async def drain(self) -> None:
        """Flush the open batch, admit + serve every blocked waiter,
        and collect everything in flight."""
        while self._queue or self._inflight or self._waiters:
            if self._waiters:
                self._pump_waiters()
            if self._queue:
                self._flush()
            while self._inflight:
                self._collect_one()
            if not (self._queue or self._waiters):
                break
        await asyncio.sleep(0)  # let resolved futures' awaiters run

    async def stop(self, drain: bool = True) -> None:
        """Stop the engine. drain=True (default) completes everything
        first; drain=False is the abort path: in-flight batches still
        complete (their kernels already launched), but queued and
        blocked publishers fail deterministically with EngineStopped —
        never a silent hang."""
        if self.closed:
            return
        if drain:
            await self.drain()
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._waiter_timer is not None:
            self._waiter_timer.cancel()
            self._waiter_timer = None
        while self._inflight:
            self._collect_one()
        aborted = 0
        err = EngineStopped("dispatch engine stopped")
        for _msg, fut, _t, _span in self._queue:
            if not fut.done():
                fut.set_exception(err)
                aborted += 1
        self._queue = []
        while self._waiters:
            _msg, fut, _t, _span = self._waiters.popleft()
            if not fut.done():
                fut.set_exception(err)
                aborted += 1
        if aborted:
            self.telemetry.count("queue_aborted_total", aborted)
        if self._probe_task is not None:
            self._probe_task.cancel()
            with contextlib.suppress(Exception, asyncio.CancelledError):
                await self._probe_task
            self._probe_task = None
        for t in list(self._shard_probe_tasks.values()):
            t.cancel()
            with contextlib.suppress(Exception, asyncio.CancelledError):
                await t
        self._shard_probe_tasks.clear()
        if self.gc_guard and self.warmed:
            # hand the frozen steady state back to the collector —
            # a stopped engine's broker graph must stay reclaimable
            gc.unfreeze()
        await asyncio.sleep(0)

    def status(self) -> dict:
        cache = self.router.match_cache
        counters = getattr(self.telemetry, "counters", {})
        return {
            "queue_depth": self.queue_depth,
            "deadline_ms": self.deadline_s * 1e3,
            "pipeline_depth": self.pipeline_depth,
            "queued": len(self._queue),
            "inflight": len(self._inflight),
            "batches_total": self.batches_total,
            "publishes_total": self.publishes_total,
            "coalesce_factor": round(
                self.publishes_total / self.batches_total, 3
            ) if self.batches_total else 0.0,
            "breaker": {
                "enabled": self.breaker_enabled,
                "state": self.breaker_state,
                "threshold": self.breaker_threshold,
                "consecutive_failures": self._consecutive_failures,
                "deadline_ms": self.breaker_deadline_s * 1e3,
                "trips": counters.get("breaker_trips_total", 0),
                "recoveries": counters.get("breaker_recoveries_total", 0),
                "fallback_publishes": counters.get(
                    "breaker_fallback_total", 0
                ),
                "degraded_batches": counters.get(
                    "breaker_degraded_batches_total", 0
                ),
                "probes": counters.get("breaker_probe_total", 0),
                "probe_failures": counters.get(
                    "breaker_probe_failures_total", 0
                ),
                "last_device_error": self.last_device_error,
            },
            "shard_breaker": {
                "open_shards": sorted(self._shard_open),
                "failures": dict(sorted(self._shard_failures.items())),
                "lost_shards": sorted(
                    getattr(self.router.device_table, "lost_shards", ())
                ),
                "shard_gen": getattr(
                    self.router.device_table, "shard_gen", 0
                ),
                "trips": counters.get("breaker_shard_trips_total", 0),
                "evacuations": counters.get(
                    "breaker_shard_evacuations_total", 0
                ),
                "recoveries": counters.get(
                    "breaker_shard_recoveries_total", 0
                ),
            },
            "admission": {
                "max_depth": self.queue_max_depth,
                "low_watermark": self.queue_low_watermark,
                "policy": self.queue_policy,
                "queue_deadline_ms": self.queue_deadline_s * 1e3,
                "outstanding": self.outstanding(),
                "waiters": len(self._waiters),
                "overloaded": self._overloaded,
                "shed": counters.get("queue_shed_total", 0),
                "blocked": counters.get("queue_blocked_total", 0),
                "deadline_expired": counters.get(
                    "queue_deadline_expired_total", 0
                ),
            },
            "match_cache": None if cache is None else {
                "capacity": cache.capacity,
                "entries": len(cache),
                "hits": cache.hits,
                "misses": cache.misses,
                "evictions": cache.evictions,
                "hit_ratio": round(cache.hit_ratio(), 6),
            },
            "ring": self.ring_status(),
        }
