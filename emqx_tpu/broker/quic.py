"""QUIC v1 transport (RFC 9000) carrying MQTT on stream 0.

The reference's MQTT-over-QUIC rides the quicer NIF around MsQuic
(apps/emqx/src/emqx_quic_connection.erl:1-346, emqx_listeners.erl:
193-210, single-stream mode: one client-initiated bidirectional
stream carries the MQTT byte stream). No QUIC library ships in this
image, so the transport is implemented from the RFCs on the
`cryptography` primitives: packet protection and the TLS 1.3
handshake live in quic_crypto.py / quic_tls.py; this module is the
connection machinery — long/short header packets with coalescing,
CRYPTO / STREAM / ACK / HANDSHAKE_DONE / CONNECTION_CLOSE frames,
per-space packet numbers, and ordered stream reassembly.

Scope: the profile our endpoints need, including the RFC 9002
recovery machinery — per-space sent-packet tracking, packet-threshold
loss declaration off ACK ranges, smoothed-RTT PTO timers that send
PROBES (not full-flight retransmits) with exponential backoff, and
retransmission of lost CRYPTO/STREAM ranges — plus NewReno
congestion control (RFC 9002 §7: slow start / congestion avoidance /
halving once per recovery period), so a lossy-but-fat link
retransmits under a cwnd, not at line rate. Flow control is real
both ways: finite windows are advertised and ENFORCED on receive
(FLOW_CONTROL_ERROR on overrun), replenished with
MAX_DATA/MAX_STREAM_DATA per stream as the app consumes, and the
peer's advertised windows gate our sends. TLS-PSK (psk_dhe_ke)
authenticates clients against a PskStore when the listener carries
one. Stream 0 is the MQTT control stream (the reference's
single-stream mode); additional client-initiated bidirectional
streams are served as DATA streams with per-stream MQTT parsing and
same-stream replies (multi-stream mode, emqx_quic_data_stream.erl)."""

from __future__ import annotations

import asyncio
import logging
import os
import struct
from typing import Callable, Dict, List, Optional, Tuple

from .quic_crypto import (
    DirectionKeys, dec_varint, enc_varint, encode_pn, initial_keys,
    protect, unprotect,
)
from .quic_tls import TlsClient, TlsServer, TlsError

log = logging.getLogger("emqx_tpu.broker.quic")

VERSION_V1 = 0x00000001
LEVELS = ("initial", "handshake", "app")

FT_PADDING = 0x00
FT_PING = 0x01
FT_ACK = 0x02
FT_CRYPTO = 0x06
FT_STREAM_BASE = 0x08  # 0x08..0x0f
FT_MAX_DATA = 0x10
FT_MAX_STREAM_DATA = 0x11

# RFC 9002 minimum-viable recovery knobs
K_PACKET_THRESHOLD = 3  # reordering threshold (§6.1.1)
PTO_INITIAL = 0.3  # s; doubles per consecutive timeout (§6.2)
PTO_MAX = 8.0
# flow-control windows we ADVERTISE (and therefore enforce on RX);
# MAX_DATA / MAX_STREAM_DATA replenish as the app consumes (§4)
FC_CONN_WINDOW = 1 << 20
FC_STREAM_WINDOW = 1 << 19
# per-packet STREAM chunk bound: a frame larger than one UDP datagram
# can never be sent (EMSGSIZE) and would retransmit forever
MAX_STREAM_CHUNK = 1200
FT_CONN_CLOSE = 0x1C
FT_CONN_CLOSE_APP = 0x1D
FT_HANDSHAKE_DONE = 0x1E

_LONG_TYPE = {"initial": 0x00, "handshake": 0x02}


def encode_transport_params(scid: bytes,
                            odcid: Optional[bytes] = None) -> bytes:
    def tp(tid: int, val: bytes) -> bytes:
        return enc_varint(tid) + enc_varint(len(val)) + val

    out = b""
    if odcid is not None:
        out += tp(0x00, odcid)  # original_destination_connection_id
    out += tp(0x01, enc_varint(30_000))  # max_idle_timeout ms
    out += tp(0x03, enc_varint(65527))  # max_udp_payload_size
    # finite windows, replenished with MAX_DATA / MAX_STREAM_DATA as
    # the app consumes (RFC 9000 §4) — and ENFORCED on receive
    out += tp(0x04, enc_varint(FC_CONN_WINDOW))  # initial_max_data
    out += tp(0x05, enc_varint(FC_STREAM_WINDOW))  # max_stream_data bidi local
    out += tp(0x06, enc_varint(FC_STREAM_WINDOW))  # bidi remote
    out += tp(0x07, enc_varint(FC_STREAM_WINDOW))  # uni
    out += tp(0x08, enc_varint(16))  # initial_max_streams_bidi
    out += tp(0x09, enc_varint(16))  # uni
    out += tp(0x0F, scid)  # initial_source_connection_id
    return out


class _SentPacket:
    """Bookkeeping for one ack-eliciting packet in flight."""

    __slots__ = ("time", "crypto", "stream", "hs_done", "ping", "fc",
                 "size")

    def __init__(self, time, crypto=None, stream=None, hs_done=False,
                 ping=False, fc=False):
        self.time = time
        self.crypto = crypto  # (offset, length) into crypto_out
        self.stream = stream  # (stream id, abs offset, length)
        self.hs_done = hs_done
        self.ping = ping
        self.fc = fc  # carried a MAX_DATA/MAX_STREAM_DATA update
        self.size = 0  # wire bytes (congestion accounting)


class _StreamState:
    """Per-stream send/receive state (RFC 9000 §2). Stream 0 is the
    MQTT control stream (the reference's single-stream mode); further
    client-initiated bidirectional streams (4, 8, ...) are the
    multi-stream mode's data streams (emqx_quic_data_stream.erl)."""

    __slots__ = ("rx", "rx_off", "out", "sent", "unacked", "rtx",
                 "fin_rcvd", "tx_max", "rx_max", "consumed", "rx_hwm")

    def __init__(self, tx_max: int, rx_max: int) -> None:
        self.rx: Dict[int, bytes] = {}
        self.rx_off = 0
        self.out = b""  # unsent suffix
        self.sent = 0  # absolute stream offset already sent
        self.unacked: Dict[int, bytes] = {}
        self.rtx: List[Tuple[int, bytes]] = []
        self.fin_rcvd = False
        self.tx_max = tx_max  # peer's allowance for OUR sends
        self.rx_max = rx_max  # our advertised window
        self.consumed = 0
        self.rx_hwm = 0  # highest received offset (FC accounting)


class _Space:
    """One packet-number space (initial / handshake / app)."""

    def __init__(self) -> None:
        self.rx: Optional[DirectionKeys] = None
        self.tx: Optional[DirectionKeys] = None
        self.next_pn = 0
        self.largest_rx = -1
        self.received: set = set()
        self.ack_due = False
        self.crypto_out = b""
        self.crypto_sent = 0
        self.crypto_in: Dict[int, bytes] = {}
        self.crypto_in_off = 0
        # --- loss recovery (RFC 9002) ---
        self.sent: Dict[int, _SentPacket] = {}
        self.largest_acked = -1
        self.crypto_rtx: List[Tuple[int, int]] = []  # lost (off, len)
        self.ping_due = False
        self.last_eliciting_sent = 0.0
        self.pto_count = 0


class QuicConnection:
    """Role-shared connection core. The owner pumps:
    datagram_received(data) -> None and flush() -> [datagrams]."""

    def __init__(self, is_server: bool, scid: bytes, dcid: bytes):
        self.is_server = is_server
        self.scid = scid  # our CID (peer addresses us with this)
        self.dcid = dcid  # peer's CID
        self.spaces = {lvl: _Space() for lvl in LEVELS}
        self.tls = None  # set by subclass
        # per-stream state; stream 0 always exists (control stream)
        self._init_tx_max_stream = 1 << 14
        self.streams: Dict[int, _StreamState] = {}
        self._stream(0)
        # streams whose MAX_STREAM_DATA replenish is due
        self._fc_stream_due: set = set()
        # --- flow control (RFC 9000 §4) ---
        # peer's allowance for OUR sends (from its transport params /
        # MAX_DATA); conservative until params parse
        self.tx_max_data = 1 << 14
        self._peer_params_seen = False
        # OUR advertised connection window (enforced on receive,
        # replenished as the app consumes)
        self.rx_max_data = FC_CONN_WINDOW
        self._rx_consumed = 0
        self._rx_hwm_total = 0  # sum of per-stream receive high-water marks
        self._fc_update_due = False
        self._clock = __import__("time").monotonic
        self.on_stream_data: Optional[Callable[[bytes], None]] = None
        # multi-stream seam: inbound bytes for sid != 0 (data streams)
        self.on_data_stream: Optional[Callable[[int, bytes], None]] = None
        self.on_close: Optional[Callable[[], None]] = None
        self.handshake_done = False
        self.closed = False
        self.close_pending: Optional[Tuple[int, str]] = None
        # --- congestion control (RFC 9002 §7, NewReno) ---
        self.max_datagram_size = 1200
        self.cwnd = 10 * self.max_datagram_size
        self.ssthresh = float("inf")
        self.bytes_in_flight = 0
        self._recovery_start = 0.0  # packets sent before this don't
        # trigger a NEW congestion event (once per RTT, §7.3.1)
        # PTO probes may exceed cwnd (§7.5) — but ONLY probes, one
        # credit per fired PTO; threshold-loss retransmissions wait
        # for window room like everything else
        self._probe_credit = 0
        # total stream bytes sent (connection-level MAX_DATA is a sum
        # across streams, not per stream)
        self.tx_sent_total = 0
        # --- RTT estimate (RFC 9002 §5) ---
        self.srtt: Optional[float] = None
        self.rttvar = 0.0

    MAX_STREAMS = 32  # accepted concurrent streams per connection
    # (DoS bound: each stream can buffer up to FC_STREAM_WINDOW of
    # reassembly; the reference's quicer listener caps streams too)

    def _stream(self, sid: int) -> _StreamState:
        st = self.streams.get(sid)
        if st is None:
            st = self.streams[sid] = _StreamState(
                self._init_tx_max_stream, FC_STREAM_WINDOW
            )
        return st

    # --- stream-0 back-compat surface (single-stream callers/tests) ---
    @property
    def stream_out(self) -> bytes:
        return self.streams[0].out

    @property
    def stream_sent(self) -> int:
        return self.streams[0].sent

    @property
    def stream_fin_rcvd(self) -> bool:
        return self.streams[0].fin_rcvd

    @property
    def rx_max_stream(self) -> int:
        return self.streams[0].rx_max

    @rx_max_stream.setter
    def rx_max_stream(self, v: int) -> None:
        self.streams[0].rx_max = v

    @property
    def tx_max_stream(self) -> int:
        return self.streams[0].tx_max

    @tx_max_stream.setter
    def tx_max_stream(self, v: int) -> None:
        self.streams[0].tx_max = v

    def _maybe_parse_peer_params(self) -> None:
        if self._peer_params_seen or self.tls is None:
            return
        raw = getattr(self.tls, "peer_transport_params", None)
        if not raw:
            return
        off = 0
        params = {}
        try:
            while off < len(raw):
                tid, off = dec_varint(raw, off)
                ln, off = dec_varint(raw, off)
                params[tid] = raw[off : off + ln]
                off += ln
        except Exception:
            return
        def vint(tid, default):
            v = params.get(tid)
            if not v:
                return default
            try:
                return dec_varint(v, 0)[0]
            except Exception:
                return default
        self.tx_max_data = vint(0x04, self.tx_max_data)
        # streams here are client-initiated bidi: the sender honors the
        # receiver's bidi_remote (server side) / bidi_local (client)
        tid = 0x06 if not self.is_server else 0x05
        init_max = vint(tid, self._init_tx_max_stream)
        self._init_tx_max_stream = init_max
        for st in self.streams.values():
            st.tx_max = max(st.tx_max, init_max)
        self._peer_params_seen = True

    # --- frame/packet building -----------------------------------------

    def _build_packet(self, level: str, frames: bytes) -> bytes:
        # header protection samples 16 bytes starting 4 bytes past the
        # pn offset: with a 2-byte pn the ciphertext (payload + 16-byte
        # tag) must be >= 18, so tiny frames pad up (RFC 9001 §5.4.2)
        if len(frames) < 3:
            frames += b"\x00" * (3 - len(frames))
        sp = self.spaces[level]
        pn = sp.next_pn
        sp.next_pn += 1
        if level == "app":
            header = bytes([0x41]) + self.dcid + encode_pn(pn)
            pn_off = 1 + len(self.dcid)
        else:
            flags = 0xC1 | (_LONG_TYPE[level] << 4)
            header = bytes([flags]) + struct.pack(">I", VERSION_V1)
            header += bytes([len(self.dcid)]) + self.dcid
            header += bytes([len(self.scid)]) + self.scid
            if level == "initial":
                header += enc_varint(0)  # token length
            header += enc_varint(len(frames) + 2 + 16)  # pn + payload + tag
            pn_off = len(header)
            header += encode_pn(pn)
        return protect(sp.tx, header, pn, frames, pn_off), pn

    def _ack_frame(self, sp: _Space) -> bytes:
        largest = sp.largest_rx
        first = 0
        while (largest - first - 1) in sp.received:
            first += 1
        return (
            bytes([FT_ACK]) + enc_varint(largest) + enc_varint(0)
            + enc_varint(0) + enc_varint(first)
        )

    def _pending_frames(self, level: str):
        """-> (frames bytes, _SentPacket meta | None). Meta is non-None
        when the packet is ack-eliciting (needs loss tracking)."""
        sp = self.spaces[level]
        out = b""
        meta = None

        def mark(**kw):
            nonlocal meta
            if meta is None:
                meta = _SentPacket(self._clock())
            for k, v in kw.items():
                setattr(meta, k, v)

        if sp.ack_due and sp.largest_rx >= 0:
            out += self._ack_frame(sp)
            sp.ack_due = False
        if sp.ping_due:
            out += bytes([FT_PING])
            sp.ping_due = False
            mark(ping=True)
        # retransmit declared-lost CRYPTO ranges first (RFC 9002 §6.3)
        if sp.crypto_rtx:
            coff, clen = sp.crypto_rtx.pop(0)
            chunk = sp.crypto_out[coff : coff + clen]
            out += (
                bytes([FT_CRYPTO]) + enc_varint(coff)
                + enc_varint(len(chunk)) + chunk
            )
            mark(crypto=(coff, clen))
        elif sp.crypto_sent < len(sp.crypto_out):
            coff = sp.crypto_sent
            chunk = sp.crypto_out[coff:]
            out += (
                bytes([FT_CRYPTO]) + enc_varint(coff)
                + enc_varint(len(chunk)) + chunk
            )
            sp.crypto_sent = len(sp.crypto_out)
            mark(crypto=(coff, len(chunk)))
        if self.close_pending is not None and level != "app" and (
            self.spaces["app"].tx is None
        ):
            # a handshake-time failure must still tell the peer (RFC
            # 9000 §10.2.3): transport-level close at this level
            code, reason = self.close_pending
            r = reason.encode()[:64]
            out += (
                bytes([FT_CONN_CLOSE]) + enc_varint(code) + enc_varint(0)
                + enc_varint(len(r)) + r
            )
            self.close_pending = None
            self.closed = True
        if level == "app":
            if self.handshake_done and self.is_server and not getattr(
                self, "_hs_done_sent", False
            ):
                out += bytes([FT_HANDSHAKE_DONE])
                self._hs_done_sent = True
                mark(hs_done=True)
            if self._fc_update_due or self._fc_stream_due:
                # replenish the peer's send windows as the app consumed
                self.rx_max_data = self._rx_consumed + FC_CONN_WINDOW
                out += bytes([FT_MAX_DATA]) + enc_varint(self.rx_max_data)
                fc_sids = sorted(self._fc_stream_due or {0})
                for sid in fc_sids:
                    st = self._stream(sid)
                    st.rx_max = st.consumed + FC_STREAM_WINDOW
                    out += (
                        bytes([FT_MAX_STREAM_DATA]) + enc_varint(sid)
                        + enc_varint(st.rx_max)
                    )
                self._fc_update_due = False
                self._fc_stream_due.clear()
                # fc records WHICH stream windows rode this packet so
                # a loss re-advertises exactly those (a lost data-
                # stream MAX_STREAM_DATA would otherwise deadlock it)
                mark(fc=tuple(fc_sids))
            self._maybe_parse_peer_params()
            # congestion window (RFC 9002 §7): new data AND threshold-
            # loss retransmissions are gated by cwnd (a halved window
            # must not re-burst the lost flight at line rate); only
            # PTO PROBES may exceed it (§7.5), one per fired PTO via
            # _probe_credit — without that exemption a fully
            # blackholed window deadlocks recovery.
            cc_room = self.cwnd - self.bytes_in_flight
            can_send = cc_room > 0 or self.bytes_in_flight == 0
            use_probe = False
            if not can_send and self._probe_credit > 0:
                can_send = use_probe = True
            stream_frame = None  # (sid, off, chunk)
            if can_send:
                for sid in sorted(self.streams):
                    st = self.streams[sid]
                    # retransmit lost chunks before new data
                    if st.rtx:
                        s_off, chunk = st.rtx.pop(0)
                        if len(chunk) > MAX_STREAM_CHUNK:  # legacy oversize
                            st.rtx.insert(
                                0,
                                (
                                    s_off + MAX_STREAM_CHUNK,
                                    chunk[MAX_STREAM_CHUNK:],
                                ),
                            )
                            chunk = chunk[:MAX_STREAM_CHUNK]
                        stream_frame = (sid, s_off, chunk)
                        st.unacked[s_off] = chunk
                        break
                    if st.out:
                        # peer flow control: the stream window bounds
                        # this stream's offset, the CONNECTION window
                        # bounds the SUM across streams (§4.1)
                        allowance = max(
                            0,
                            min(
                                st.tx_max - st.sent,
                                self.tx_max_data - self.tx_sent_total,
                            ),
                        )
                        chunk = st.out[:min(allowance, MAX_STREAM_CHUNK)]
                        if chunk:
                            stream_frame = (sid, st.sent, chunk)
                            st.unacked[st.sent] = chunk
                            st.sent += len(chunk)
                            self.tx_sent_total += len(chunk)
                            st.out = st.out[len(chunk):]
                            break
            if stream_frame is not None and use_probe:
                self._probe_credit -= 1
            if stream_frame is not None:
                sid, s_off, chunk = stream_frame
                out += (
                    bytes([FT_STREAM_BASE | 0x04 | 0x02])  # off+len
                    + enc_varint(sid)
                    + enc_varint(s_off)
                    + enc_varint(len(chunk)) + chunk
                )
                mark(stream=(sid, s_off, len(chunk)))
            if self.close_pending is not None:
                code, reason = self.close_pending
                r = reason.encode()[:64]
                out += (
                    bytes([FT_CONN_CLOSE_APP]) + enc_varint(code)
                    + enc_varint(len(r)) + r
                )
                self.close_pending = None
                self.closed = True
        return out, meta

    def flush(self) -> List[bytes]:
        """Datagrams ready to send (levels coalesced). Loops per level
        until drained (retransmissions emit one range per packet)."""
        dgrams: List[bytes] = []
        while True:
            dgram = b""
            for level in LEVELS:
                sp = self.spaces[level]
                if sp.tx is None:
                    continue
                frames, meta = self._pending_frames(level)
                if not frames:
                    continue
                if level == "initial" and not self.is_server:
                    # client Initials pad the DATAGRAM to >=1200 (RFC
                    # 9000 §14.1); header+tag overhead ~44B
                    need = 1200 - len(frames) - 28
                    if need > 0:
                        frames += b"\x00" * need
                pkt, pn = self._build_packet(level, frames)
                dgram += pkt
                if meta is not None:
                    meta.size = len(pkt)
                    self.bytes_in_flight += meta.size
                    sp.sent[pn] = meta
                    sp.last_eliciting_sent = meta.time
            if not dgram:
                return dgrams
            dgrams.append(dgram)

    # --- receive --------------------------------------------------------

    def datagram_received(self, data: bytes) -> None:
        off = 0
        while off < len(data) and not self.closed:
            consumed = self._packet_received(data[off:])
            if consumed <= 0:
                break
            off += consumed

    def _packet_received(self, data: bytes) -> int:
        first = data[0]
        if first & 0x80:  # long header
            version = struct.unpack_from(">I", data, 1)[0]
            if version != VERSION_V1:
                return -1
            ptype = (first & 0x30) >> 4
            off = 5
            dcid_len = data[off]
            off += 1 + dcid_len
            scid_len = data[off]
            peer_scid = data[off + 1 : off + 1 + scid_len]
            off += 1 + scid_len
            if ptype == 0:  # initial
                tok_len, off = dec_varint(data, off)
                off += tok_len
                level = "initial"
            elif ptype == 2:
                level = "handshake"
            else:
                return -1  # 0-RTT/Retry unsupported
            length, off = dec_varint(data, off)
            total = off + length
            if self.dcid == b"" or level == "initial":
                self.dcid = peer_scid  # latch the peer's CID
            sp = self.spaces[level]
            if sp.rx is None:
                return total
            try:
                pn, payload = unprotect(
                    sp.rx, data[:total], off, sp.largest_rx
                )
            except Exception:
                return total  # undecryptable: drop silently (RFC 9001)
            self._accept(level, sp, pn, payload)
            return total
        # short header: consumes the remainder of the datagram
        sp = self.spaces["app"]
        if sp.rx is None:
            return -1
        pn_off = 1 + len(self.scid)
        try:
            pn, payload = unprotect(sp.rx, data, pn_off, sp.largest_rx)
        except Exception:
            return -1
        self._accept("app", sp, pn, payload)
        return len(data)

    def _accept(self, level: str, sp: _Space, pn: int, payload: bytes) -> None:
        if pn in sp.received:
            return
        sp.received.add(pn)
        sp.largest_rx = max(sp.largest_rx, pn)
        if len(sp.received) > 256:
            # acks only describe the contiguous run below largest_rx;
            # anything 256 behind can never matter again
            floor = sp.largest_rx - 256
            sp.received = {p for p in sp.received if p >= floor}
        if self._handle_frames(level, payload):
            sp.ack_due = True

    # --- frames ---------------------------------------------------------

    def _handle_frames(self, level: str, payload: bytes) -> bool:
        """Returns True if any frame was ack-eliciting."""
        off = 0
        eliciting = False
        n = len(payload)
        while off < n:
            ft = payload[off]
            off += 1
            if ft == FT_PADDING:
                continue
            if ft == FT_PING:
                eliciting = True
                continue
            if ft == FT_ACK:
                largest, off = dec_varint(payload, off)
                _delay, off = dec_varint(payload, off)
                rc, off = dec_varint(payload, off)
                first, off = dec_varint(payload, off)
                # ranges stay as (lo, hi) BOUNDS — the varints are
                # peer-controlled up to 2^62; materializing them as a
                # set would be a one-frame memory-exhaustion DoS
                ranges = [(largest - first, largest)]
                lo = largest - first
                for i in range(rc):
                    gap, off = dec_varint(payload, off)
                    rng, off = dec_varint(payload, off)
                    if i < 1024:  # DoS cap on TRACKED ranges; the rest
                        hi = lo - gap - 2  # still parse (frame sync).
                        ranges.append((hi - rng, hi))
                        lo = hi - rng
                    # beyond the cap (a pathologically lossy link),
                    # unmatched acked packets are later threshold-lost
                    # and retransmit — duplicates the receiver already
                    # tolerates (ADVICE r4: bandwidth, not corruption)
                self._on_ack(level, ranges)
                continue
            if ft == FT_CRYPTO:
                coff, off = dec_varint(payload, off)
                clen, off = dec_varint(payload, off)
                self._crypto_in(level, coff, payload[off : off + clen])
                off += clen
                eliciting = True
                continue
            if FT_STREAM_BASE <= ft <= 0x0F:
                sid, off = dec_varint(payload, off)
                s_off = 0
                if ft & 0x04:
                    s_off, off = dec_varint(payload, off)
                if ft & 0x02:
                    slen, off = dec_varint(payload, off)
                else:
                    slen = n - off
                data = payload[off : off + slen]
                off += slen
                self._stream_in(sid, s_off, data, bool(ft & 0x01))
                eliciting = True
                continue
            if ft in (FT_CONN_CLOSE, FT_CONN_CLOSE_APP):
                code, off = dec_varint(payload, off)
                if ft == FT_CONN_CLOSE:
                    _ft2, off = dec_varint(payload, off)
                rlen, off = dec_varint(payload, off)
                off += rlen
                self._closed_by_peer()
                continue
            if ft == FT_HANDSHAKE_DONE:
                self.handshake_done = True
                eliciting = True
                continue
            if ft == FT_MAX_DATA:
                v, off = dec_varint(payload, off)
                self.tx_max_data = max(self.tx_max_data, v)
                eliciting = True
                continue
            if ft == FT_MAX_STREAM_DATA:
                sid, off = dec_varint(payload, off)
                v, off = dec_varint(payload, off)
                # only update KNOWN streams — a flood of window frames
                # for arbitrary ids must not allocate state
                st = self.streams.get(sid)
                if st is not None:
                    st.tx_max = max(st.tx_max, v)
                eliciting = True
                continue
            if ft in (0x12, 0x13):  # MAX_STREAMS
                _v, off = dec_varint(payload, off)
                eliciting = True
                continue
            if ft in (0x18,):  # NEW_CONNECTION_ID: skip fields
                _seq, off = dec_varint(payload, off)
                _rpt, off = dec_varint(payload, off)
                cl = payload[off]
                off += 1 + cl + 16
                eliciting = True
                continue
            # RFC 9000 §12.4: an unknown frame type is a
            # FRAME_ENCODING_ERROR — fail LOUDLY; silently skipping
            # would drop coalesced STREAM/CRYPTO data with no
            # retransmit to recover it
            log.warning("quic: unknown frame 0x%02x — closing", ft)
            self.close(0x07, f"unknown frame 0x{ft:02x}")
            return True
        return eliciting

    def _crypto_in(self, level: str, coff: int, data: bytes) -> None:
        sp = self.spaces[level]
        sp.crypto_in[coff] = data
        out = b""
        while sp.crypto_in_off in sp.crypto_in:
            chunk = sp.crypto_in.pop(sp.crypto_in_off)
            out += chunk
            sp.crypto_in_off += len(chunk)
        if out:
            try:
                self._tls_input(level, out)
            except TlsError as e:
                log.warning("quic tls failure: %s", e)
                self.close(0x0128, str(e))

    def _stream_in(self, sid: int, s_off: int, data: bytes, fin: bool) -> None:
        if sid % 4 != 0:
            # only client-initiated bidirectional streams are served
            # (the reference's quicer listener accepts the same set)
            self.close(0x05, f"unsupported stream id {sid}")
            return
        st = self.streams.get(sid)
        if st is None:
            if len(self.streams) >= self.MAX_STREAMS:
                self.close(0x04, "stream limit exceeded")
                return
            st = self._stream(sid)
        end = s_off + len(data)
        # FC accounting is OFFSET-based (RFC 9000 §4.1): duplicates /
        # retransmissions never advance the high-water marks, so a
        # PTO-probed copy of delivered data cannot trip a violation
        hwm_delta = max(0, end - st.rx_hwm)
        if end > st.rx_max or (
            self._rx_hwm_total + hwm_delta > self.rx_max_data
        ):
            # the peer overran a window we advertised (RFC 9000
            # §4.1): FLOW_CONTROL_ERROR, not silent acceptance
            self.close(0x03, "flow control violated")
            return
        st.rx_hwm = end if end > st.rx_hwm else st.rx_hwm
        self._rx_hwm_total += hwm_delta
        if s_off + len(data) <= st.rx_off:
            return  # spurious retransmission of delivered data
        if s_off < st.rx_off:
            # trim the already-delivered prefix so the chunk keys at
            # the reassembly cursor (a stale key would leak forever)
            data = data[st.rx_off - s_off:]
            s_off = st.rx_off
        st.rx[s_off] = data
        out = b""
        while st.rx_off in st.rx:
            chunk = st.rx.pop(st.rx_off)
            out += chunk
            st.rx_off += len(chunk)
        if out:
            self._rx_consumed += len(out)
            st.consumed += len(out)
            # replenish once half of EITHER window is consumed — the
            # (smaller) stream window exhausts first; keying only off
            # the connection window would deadlock a conformant peer
            if self.rx_max_data - self._rx_consumed < FC_CONN_WINDOW // 2:
                self._fc_update_due = True
            if st.rx_max - st.consumed < FC_STREAM_WINDOW // 2:
                self._fc_stream_due.add(sid)
            if sid == 0:
                if self.on_stream_data is not None:
                    self.on_stream_data(out)
            elif self.on_data_stream is not None:
                self.on_data_stream(sid, out)
        if fin:
            st.fin_rcvd = True
            if sid == 0:
                # the control stream closing ends the connection (the
                # reference tears the channel down with it); a data
                # stream's FIN just finishes that stream
                self._closed_by_peer()

    def _on_ack(self, level: str, ranges: list) -> None:
        sp = self.spaces[level]
        # clamp acknowledgment claims to what we actually sent
        sent_max = sp.next_pn - 1
        newly = [
            pn for pn in sp.sent
            if any(lo <= pn <= hi for lo, hi in ranges)
        ]
        if not newly:
            return
        sp.pto_count = 0  # forward progress resets the backoff
        now = self._clock()
        # RTT sample off the largest newly-acked packet (RFC 9002 §5)
        largest_newly = max(newly)
        sample = now - sp.sent[largest_newly].time
        if sample >= 0:
            if self.srtt is None:
                self.srtt = sample
                self.rttvar = sample / 2
            else:
                self.rttvar = 0.75 * self.rttvar + 0.25 * abs(
                    self.srtt - sample
                )
                self.srtt = 0.875 * self.srtt + 0.125 * sample
        for pn in newly:
            meta = sp.sent.pop(pn)
            self.bytes_in_flight = max(0, self.bytes_in_flight - meta.size)
            self._cc_on_ack(meta)
            if meta.stream is not None:
                sid, s_off, _ln = meta.stream
                st = self.streams.get(sid)
                if st is not None:
                    st.unacked.pop(s_off, None)
        claimed = max(hi for _lo, hi in ranges)
        sp.largest_acked = max(sp.largest_acked, min(claimed, sent_max))
        self._detect_losses(sp)

    # --- congestion control (RFC 9002 §7: NewReno) ----------------------

    def _cc_on_ack(self, meta: "_SentPacket") -> None:
        if meta.size <= 0 or meta.time <= self._recovery_start:
            return  # acks for pre-recovery packets don't grow cwnd
        if self.cwnd < self.ssthresh:
            self.cwnd += meta.size  # slow start (§7.3.1)
        else:
            # congestion avoidance: ~one MTU per cwnd of acked bytes
            self.cwnd += (
                self.max_datagram_size * meta.size // max(self.cwnd, 1)
            )

    def _cc_on_loss(self, meta: "_SentPacket") -> None:
        self.bytes_in_flight = max(0, self.bytes_in_flight - meta.size)
        if meta.time <= self._recovery_start:
            return  # one congestion event per recovery period (§7.3.1)
        self._recovery_start = self._clock()
        self.ssthresh = max(self.cwnd // 2, 2 * self.max_datagram_size)
        self.cwnd = self.ssthresh

    def _pto_interval(self, sp: _Space) -> float:
        """PTO = srtt + 4*rttvar + max_ack_delay, backed off (§6.2.1);
        the static initial value only seeds the first flight."""
        if self.srtt is None:
            base = PTO_INITIAL
        else:
            base = self.srtt + max(4 * self.rttvar, 0.001) + 0.025
        return min(max(base, 0.05) * (2 ** sp.pto_count), PTO_MAX)

    def _detect_losses(self, sp: _Space) -> None:
        """Packet-threshold loss (RFC 9002 §6.1.1): anything
        K_PACKET_THRESHOLD below the largest acked is lost."""
        lost = [
            pn for pn in sp.sent
            if pn <= sp.largest_acked - K_PACKET_THRESHOLD
        ]
        for pn in sorted(lost):
            meta = sp.sent.pop(pn)
            self._cc_on_loss(meta)
            self._declare_lost(sp, meta)

    def _declare_lost(self, sp: _Space, meta: "_SentPacket") -> None:
        if meta.crypto is not None:
            sp.crypto_rtx.append(meta.crypto)
        if meta.stream is not None:
            sid, s_off, _ln = meta.stream
            st = self.streams.get(sid)
            chunk = st.unacked.pop(s_off, None) if st is not None else None
            if chunk is not None:
                st.rtx.append((s_off, chunk))
        if meta.hs_done:
            self._hs_done_sent = False
        if meta.fc:
            # the peer may be BLOCKED on these updates; resend the
            # SAME stream windows (a lost data-stream MAX_STREAM_DATA
            # would otherwise deadlock that stream: its local rx_max
            # already advanced, so the consume trigger can't re-fire)
            self._fc_update_due = True
            if isinstance(meta.fc, tuple):
                self._fc_stream_due.update(meta.fc)

    def next_timeout(self) -> Optional[float]:
        """Earliest PTO deadline across spaces (absolute monotonic
        time), None when nothing is in flight."""
        deadline = None
        for sp in self.spaces.values():
            if sp.tx is None or not sp.sent:
                continue
            d = sp.last_eliciting_sent + self._pto_interval(sp)
            deadline = d if deadline is None else min(deadline, d)
        return deadline

    def on_timeout(self, now: Optional[float] = None) -> bool:
        """PTO expiry (RFC 9002 §6.2.4): send PROBE data — a duplicate
        of the oldest unacked crypto/stream range — without declaring
        the whole in-flight set lost (ADVICE r4: on paths with RTT
        near the timer a merely delayed ACK previously triggered a
        full spurious retransmit burst). In-flight packets stay
        tracked; real losses surface via the packet threshold when the
        probe's ack arrives. Returns True when anything became
        sendable (owner must flush)."""
        now = self._clock() if now is None else now
        fired = False
        for sp in self.spaces.values():
            if sp.tx is None or not sp.sent or self.closed:
                continue
            if now - sp.last_eliciting_sent < self._pto_interval(sp):
                continue
            sp.pto_count += 1
            # §7.5: probe packets may exceed the congestion window
            self._probe_credit = min(self._probe_credit + 1, 2)
            probed = False
            oldest = min(sp.sent, key=lambda pn: sp.sent[pn].time)
            meta = sp.sent[oldest]
            if meta.crypto is not None and meta.crypto not in sp.crypto_rtx:
                sp.crypto_rtx.append(meta.crypto)
                probed = True
            if meta.stream is not None:
                sid, s_off, _ln = meta.stream
                st = self.streams.get(sid)
                chunk = st.unacked.get(s_off) if st is not None else None
                if chunk is not None and all(o != s_off for o, _c in st.rtx):
                    st.rtx.append((s_off, chunk))
                    probed = True
            if not probed:
                sp.ping_due = True  # nothing rebuildable: bare probe
            fired = True
        return fired

    def _closed_by_peer(self) -> None:
        if not self.closed:
            self.closed = True
            if self.on_close is not None:
                self.on_close()

    # --- app API ---------------------------------------------------------

    def send_stream(self, data: bytes, sid: int = 0) -> None:
        st = self._stream(sid)
        st.out += data

    def next_client_stream(self) -> int:
        """Allocate the next client-initiated bidirectional stream id
        (0, 4, 8, ... — RFC 9000 §2.1). Client side only."""
        used = [s for s in self.streams if s % 4 == 0]
        return (max(used) + 4) if used else 0

    def close(self, code: int = 0, reason: str = "") -> None:
        if not self.closed:
            self.close_pending = (code, reason)

    def _tls_input(self, level: str, data: bytes) -> None:
        raise NotImplementedError


class ServerConnection(QuicConnection):
    def __init__(self, odcid: bytes, cert=None, psk_lookup=None):
        super().__init__(True, scid=os.urandom(8), dcid=b"")
        sp = self.spaces["initial"]
        sp.rx, sp.tx = initial_keys(odcid, is_server=True)
        self.tls = TlsServer(
            encode_transport_params(self.scid, odcid=odcid), cert=cert,
            psk_lookup=psk_lookup,
        )

    def _tls_input(self, level: str, data: bytes) -> None:
        if level == "initial":
            for lvl, out in self.tls.feed_initial(data):
                self.spaces[lvl].crypto_out += out
            if self.tls.server_hs_secret is not None:
                hs = self.spaces["handshake"]
                hs.rx = DirectionKeys(self.tls.client_hs_secret)
                hs.tx = DirectionKeys(self.tls.server_hs_secret)
                app = self.spaces["app"]
                app.rx = DirectionKeys(self.tls.client_app_secret)
                app.tx = DirectionKeys(self.tls.server_app_secret)
        elif level == "handshake":
            self.tls.feed_handshake(data)
            if self.tls.handshake_complete:
                self.handshake_done = True


class ClientConnection(QuicConnection):
    def __init__(self, psk_identity=None, psk=None):
        odcid = os.urandom(8)
        super().__init__(False, scid=os.urandom(8), dcid=odcid)
        sp = self.spaces["initial"]
        sp.rx, sp.tx = initial_keys(odcid, is_server=False)
        self.tls = TlsClient(
            encode_transport_params(self.scid),
            psk_identity=psk_identity, psk=psk,
        )
        sp.crypto_out += self.tls.client_hello()

    def _tls_input(self, level: str, data: bytes) -> None:
        if level == "initial":
            self.tls.feed_initial(data)
            if self.tls.client_hs_secret is not None:
                hs = self.spaces["handshake"]
                hs.rx = DirectionKeys(self.tls.server_hs_secret)
                hs.tx = DirectionKeys(self.tls.client_hs_secret)
        elif level == "handshake":
            fin = self.tls.feed_handshake(data)
            if fin is not None:
                self.spaces["handshake"].crypto_out += fin
                app = self.spaces["app"]
                app.rx = DirectionKeys(self.tls.server_app_secret)
                app.tx = DirectionKeys(self.tls.client_app_secret)


# --- UDP endpoints ---------------------------------------------------------


def _dgram_dcid(data: bytes) -> Optional[bytes]:
    """Destination CID of a datagram's first packet (routing key)."""
    try:
        if data[0] & 0x80:
            ln = data[5]
            return bytes(data[6 : 6 + ln])
        return bytes(data[1:9])  # our CIDs are always 8 bytes
    except IndexError:
        return None


class QuicStreamTransport:
    """Adapts stream 0 of a QUIC connection to the byte-stream
    transport contract the MQTT Connection runtime uses (read/write/
    drain/close/peername) — the quicer single-stream mode.

    MULTI-STREAM mode (emqx_quic_data_stream.erl): further client-
    initiated bidirectional streams are DATA streams. Each gets its
    own MQTT parser; its packets feed the SAME channel (so session,
    auth, aliases and quotas are shared) and the replies they elicit
    (PUBACK/PUBREC/...) return on the SAME stream, per the reference's
    per-stream ordering contract. Connection-level packets (CONNECT /
    DISCONNECT / AUTH) are only legal on the control stream — a data
    stream carrying one is a protocol error. Broker-initiated
    deliveries ride the control stream."""

    quic = True

    def __init__(self, conn: "ServerConnection", endpoint, addr):
        self.conn = conn
        self.endpoint = endpoint
        self.addr = addr
        self._q: asyncio.Queue = asyncio.Queue()
        self.mqtt_conn = None  # set by the endpoint after Connection()
        self._ds_q: Dict[int, asyncio.Queue] = {}
        self._ds_tasks: Dict[int, object] = {}
        conn.on_stream_data = self._q.put_nowait
        conn.on_data_stream = self._data_stream_in
        conn.on_close = self._on_conn_close

    def _on_conn_close(self) -> None:
        self._q.put_nowait(b"")
        for t in self._ds_tasks.values():
            t.cancel()
        self._ds_tasks.clear()

    def _data_stream_in(self, sid: int, data: bytes) -> None:
        q = self._ds_q.get(sid)
        if q is None:
            q = self._ds_q[sid] = asyncio.Queue()
            self._ds_tasks[sid] = asyncio.ensure_future(
                self._ds_run(sid, q)
            )
        q.put_nowait(data)

    def _ds_abort(self, reason: str) -> None:
        self.conn.close(0x0A, reason)
        self.endpoint.kick(self.conn)

    async def _ds_run(self, sid: int, q: asyncio.Queue) -> None:
        """One data stream's packet loop — the emqx_quic_data_stream
        process analog. Mirrors the control-stream run loop's gates:
        the SAME publish/byte limiters (a client must not evade quotas
        by spreading publishes over streams), the listener's packet-
        size cap, and connection-level-packet rejection. Replies
        return on this stream; keepalive is touched by the channel's
        own handle_packet."""
        from . import frame
        from .packet import Auth, Connect, Disconnect, Publish

        parser = None
        try:
            while True:
                data = await q.get()
                mc = self.mqtt_conn
                ch = getattr(mc, "channel", None)
                if ch is None or not ch.connected:
                    # data streams are valid only on a CONNECTed
                    # session (emqx_quic_data_stream waits for the
                    # control stream's CONNECT)
                    self._ds_abort("data stream before CONNECT")
                    return
                if parser is None:
                    parser = frame.Parser(
                        max_packet_size=mc.parser.max_packet_size,
                        proto_ver=ch.proto_ver,
                    )
                out = b""
                for pkt in parser.feed(data):
                    if isinstance(pkt, (Connect, Disconnect, Auth)):
                        self._ds_abort(
                            "connection-level packet on data stream"
                        )
                        return
                    if isinstance(pkt, Publish):
                        ok = await mc.pub_limiter.acquire(1.0)
                        ok = ok and await mc.byte_limiter.acquire(
                            float(len(pkt.payload))
                        )
                        if not ok:
                            self.endpoint.mqtt.broker.metrics.inc(
                                "messages.dropped.quota_exceeded"
                            )
                            self._ds_abort("publish quota exceeded")
                            return
                    for reply in ch.handle_packet(pkt):
                        out += frame.serialize(reply, ch.proto_ver)
                if out:
                    self.conn.send_stream(out, sid=sid)
                    self.endpoint.kick(self.conn)
        except asyncio.CancelledError:
            return
        except Exception as e:
            log.warning("quic data stream %d failed: %s", sid, e)
            self._ds_abort(f"data stream error: {e}")

    def peername(self):
        return self.addr

    async def read(self) -> bytes:
        if self.conn.closed and self._q.empty():
            return b""
        return await self._q.get()

    def write(self, data: bytes) -> None:
        self.conn.send_stream(data)
        self.endpoint.kick(self.conn)

    async def drain(self) -> None:
        self.endpoint.kick(self.conn)

    def close(self) -> None:
        if not self.conn.closed:
            self.conn.close(0, "server closed")
            self.endpoint.kick(self.conn)
            self.conn.closed = True
        self._q.put_nowait(b"")


class _QuicServerProtocol(asyncio.DatagramProtocol):
    def __init__(self, server: "QuicServer"):
        self.server = server

    def connection_made(self, transport):
        self.server._udp = transport

    def datagram_received(self, data, addr):
        try:
            self.server._on_datagram(data, addr)
        except Exception:
            log.exception("quic datagram crashed")


class QuicServer:
    """MQTT-over-QUIC listener: owns the UDP socket, routes datagrams
    to connections by CID, and hands handshaken connections to the
    MQTT Connection runtime of an ordinary `Server` (emqx_listeners
    quic listener analog)."""

    HANDSHAKE_TIMEOUT = 10.0  # reap pre-handshake conns (spoofed
    # Initials are cheap to send; state for them must not be)

    def __init__(self, mqtt_server, host: str = "0.0.0.0", port: int = 14567,
                 cert=None, psk_store=None):
        import time as _time

        self.mqtt = mqtt_server  # a broker Server (never TCP-started)
        self.host, self.port = host, port
        self._udp = None
        self.listen_addr = None
        self.conns: Dict[bytes, ServerConnection] = {}
        self._addr: Dict[bytes, tuple] = {}  # scid -> last peer addr
        self._started: set = set()
        self._conn_tasks: set = set()  # retained connection-run handles
        self._born: Dict[bytes, float] = {}  # scid -> accept time
        self._now = _time.monotonic
        # ONE certificate per listener (configurable PEMs or generated
        # once) — not per connection
        from .quic_tls import make_server_cert

        # TLS-PSK identity store (emqx_psk analog); enables psk_dhe_ke
        # on this listener when set
        self.psk_store = psk_store

        self.cert = cert or make_server_cert()
        self._gc_task = None
        self._pto_task = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.create_datagram_endpoint(
            lambda: _QuicServerProtocol(self),
            local_addr=(self.host, self.port),
        )
        self.listen_addr = self._udp.get_extra_info("sockname")[:2]
        self._gc_task = asyncio.ensure_future(self._gc_loop())
        self._pto_task = asyncio.ensure_future(self._pto_loop())
        log.info("quic listening on %s", self.listen_addr)

    async def _pto_loop(self) -> None:
        """Recovery pump: fire overdue PTOs and ship retransmissions
        (RFC 9002 §6.2). 100ms granularity bounds timer error well
        under one PTO backoff step."""
        while True:
            try:
                await asyncio.sleep(0.1)
                for scid, conn in list(self.conns.items()):
                    if conn.closed:
                        continue
                    if conn.on_timeout():
                        addr = self._addr.get(conn.scid)
                        if addr is not None and self._udp is not None:
                            for dgram in conn.flush():
                                self._udp.sendto(dgram, addr)
            except asyncio.CancelledError:
                return
            except Exception:
                log.exception("quic pto loop crashed")

    async def _gc_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(min(2.0, self.HANDSHAKE_TIMEOUT / 2))
                now = self._now()
                for scid, born in list(self._born.items()):
                    conn = self.conns.get(scid)
                    if conn is None:
                        self._born.pop(scid, None)
                        continue
                    if scid in self._started:
                        continue
                    if now - born > self.HANDSHAKE_TIMEOUT:
                        self._forget(conn)
            except asyncio.CancelledError:
                return
            except Exception:
                log.exception("quic gc crashed")

    def _forget(self, conn: "ServerConnection") -> None:
        for k in [k for k, v in self.conns.items() if v is conn]:
            self.conns.pop(k, None)
        self._addr.pop(conn.scid, None)
        self._born.pop(conn.scid, None)
        self._started.discard(conn.scid)

    async def stop(self) -> None:
        for conn in set(self.conns.values()):
            conn.close(0, "listener stopped")
            self.kick(conn)
        if self._gc_task is not None:
            self._gc_task.cancel()
            self._gc_task = None
        if getattr(self, "_pto_task", None) is not None:
            self._pto_task.cancel()
            self._pto_task = None
        if self._udp is not None:
            self._udp.close()
            self._udp = None

    def kick(self, conn: "ServerConnection") -> None:
        addr = self._addr.get(conn.scid)
        if addr is None or self._udp is None:
            return
        for dgram in conn.flush():
            self._udp.sendto(dgram, addr)

    def _on_datagram(self, data: bytes, addr) -> None:
        cid = _dgram_dcid(data)
        if cid is None:
            return
        conn = self.conns.get(cid)
        if conn is None:
            if not data[0] & 0x80 or len(data) < 1200:
                return  # only full-size Initials create state
            # accept gates: eviction + the listener's conn-rate bucket,
            # exactly like the TCP accept path
            if self.mqtt.evicting or not self.mqtt.limits.accept_allowed():
                self.mqtt.broker.metrics.inc("listener.conn_rate_limited")
                return
            conn = ServerConnection(
                odcid=cid, cert=self.cert,
                psk_lookup=(
                    self.psk_store.lookup if self.psk_store is not None
                    else None
                ),
            )
            self.conns[cid] = conn
            self.conns[conn.scid] = conn
            self._born[conn.scid] = self._now()
        self._addr[conn.scid] = addr
        conn.datagram_received(data)
        self.kick(conn)
        if conn.tls.handshake_complete and conn.scid not in self._started:
            self._started.add(conn.scid)
            transport = QuicStreamTransport(conn, self, addr)
            from .server import Connection

            mqtt_conn = Connection(self.mqtt, transport)
            transport.mqtt_conn = mqtt_conn  # data-stream channel seam
            self.mqtt._conns.add(mqtt_conn)

            async def run():
                try:
                    await mqtt_conn.run()
                finally:
                    self.mqtt._conns.discard(mqtt_conn)
                    self._forget(conn)

            task = asyncio.ensure_future(run())
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)


class QuicClientEndpoint:
    """Client seam: UDP socket + ClientConnection + handshake pump.
    recv() yields ordered stream-0 bytes (the MQTT byte stream)."""

    def __init__(self, psk_identity=None, psk=None):
        self.conn = ClientConnection(psk_identity=psk_identity, psk=psk)
        self._udp = None
        self.addr = None
        self._q: asyncio.Queue = asyncio.Queue()
        self._ds_q: Dict[int, asyncio.Queue] = {}  # data-stream inboxes
        self.conn.on_stream_data = self._q.put_nowait
        self.conn.on_data_stream = self._on_ds
        self.conn.on_close = lambda: self._q.put_nowait(b"")

    def _on_ds(self, sid: int, data: bytes) -> None:
        self._ds_q.setdefault(sid, asyncio.Queue()).put_nowait(data)

    async def connect(self, host: str, port: int, timeout: float = 5.0):
        loop = asyncio.get_running_loop()
        outer = self

        class P(asyncio.DatagramProtocol):
            def connection_made(self, tr):
                outer._udp = tr

            def datagram_received(self, data, _addr):
                outer.conn.datagram_received(data)
                outer._flush()

        await loop.create_datagram_endpoint(P, remote_addr=(host, port))
        self.addr = (host, port)
        self._flush()  # ships the Initial (client hello)
        deadline = loop.time() + timeout
        while not self.conn.handshake_done:
            if loop.time() > deadline:
                raise TimeoutError("quic handshake timed out")
            await asyncio.sleep(0.005)
            # drive client-side loss recovery during the handshake too:
            # a dropped Initial/Handshake datagram must retransmit
            self.conn.on_timeout()
            self._flush()
        self._pump_task = asyncio.ensure_future(self._pump())
        return self

    async def _pump(self) -> None:
        """Post-handshake recovery pump (PTO + retransmissions)."""
        while not self.conn.closed:
            await asyncio.sleep(0.1)
            try:
                if self.conn.on_timeout():
                    self._flush()
            except Exception:
                log.exception("quic client pump crashed")
                return

    def _flush(self) -> None:
        if self._udp is None:
            return
        for dgram in self.conn.flush():
            self._udp.sendto(dgram)

    def send(self, data: bytes) -> None:
        self.conn.send_stream(data)
        self._flush()

    async def recv(self, timeout: float = 5.0) -> bytes:
        return await asyncio.wait_for(self._q.get(), timeout)

    # --- multi-stream mode (data streams) ----------------------------
    def open_stream(self) -> int:
        """Open a new client-initiated bidi DATA stream; returns its
        id (the reference's multi-stream mode publishes on these)."""
        sid = self.conn.next_client_stream()
        self.conn._stream(sid)
        self._ds_q.setdefault(sid, asyncio.Queue())
        return sid

    def send_on(self, sid: int, data: bytes) -> None:
        self.conn.send_stream(data, sid=sid)
        self._flush()

    async def recv_on(self, sid: int, timeout: float = 5.0) -> bytes:
        q = self._ds_q.setdefault(sid, asyncio.Queue())
        return await asyncio.wait_for(q.get(), timeout)

    def close(self) -> None:
        t = getattr(self, "_pump_task", None)
        if t is not None:
            t.cancel()
        self.conn.close(0, "client done")
        self._flush()
        if self._udp is not None:
            self._udp.close()
            self._udp = None
