"""Session state: subscriptions, message queue, in-flight windows.

The in-memory session of the reference (apps/emqx/src/emqx_session_mem.erl
mqueue+inflight, emqx_mqueue.erl bounded priority queue, emqx_inflight.erl
receive-maximum window, and the QoS2 awaiting_rel set of
emqx_channel.erl:705-746) collapsed into one transport-agnostic object.
The channel drives it with packets; it emits outgoing packets.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..obs.profiler import STAGE_MARK
from .message import Message
from .packet import Publish, SubOpts


@dataclass
class SessionConfig:
    max_mqueue_len: int = 1000
    receive_maximum: int = 32  # outgoing inflight window
    max_awaiting_rel: int = 100  # incoming QoS2 window
    await_rel_timeout: float = 300.0
    retry_interval: float = 30.0
    session_expiry_interval: float = 0.0  # 0 = ends with connection
    upgrade_qos: bool = False
    # durable-session routing override (the per-zone
    # `durable_sessions.enable` analog): None = auto (nonzero expiry
    # becomes durable when a DS manager is attached), False = stay a
    # live in-memory session regardless of expiry
    durable: Optional[bool] = None
    # mqueue priorities (emqx_mqueue.erl): exact topic -> 1..255,
    # higher drains first; store_qos0=False drops queued QoS0 while
    # the client is disconnected
    mqueue_priorities: Dict[str, int] = field(default_factory=dict)
    mqueue_default_priority: int = 0
    mqueue_store_qos0: bool = True


@dataclass
class _InflightEntry:
    msg: Message
    phase: str  # 'puback' | 'pubrec' | 'pubcomp'
    sent_at: float
    dup: bool = False


class Session:
    """One client's session (mem-session semantics)."""

    def __init__(self, client_id: str, cfg: Optional[SessionConfig] = None):
        self.client_id = client_id
        self.cfg = cfg or SessionConfig()
        self.created_at = time.time()
        self.subscriptions: Dict[str, SubOpts] = {}  # full filter (incl $share)
        # (priority, msg, subopts); highest priority at the head
        self.mqueue: Deque[Tuple[int, Message, SubOpts]] = deque()
        self.inflight: "OrderedDict[int, _InflightEntry]" = OrderedDict()
        self.awaiting_rel: Dict[int, float] = {}  # incoming QoS2 pids
        self._next_pid = 1
        self.connected = True
        self.disconnected_at: Optional[float] = None
        # counters surfaced in stats/info
        self.dropped = 0
        # transport seams set by the connection layer: packet sink and
        # socket closer (used by admin kick / takeover)
        self.outgoing_sink = None
        # wide-fanout bytes fast path: a mountpoint-free connection
        # accepts the shared pre-serialized QoS0 PUBLISH directly
        # (set together with outgoing_sink by the transport)
        self.outgoing_sink_bytes = None
        self.sink_proto_ver = 4
        self.closer = None

    # --- packet-id allocation ------------------------------------------

    def alloc_packet_id(self) -> int:
        for _ in range(0xFFFF):
            pid = self._next_pid
            self._next_pid = pid % 0xFFFF + 1
            if pid not in self.inflight:
                return pid
        raise RuntimeError("no free packet id")

    # --- outgoing delivery ---------------------------------------------

    def deliver(self, msg: Message, subopts: SubOpts) -> List[Publish]:
        """Route one matched message into this session; returns the
        PUBLISH packets to send now (emqx_session:deliver/3)."""
        qos = min(msg.qos, subopts.qos) if not self.cfg.upgrade_qos else max(
            msg.qos, subopts.qos
        )
        if subopts.no_local and msg.from_client == self.client_id:
            return []
        eff = Message(**{**msg.__dict__})
        eff.qos = qos
        if not subopts.retain_as_published:
            eff.retain = False
        if not self.connected:
            self._enqueue(eff, subopts)
            return []
        if qos == 0:
            return [self._to_publish(eff, None)]
        if len(self.inflight) >= self.cfg.receive_maximum:
            self._enqueue(eff, subopts)
            return []
        pid = self.alloc_packet_id()
        self.inflight[pid] = _InflightEntry(
            eff, "puback" if qos == 1 else "pubrec", time.time()
        )
        return [self._to_publish(eff, pid)]

    def _queue_priority(self, msg: Message) -> int:
        return self.cfg.mqueue_priorities.get(
            msg.topic, self.cfg.mqueue_default_priority
        )

    def _enqueue(self, msg: Message, subopts: SubOpts) -> None:
        if (
            msg.qos == 0
            and not self.connected
            and not self.cfg.mqueue_store_qos0
        ):
            # emqx_mqueue store_qos0=false: QoS0 is not worth holding
            # for an absent client
            self.dropped += 1
            return
        prio = self._queue_priority(msg)
        if len(self.mqueue) >= self.cfg.max_mqueue_len:
            # emqx_mqueue overflow, priority-aware: shed from the
            # LOWEST priority class, never to admit something lower.
            # 1) prefer a QoS0 victim of <= incoming priority (tail =
            #    lowest first); 2) else any strictly-lower-priority
            #    tail entry; 3) else the INCOMING message is the
            #    lowest-value item — drop it.
            victim = None
            for i in range(len(self.mqueue) - 1, -1, -1):
                if self.mqueue[i][1].qos == 0 and self.mqueue[i][0] <= prio:
                    victim = i
                    break
            if victim is None and self.mqueue and self.mqueue[-1][0] < prio:
                victim = len(self.mqueue) - 1
            if victim is None:
                self.dropped += 1
                return
            del self.mqueue[victim]
            self.dropped += 1
        if not self.cfg.mqueue_priorities or not self.mqueue:
            self.mqueue.append((prio, msg, subopts))
            return
        # priority queue (emqx_pqueue analog): keep the deque sorted by
        # non-increasing priority, FIFO within a priority class
        i = len(self.mqueue)
        while i > 0 and self.mqueue[i - 1][0] < prio:
            i -= 1
        self.mqueue.insert(i, (prio, msg, subopts))

    def _to_publish(self, msg: Message, pid: Optional[int]) -> Publish:
        props = dict(msg.props)
        return Publish(
            topic=msg.topic,
            payload=msg.payload,
            qos=msg.qos,
            retain=msg.retain,
            packet_id=pid,
            props=props,
        )

    def drain(self) -> List[Publish]:
        """Move queued messages into the inflight window (after acks
        free slots, or on reconnect)."""
        # ack_sweep stage mark: the sampler buckets stacks caught in
        # this window-advance walk under the ack sweep sub-stage (the
        # wall time is measured by the channel's sampled ack clock)
        STAGE_MARK.stage = "ack_sweep"
        out: List[Publish] = []
        while self.mqueue:
            _prio, msg, subopts = self.mqueue[0]
            if msg.expired():
                self.mqueue.popleft()
                self.dropped += 1
                continue
            if msg.qos == 0:
                self.mqueue.popleft()
                out.append(self._to_publish(msg, None))
                continue
            if len(self.inflight) >= self.cfg.receive_maximum:
                break
            self.mqueue.popleft()
            pid = self.alloc_packet_id()
            self.inflight[pid] = _InflightEntry(
                msg, "puback" if msg.qos == 1 else "pubrec", time.time()
            )
            out.append(self._to_publish(msg, pid))
        STAGE_MARK.stage = ""
        return out

    # --- outgoing acks --------------------------------------------------

    def on_puback(self, pid: int) -> bool:
        e = self.inflight.get(pid)
        if e is None or e.phase != "puback":
            return False
        del self.inflight[pid]
        return True

    def on_pubrec(self, pid: int) -> bool:
        e = self.inflight.get(pid)
        if e is None or e.phase != "pubrec":
            return False
        e.phase = "pubcomp"
        e.msg = Message(topic=e.msg.topic)  # payload released (rel marker)
        return True

    def on_pubcomp(self, pid: int) -> bool:
        e = self.inflight.get(pid)
        if e is None or e.phase != "pubcomp":
            return False
        del self.inflight[pid]
        return True

    def retry(self, now: Optional[float] = None) -> List[Publish]:
        """Re-send unacked QoS1/2 after retry_interval (dup=1)."""
        STAGE_MARK.stage = "ack_sweep"
        now = now if now is not None else time.time()
        out = []
        for pid, e in self.inflight.items():
            if now - e.sent_at >= self.cfg.retry_interval:
                e.sent_at = now
                e.dup = True
                if e.phase in ("puback", "pubrec"):
                    p = self._to_publish(e.msg, pid)
                    p.dup = True
                    out.append(p)
                # phase 'pubcomp': PUBREL retransmit handled by channel
        STAGE_MARK.stage = ""
        return out

    # --- incoming QoS2 --------------------------------------------------

    def await_rel(self, pid: int) -> bool:
        """Register an incoming QoS2 publish; False if window full or
        duplicate (duplicate is not an error: dup redelivery)."""
        if pid in self.awaiting_rel:
            return False
        if len(self.awaiting_rel) >= self.cfg.max_awaiting_rel:
            raise OverflowError("RECEIVE_MAXIMUM_EXCEEDED")
        self.awaiting_rel[pid] = time.time()
        return True

    def release_rel(self, pid: int) -> bool:
        return self.awaiting_rel.pop(pid, None) is not None

    # --- lifecycle -------------------------------------------------------

    def on_disconnect(self) -> None:
        self.connected = False
        self.disconnected_at = time.time()

    def on_reconnect(self) -> List[Publish]:
        """Resume: re-send inflight (dup) then drain the queue
        (emqx_session_mem:replay)."""
        self.connected = True
        self.disconnected_at = None
        out = []
        for pid, e in self.inflight.items():
            e.sent_at = time.time()
            if e.phase in ("puback", "pubrec"):
                p = self._to_publish(e.msg, pid)
                p.dup = True
                out.append(p)
        out.extend(self.drain())
        return out

    def expired(self, now: Optional[float] = None) -> bool:
        if self.connected or self.disconnected_at is None:
            return False
        now = now if now is not None else time.time()
        return now - self.disconnected_at >= self.cfg.session_expiry_interval
