"""Session state: subscriptions, message queue, in-flight windows.

The in-memory session of the reference (apps/emqx/src/emqx_session_mem.erl
mqueue+inflight, emqx_mqueue.erl bounded priority queue, emqx_inflight.erl
receive-maximum window, and the QoS2 awaiting_rel set of
emqx_channel.erl:705-746) collapsed into one transport-agnostic object.
The channel drives it with packets; it emits outgoing packets.

The NUMERIC side of that state — packet-id allocation, window
occupancy, ack phases, retry stamps, and the priority-aware mqueue
overflow decision — lives in the process-global delivery ledger
(broker/delivery.py: native `delivery_*` legs of speedups.cc, or the
bit-exact Python twin).  This object keeps owning the messages:
`inflight` stays the pid → entry mapping and `mqueue` the real deque;
entry phase/dup/sent_at fields are observability mirrors of the
ledger's authoritative copies.
"""

from __future__ import annotations

import time
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..obs.profiler import STAGE_MARK
from . import delivery as _delivery
from .message import Message
from .packet import Publish, SubOpts


@dataclass
class SessionConfig:
    max_mqueue_len: int = 1000
    receive_maximum: int = 32  # outgoing inflight window
    max_awaiting_rel: int = 100  # incoming QoS2 window
    await_rel_timeout: float = 300.0
    retry_interval: float = 30.0
    session_expiry_interval: float = 0.0  # 0 = ends with connection
    upgrade_qos: bool = False
    # durable-session routing override (the per-zone
    # `durable_sessions.enable` analog): None = auto (nonzero expiry
    # becomes durable when a DS manager is attached), False = stay a
    # live in-memory session regardless of expiry
    durable: Optional[bool] = None
    # mqueue priorities (emqx_mqueue.erl): exact topic -> 1..255,
    # higher drains first; store_qos0=False drops queued QoS0 while
    # the client is disconnected
    mqueue_priorities: Dict[str, int] = field(default_factory=dict)
    mqueue_default_priority: int = 0
    mqueue_store_qos0: bool = True


@dataclass
class _InflightEntry:
    msg: Message
    phase: str  # 'puback' | 'pubrec' | 'pubcomp'
    sent_at: float
    dup: bool = False


class Session:
    """One client's session (mem-session semantics)."""

    def __init__(self, client_id: str, cfg: Optional[SessionConfig] = None):
        self.client_id = client_id
        self.cfg = cfg or SessionConfig()
        self.created_at = time.time()
        self.subscriptions: Dict[str, SubOpts] = {}  # full filter (incl $share)
        # (priority, msg, subopts); highest priority at the head
        self.mqueue: Deque[Tuple[int, Message, SubOpts]] = deque()
        self.inflight: "OrderedDict[int, _InflightEntry]" = OrderedDict()
        self.awaiting_rel: Dict[int, float] = {}  # incoming QoS2 pids
        self.connected = True
        self.disconnected_at: Optional[float] = None
        # counters surfaced in stats/info
        self.dropped = 0
        # transport seams set by the connection layer: packet sink and
        # socket closer (used by admin kick / takeover)
        self.outgoing_sink = None
        # wide-fanout bytes fast path: a mountpoint-free connection
        # accepts the shared pre-serialized QoS0 PUBLISH directly
        # (set together with outgoing_sink by the transport)
        self.outgoing_sink_bytes = None
        self.sink_proto_ver = 4
        self.closer = None
        # delivery ledger binding: all pid/window/phase/queue-overflow
        # arithmetic runs in the shared ledger slot; the finalizer
        # returns the slot when the broker drops this session
        self._ledger = _delivery.make_ledger()
        self._dslot = self._ledger.open()
        self._dslot_finalizer = weakref.finalize(
            self, self._ledger.close, self._dslot
        )

    # --- outgoing delivery ---------------------------------------------

    def deliver(self, msg: Message, subopts: SubOpts) -> List[Publish]:
        """Route one matched message into this session; returns the
        PUBLISH packets to send now (emqx_session:deliver/3)."""
        qos = min(msg.qos, subopts.qos) if not self.cfg.upgrade_qos else max(
            msg.qos, subopts.qos
        )
        if subopts.no_local and msg.from_client == self.client_id:
            return []
        eff = Message(**{**msg.__dict__})
        eff.qos = qos
        if not subopts.retain_as_published:
            eff.retain = False
        if not self.connected:
            self._enqueue(eff, subopts)
            return []
        if qos == 0:
            return [self._to_publish(eff, None)]
        now = time.time()
        pid = self._ledger.reserve(
            self._dslot, qos, now, self.cfg.receive_maximum
        )
        if pid == 0:  # window full
            self._enqueue(eff, subopts)
            return []
        self.inflight[pid] = _InflightEntry(
            eff, "puback" if qos == 1 else "pubrec", now
        )
        return [self._to_publish(eff, pid)]

    def deliver_many(self, items: List[Tuple[Message, SubOpts]]) -> List[Publish]:
        """Window-batched deliver: semantically a `deliver()` per item
        in order — same option walk, same packets, same queue behavior
        (oracle-checked in tests/test_delivery_engine.py) — but every
        inflight reservation for the window rides ONE batched ledger
        call (`delivery_reserve_many`) instead of a per-message leg.
        The broker's window dispatch calls this once per (session,
        dispatch window)."""
        if len(items) == 1:
            return self.deliver(items[0][0], items[0][1])
        out: List[Optional[Publish]] = []
        resv: List[Tuple[int, Message, SubOpts]] = []  # (out idx, eff, opts)
        upgrade = self.cfg.upgrade_qos
        for msg, subopts in items:
            qos = (
                max(msg.qos, subopts.qos)
                if upgrade
                else min(msg.qos, subopts.qos)
            )
            if subopts.no_local and msg.from_client == self.client_id:
                continue
            eff = Message(**{**msg.__dict__})
            eff.qos = qos
            if not subopts.retain_as_published:
                eff.retain = False
            if not self.connected:
                # connected is constant across the window, so enqueue
                # order stays item order (nothing reserves below)
                self._enqueue(eff, subopts)
                continue
            if qos == 0:
                out.append(self._to_publish(eff, None))
                continue
            out.append(None)  # placeholder keeps packet order exact
            resv.append((len(out) - 1, eff, subopts))
        if resv:
            now = time.time()
            slot = self._dslot
            pids = self._ledger.reserve_many(
                [slot] * len(resv),
                [e.qos for _i, e, _o in resv],
                now,
                [self.cfg.receive_maximum] * len(resv),
            )
            for (pos, eff, subopts), pid in zip(resv, pids):
                if pid == 0:  # window full at this item's turn
                    self._enqueue(eff, subopts)
                    continue
                self.inflight[pid] = _InflightEntry(
                    eff, "puback" if eff.qos == 1 else "pubrec", now
                )
                out[pos] = self._to_publish(eff, pid)
        return [p for p in out if p is not None]

    def _queue_priority(self, msg: Message) -> int:
        return self.cfg.mqueue_priorities.get(
            msg.topic, self.cfg.mqueue_default_priority
        )

    def _enqueue(self, msg: Message, subopts: SubOpts) -> None:
        if (
            msg.qos == 0
            and not self.connected
            and not self.cfg.mqueue_store_qos0
        ):
            # emqx_mqueue store_qos0=false: QoS0 is not worth holding
            # for an absent client
            self.dropped += 1
            return
        prio = self._queue_priority(msg)
        # emqx_mqueue admission, priority-aware: the ledger's shadow
        # queue decides — shed from the LOWEST priority class, never
        # to admit something lower (QoS0 victims first, then a
        # strictly-lower-priority tail entry, else drop the incoming) —
        # and hands back where the real deque mutates
        packed = self._ledger.enqueue(
            self._dslot,
            prio,
            msg.qos,
            self.cfg.max_mqueue_len,
            1 if self.cfg.mqueue_priorities else 0,
        )
        action = packed & 0x3
        if action == 0:
            self.dropped += 1
            return
        if action == 2:
            del self.mqueue[packed >> 32]
            self.dropped += 1
        idx = (packed >> 2) & 0x3FFFFFFF
        if idx == len(self.mqueue):
            self.mqueue.append((prio, msg, subopts))
        else:
            # priority queue (emqx_pqueue analog): non-increasing
            # priority order, FIFO within a priority class
            self.mqueue.insert(idx, (prio, msg, subopts))

    def _to_publish(self, msg: Message, pid: Optional[int]) -> Publish:
        props = dict(msg.props)
        return Publish(
            topic=msg.topic,
            payload=msg.payload,
            qos=msg.qos,
            retain=msg.retain,
            packet_id=pid,
            props=props,
        )

    def drain(self) -> List[Publish]:
        """Move queued messages into the inflight window (after acks
        free slots, or on reconnect)."""
        # ack_sweep stage mark: the sampler buckets stacks caught in
        # this window-advance walk under the ack sweep sub-stage (the
        # wall time is measured by the channel's sampled ack clock)
        STAGE_MARK.stage = "ack_sweep"
        out: List[Publish] = []
        led, slot = self._ledger, self._dslot
        while self.mqueue:
            _prio, msg, subopts = self.mqueue[0]
            if msg.expired():
                self.mqueue.popleft()
                led.popleft(slot)
                self.dropped += 1
                continue
            if msg.qos == 0:
                self.mqueue.popleft()
                led.popleft(slot)
                out.append(self._to_publish(msg, None))
                continue
            now = time.time()
            pid = led.reserve(slot, msg.qos, now, self.cfg.receive_maximum)
            if pid == 0:  # window full
                break
            self.mqueue.popleft()
            led.popleft(slot)
            self.inflight[pid] = _InflightEntry(
                msg, "puback" if msg.qos == 1 else "pubrec", now
            )
            out.append(self._to_publish(msg, pid))
        STAGE_MARK.stage = ""
        return out

    # --- outgoing acks --------------------------------------------------

    def on_puback(self, pid: int) -> bool:
        if not self._ledger.ack(self._dslot, pid, _delivery.PHASE_PUBACK):
            return False
        self.inflight.pop(pid, None)
        return True

    def on_pubrec(self, pid: int) -> bool:
        if not self._ledger.ack(self._dslot, pid, _delivery.PHASE_PUBREC):
            return False
        e = self.inflight.get(pid)
        if e is not None:
            e.phase = "pubcomp"
            e.msg = Message(topic=e.msg.topic)  # payload released (rel marker)
        return True

    def on_pubcomp(self, pid: int) -> bool:
        if not self._ledger.ack(self._dslot, pid, _delivery.PHASE_PUBCOMP):
            return False
        self.inflight.pop(pid, None)
        return True

    def forget_inflight(self, pid: int) -> bool:
        """Release an inflight slot unconditionally — the transport's
        drop-too-large path: the client never received the packet, so
        no ack will ever free the window entry."""
        self._ledger.forget(self._dslot, pid)
        return self.inflight.pop(pid, None) is not None

    def retry(self, now: Optional[float] = None) -> List[Publish]:
        """Re-send unacked QoS1/2 after retry_interval (dup=1)."""
        STAGE_MARK.stage = "ack_sweep"
        now = now if now is not None else time.time()
        out = []
        for pid, phase in self._ledger.retry_due(
            self._dslot, now, self.cfg.retry_interval
        ):
            e = self.inflight.get(pid)
            if e is None:
                continue
            e.sent_at = now
            e.dup = True
            if phase != _delivery.PHASE_PUBCOMP:
                p = self._to_publish(e.msg, pid)
                p.dup = True
                out.append(p)
            # phase 'pubcomp': PUBREL retransmit handled by channel
        STAGE_MARK.stage = ""
        return out

    # --- incoming QoS2 --------------------------------------------------

    def await_rel(self, pid: int) -> bool:
        """Register an incoming QoS2 publish; False if window full or
        duplicate (duplicate is not an error: dup redelivery)."""
        if pid in self.awaiting_rel:
            return False
        if len(self.awaiting_rel) >= self.cfg.max_awaiting_rel:
            raise OverflowError("RECEIVE_MAXIMUM_EXCEEDED")
        self.awaiting_rel[pid] = time.time()
        return True

    def release_rel(self, pid: int) -> bool:
        return self.awaiting_rel.pop(pid, None) is not None

    # --- lifecycle -------------------------------------------------------

    def on_disconnect(self) -> None:
        self.connected = False
        self.disconnected_at = time.time()

    def on_reconnect(self) -> List[Publish]:
        """Resume: re-send inflight (dup) then drain the queue
        (emqx_session_mem:replay)."""
        self.connected = True
        self.disconnected_at = None
        out = []
        now = time.time()
        for pid, phase in self._ledger.touch_all(self._dslot, now):
            e = self.inflight.get(pid)
            if e is None:
                continue
            e.sent_at = now
            if phase != _delivery.PHASE_PUBCOMP:
                p = self._to_publish(e.msg, pid)
                p.dup = True
                out.append(p)
        out.extend(self.drain())
        return out

    def expired(self, now: Optional[float] = None) -> bool:
        if self.connected or self.disconnected_at is None:
            return False
        now = now if now is not None else time.time()
        return now - self.disconnected_at >= self.cfg.session_expiry_interval
