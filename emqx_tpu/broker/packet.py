"""MQTT control-packet model (3.1 / 3.1.1 / 5.0).

The typed mirror of the reference's packet records
(apps/emqx/include/emqx_mqtt.hrl, apps/emqx/src/emqx_packet.erl):
plain dataclasses the codec (broker/frame.py) parses into and
serializes from.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class Type(enum.IntEnum):
    CONNECT = 1
    CONNACK = 2
    PUBLISH = 3
    PUBACK = 4
    PUBREC = 5
    PUBREL = 6
    PUBCOMP = 7
    SUBSCRIBE = 8
    SUBACK = 9
    UNSUBSCRIBE = 10
    UNSUBACK = 11
    PINGREQ = 12
    PINGRESP = 13
    DISCONNECT = 14
    AUTH = 15


# protocol levels
MQTT_V3 = 3  # 3.1    (MQIsdp)
MQTT_V4 = 4  # 3.1.1  (MQTT)
MQTT_V5 = 5  # 5.0


class RC(enum.IntEnum):
    """MQTT 5.0 reason codes (subset used by the broker; v3 SUBACK
    failure is 0x80). Mirrors apps/emqx/src/emqx_reason_codes.erl."""

    SUCCESS = 0x00
    GRANTED_QOS_1 = 0x01
    GRANTED_QOS_2 = 0x02
    DISCONNECT_WITH_WILL = 0x04
    NO_MATCHING_SUBSCRIBERS = 0x10
    NO_SUBSCRIPTION_EXISTED = 0x11
    CONTINUE_AUTHENTICATION = 0x18
    REAUTHENTICATE = 0x19
    UNSPECIFIED_ERROR = 0x80
    MALFORMED_PACKET = 0x81
    PROTOCOL_ERROR = 0x82
    IMPLEMENTATION_SPECIFIC = 0x83
    UNSUPPORTED_PROTOCOL_VERSION = 0x84
    CLIENT_IDENTIFIER_NOT_VALID = 0x85
    BAD_USERNAME_OR_PASSWORD = 0x86
    NOT_AUTHORIZED = 0x87
    SERVER_UNAVAILABLE = 0x88
    SERVER_BUSY = 0x89
    BANNED = 0x8A
    BAD_AUTHENTICATION_METHOD = 0x8C
    KEEPALIVE_TIMEOUT = 0x8D
    SESSION_TAKEN_OVER = 0x8E
    TOPIC_FILTER_INVALID = 0x8F
    TOPIC_NAME_INVALID = 0x90
    PACKET_IDENTIFIER_IN_USE = 0x91
    PACKET_IDENTIFIER_NOT_FOUND = 0x92
    RECEIVE_MAXIMUM_EXCEEDED = 0x93
    TOPIC_ALIAS_INVALID = 0x94
    PACKET_TOO_LARGE = 0x95
    MESSAGE_RATE_TOO_HIGH = 0x96
    QUOTA_EXCEEDED = 0x97
    ADMINISTRATIVE_ACTION = 0x98
    PAYLOAD_FORMAT_INVALID = 0x99
    RETAIN_NOT_SUPPORTED = 0x9A
    QOS_NOT_SUPPORTED = 0x9B
    USE_ANOTHER_SERVER = 0x9C
    SERVER_MOVED = 0x9D
    SHARED_SUBSCRIPTIONS_NOT_SUPPORTED = 0x9E
    CONNECTION_RATE_EXCEEDED = 0x9F
    MAXIMUM_CONNECT_TIME = 0xA0
    SUBSCRIPTION_IDENTIFIERS_NOT_SUPPORTED = 0xA1
    WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED = 0xA2


Properties = Dict[str, object]  # name -> value ('user_property': list of pairs)


@dataclass
class Will:
    topic: str
    payload: bytes
    qos: int = 0
    retain: bool = False
    props: Properties = field(default_factory=dict)


@dataclass
class Connect:
    proto_name: str = "MQTT"
    proto_ver: int = MQTT_V4
    clean_start: bool = True
    keepalive: int = 60
    client_id: str = ""
    will: Optional[Will] = None
    username: Optional[str] = None
    password: Optional[bytes] = None
    props: Properties = field(default_factory=dict)


@dataclass
class Connack:
    session_present: bool = False
    code: int = 0  # v3 return code or v5 reason code
    props: Properties = field(default_factory=dict)


@dataclass
class Publish:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None  # required for qos > 0
    props: Properties = field(default_factory=dict)


@dataclass
class Puback:  # also PUBREC/PUBREL/PUBCOMP via `type`
    type: Type
    packet_id: int
    code: int = 0
    props: Properties = field(default_factory=dict)


@dataclass
class SubOpts:
    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0


@dataclass
class Subscribe:
    packet_id: int
    filters: List[Tuple[str, SubOpts]] = field(default_factory=list)
    props: Properties = field(default_factory=dict)


@dataclass
class Suback:
    packet_id: int
    codes: List[int] = field(default_factory=list)
    props: Properties = field(default_factory=dict)


@dataclass
class Unsubscribe:
    packet_id: int
    filters: List[str] = field(default_factory=list)
    props: Properties = field(default_factory=dict)


@dataclass
class Unsuback:
    packet_id: int
    codes: List[int] = field(default_factory=list)  # v5 only on wire
    props: Properties = field(default_factory=dict)


@dataclass
class Pingreq:
    pass


@dataclass
class Pingresp:
    pass


@dataclass
class Disconnect:
    code: int = 0
    props: Properties = field(default_factory=dict)


@dataclass
class Auth:
    code: int = 0
    props: Properties = field(default_factory=dict)


Packet = object  # union of the above
