"""Delivery-ledger seam: native QoS bookkeeping with a Python twin.

The per-session numeric state of `broker/session.py` — the inflight
window (packet id, ack phase, dup, sent_at), the wraparound packet-id
allocator, the QoS1/2 retry sweep and the priority-aware mqueue
overflow decision — is pure integer bookkeeping the Python interpreter
pays object-model tax on for every delivered message.  This seam moves
it behind one process-global ledger with two interchangeable
implementations:

  * `NativeDeliveryLedger` — the `delivery_*` legs of
    `native/speedups.cc` (`_emqx_speedups.so`), slot arrays behind a
    capsule handle with the same discipline as the route-churn engine;
  * `PyDeliveryLedger` — the bit-exact Python twin, always available,
    fuzzed head-to-head in tests/test_delivery_engine.py.

Sessions keep owning the *messages* (`Session.inflight` stays the
pid → entry mapping, `Session.mqueue` stays the real deque); the
ledger owns only the numbers, and config scalars ride each call so
`SessionConfig` stays authoritative.  The `emqx_delivery_*` families
render on every scrape; `broker.perf.tpu_delivery_native` is the knob.

Inflight phases are encoded 0 = awaiting PUBACK, 1 = awaiting PUBREC,
2 = awaiting PUBCOMP; ack kinds use the same codes.  `enqueue` returns
a packed decision over the (priority, qos) shadow queue:

  bits 0..1   action: 0 drop the incoming message, 1 admit,
              2 admit after evicting the victim
  bits 2..31  insert index (post-eviction queue coordinates)
  bits 32+    victim index (action 2 only, pre-eviction coordinates)
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..ops import speedups as _speedups

PHASE_PUBACK = 0
PHASE_PUBREC = 1
PHASE_PUBCOMP = 2

PHASE_NAMES = ("puback", "pubrec", "pubcomp")

_mod = None
_tried = False
_enabled = True


class DeliveryMetrics:
    """Process-global delivery-ledger ledger (`emqx_delivery_*`).

    Plain unlocked ints under the GIL, same discipline as the jsonc /
    framec seams; tests assert deltas."""

    def __init__(self) -> None:
        self.sessions_native = 0
        self.sessions_python = 0
        self.batch_reserves = 0

    def snapshot(self) -> dict:
        return {
            "sessions_native": self.sessions_native,
            "sessions_python": self.sessions_python,
            "batch_reserves": self.batch_reserves,
            "native_enabled": 1 if (_mod is not None and _enabled) else 0,
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        node = f'node="{node_name}"'
        enabled = 1 if (_mod is not None and _enabled) else 0
        return [
            "# TYPE emqx_delivery_native_enabled gauge",
            f"emqx_delivery_native_enabled{{{node}}} {enabled}",
            "# TYPE emqx_delivery_sessions_native_total counter",
            f"emqx_delivery_sessions_native_total{{{node}}} "
            f"{self.sessions_native}",
            "# TYPE emqx_delivery_sessions_python_total counter",
            f"emqx_delivery_sessions_python_total{{{node}}} "
            f"{self.sessions_python}",
            "# TYPE emqx_delivery_batch_reserves_total counter",
            f"emqx_delivery_batch_reserves_total{{{node}}} "
            f"{self.batch_reserves}",
        ]


DELIVERY_METRICS = DeliveryMetrics()


def set_native_enabled(flag: bool) -> None:
    """Config seam for the `broker.perf.tpu_delivery_native` knob."""
    global _enabled
    _enabled = bool(flag)


def native_enabled() -> bool:
    return _enabled and _load() is not None


def _probe(mod) -> bool:
    """Mini parity probe: one slot through reserve / ack / enqueue /
    dump against hand-computed expectations, so a committed .so missing
    the delivery legs (or miscompiled) falls back instead of lying."""
    try:
        h = mod.delivery_make_handle()
        slot = mod.delivery_open(h)
        if mod.delivery_reserve(h, slot, 1, 1.5, 2) != 1:
            return False
        if mod.delivery_reserve(h, slot, 2, 2.5, 2) != 2:
            return False
        if mod.delivery_reserve(h, slot, 1, 3.5, 2) != 0:  # window full
            return False
        if mod.delivery_ack(h, slot, 2, PHASE_PUBACK) != 0:  # wrong phase
            return False
        if mod.delivery_ack(h, slot, 2, PHASE_PUBREC) != 1:
            return False
        if mod.delivery_ack(h, slot, 1, PHASE_PUBACK) != 1:
            return False
        # overflow: QoS0 victim at index 0, insert at tail of 1-queue
        if mod.delivery_enqueue(h, slot, 1, 0, 2, 0) != 1:
            return False
        if mod.delivery_enqueue(h, slot, 1, 1, 2, 0) != (1 | (1 << 2)):
            return False
        # overflow evicts the QoS0 entry at index 0; the higher-
        # priority incoming message then inserts at the head
        packed = mod.delivery_enqueue(h, slot, 2, 1, 2, 1)
        if packed != (2 | (0 << 2) | (0 << 32)):
            return False
        if mod.delivery_dump(h, slot) != (
            3,
            [(2, PHASE_PUBCOMP, 0, 2.5)],
            [(2, 1), (1, 1)],
        ):
            return False
        mod.delivery_close(h, slot)
        return True
    except Exception:
        return False


def _load():
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    _tried = True
    mod = _speedups.load()
    if mod is None or not hasattr(mod, "delivery_make_handle"):
        return None
    if not _probe(mod):
        return None
    _mod = mod
    return _mod


class PyDeliveryLedger:
    """Bit-exact Python twin of the native delivery legs.

    Slots hold `[next_pid, infl, queue]` where `infl` is a list of
    `[pid, phase, dup, sent_at]` in insertion order and `queue` a list
    of `(prio, qos)` shadow entries; every method mirrors one
    `delivery_*` export, result-for-result."""

    is_native = False

    def __init__(self) -> None:
        self._slots: List[Optional[list]] = []
        self._free: List[int] = []

    def open(self) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            slot = len(self._slots)
            self._slots.append(None)
        self._slots[slot] = [1, [], []]
        return slot

    def close(self, slot: int) -> None:
        if 0 <= slot < len(self._slots) and self._slots[slot] is not None:
            self._slots[slot] = None
            self._free.append(slot)

    def _slot(self, slot: int) -> list:
        if not (0 <= slot < len(self._slots)) or self._slots[slot] is None:
            raise ValueError("bad delivery slot")
        return self._slots[slot]

    def _alloc_pid(self, s: list) -> int:
        taken = {e[0] for e in s[1]}
        for _ in range(0xFFFF):
            pid = s[0]
            s[0] = pid % 0xFFFF + 1
            if pid not in taken:
                return pid
        return -1

    def _reserve_one(self, s: list, qos: int, now: float, recv_max: int) -> int:
        if len(s[1]) >= recv_max:
            return 0
        pid = self._alloc_pid(s)
        if pid < 0:
            raise RuntimeError("no free packet id")
        s[1].append([pid, PHASE_PUBACK if qos == 1 else PHASE_PUBREC, 0, now])
        return pid

    def reserve(self, slot: int, qos: int, now: float, recv_max: int) -> int:
        return self._reserve_one(self._slot(slot), qos, now, recv_max)

    def reserve_many(
        self,
        slots: Sequence[int],
        qoses: Sequence[int],
        now: float,
        recv_maxes: Sequence[int],
    ) -> List[int]:
        return [
            self._reserve_one(self._slot(slot), qos, now, rmax)
            for slot, qos, rmax in zip(slots, qoses, recv_maxes)
        ]

    def ack(self, slot: int, pid: int, kind: int) -> int:
        s = self._slot(slot)
        for i, e in enumerate(s[1]):
            if e[0] != pid:
                continue
            if e[1] != kind:
                return 0
            if kind == PHASE_PUBREC:
                e[1] = PHASE_PUBCOMP
            else:
                del s[1][i]
            return 1
        return 0

    def forget(self, slot: int, pid: int) -> int:
        s = self._slot(slot)
        for i, e in enumerate(s[1]):
            if e[0] == pid:
                del s[1][i]
                return 1
        return 0

    def retry_due(
        self, slot: int, now: float, interval: float
    ) -> List[Tuple[int, int]]:
        out = []
        for e in self._slot(slot)[1]:
            if now - e[3] < interval:
                continue
            e[3] = now
            e[2] = 1
            out.append((e[0], e[1]))
        return out

    def touch_all(self, slot: int, now: float) -> List[Tuple[int, int]]:
        out = []
        for e in self._slot(slot)[1]:
            e[3] = now
            out.append((e[0], e[1]))
        return out

    def enqueue(
        self,
        slot: int,
        prio: int,
        qos: int,
        max_len: int,
        has_prios: int,
    ) -> int:
        q = self._slot(slot)[2]
        prio &= 0x3FFF
        qos &= 0x3
        action, victim = 1, -1
        if len(q) >= max_len:
            for i in range(len(q) - 1, -1, -1):
                if q[i][1] == 0 and q[i][0] <= prio:
                    victim = i
                    break
            if victim < 0 and q and q[-1][0] < prio:
                victim = len(q) - 1
            if victim < 0:
                return 0
            del q[victim]
            action = 2
        idx = len(q)
        if has_prios and q:
            while idx > 0 and q[idx - 1][0] < prio:
                idx -= 1
        q.insert(idx, (prio, qos))
        packed = action | (idx << 2)
        if action == 2:
            packed |= victim << 32
        return packed

    def popleft(self, slot: int) -> int:
        q = self._slot(slot)[2]
        if not q:
            return 0
        del q[0]
        return 1

    def window_len(self, slot: int) -> int:
        return len(self._slot(slot)[1])

    def dump(self, slot: int) -> tuple:
        s = self._slot(slot)
        return (
            s[0],
            [tuple(e) for e in s[1]],
            list(s[2]),
        )


class NativeDeliveryLedger:
    """Capsule-handle wrapper over the `delivery_*` native legs, same
    method surface as the twin."""

    is_native = True

    def __init__(self, mod) -> None:
        self._mod = mod
        self._h = mod.delivery_make_handle()

    def open(self) -> int:
        return self._mod.delivery_open(self._h)

    def close(self, slot: int) -> None:
        self._mod.delivery_close(self._h, slot)

    def reserve(self, slot: int, qos: int, now: float, recv_max: int) -> int:
        return self._mod.delivery_reserve(self._h, slot, qos, now, recv_max)

    def reserve_many(self, slots, qoses, now, recv_maxes) -> List[int]:
        return self._mod.delivery_reserve_many(
            self._h, slots, qoses, now, recv_maxes
        )

    def ack(self, slot: int, pid: int, kind: int) -> int:
        return self._mod.delivery_ack(self._h, slot, pid, kind)

    def forget(self, slot: int, pid: int) -> int:
        return self._mod.delivery_forget(self._h, slot, pid)

    def retry_due(self, slot: int, now: float, interval: float):
        return self._mod.delivery_retry_due(self._h, slot, now, interval)

    def touch_all(self, slot: int, now: float):
        return self._mod.delivery_touch_all(self._h, slot, now)

    def enqueue(self, slot, prio, qos, max_len, has_prios) -> int:
        return self._mod.delivery_enqueue(
            self._h, slot, prio, qos, max_len, has_prios
        )

    def popleft(self, slot: int) -> int:
        return self._mod.delivery_popleft(self._h, slot)

    def window_len(self, slot: int) -> int:
        return self._mod.delivery_window_len(self._h, slot)

    def dump(self, slot: int) -> tuple:
        return self._mod.delivery_dump(self._h, slot)


_native_ledger: Optional[NativeDeliveryLedger] = None
_py_ledger: Optional[PyDeliveryLedger] = None


def make_ledger():
    """The process-global ledger a new Session binds to: native when
    the knob allows and the extension carries the delivery legs, the
    Python twin otherwise — counted either way so the split shows up
    on the scrape."""
    global _native_ledger, _py_ledger
    if _enabled:
        mod = _load()
        if mod is not None:
            if _native_ledger is None:
                _native_ledger = NativeDeliveryLedger(mod)
            DELIVERY_METRICS.sessions_native += 1
            return _native_ledger
    if _py_ledger is None:
        _py_ledger = PyDeliveryLedger()
    DELIVERY_METRICS.sessions_python += 1
    return _py_ledger
