"""QUIC v1 packet protection + TLS 1.3 key schedule (RFC 9001/8446).

The reference's QUIC transport is the quicer NIF around MsQuic
(apps/emqx/src/emqx_quic_connection.erl, emqx_listeners.erl:193-210).
No QUIC library ships in this image, so the protocol is implemented
from the RFCs on the `cryptography` primitives:

  * HKDF-Expand-Label / Derive-Secret (RFC 8446 §7.1)
  * v1 initial secrets from the client's DCID (RFC 9001 §5.2)
  * AEAD packet protection: AES-128-GCM, nonce = iv XOR packet number
    (RFC 9001 §5.3), AES-ECB header protection masks (§5.4)
  * the TLS 1.3 key schedule through handshake and application
    traffic secrets, finished keys, and the CertificateVerify
    content (§4.4.3)

Only the profile both our endpoints speak: TLS_AES_128_GCM_SHA256 +
x25519 + ecdsa_secp256r1_sha256. That is also MsQuic's default suite."""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Optional, Tuple

from cryptography.hazmat.primitives.ciphers import Cipher
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.ciphers.algorithms import AES
from cryptography.hazmat.primitives.ciphers.modes import ECB

INITIAL_SALT_V1 = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")
HASH_LEN = 32  # SHA-256


def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: str, context: bytes,
                      length: int) -> bytes:
    full = b"tls13 " + label.encode()
    info = (
        struct.pack(">H", length)
        + bytes([len(full)]) + full
        + bytes([len(context)]) + context
    )
    return hkdf_expand(secret, info, length)


def derive_secret(secret: bytes, label: str, transcript: bytes) -> bytes:
    return hkdf_expand_label(
        secret, label, hashlib.sha256(transcript).digest(), HASH_LEN
    )


class DirectionKeys:
    """AEAD + header-protection keys for one direction at one level."""

    def __init__(self, secret: bytes):
        self.secret = secret
        self.key = hkdf_expand_label(secret, "quic key", b"", 16)
        self.iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        self.hp = hkdf_expand_label(secret, "quic hp", b"", 16)
        self._aead = AESGCM(self.key)

    def nonce(self, pn: int) -> bytes:
        return bytes(
            b ^ ((pn >> (8 * (11 - i))) & 0xFF)
            for i, b in enumerate(self.iv)
        )

    def seal(self, pn: int, header: bytes, payload: bytes) -> bytes:
        return self._aead.encrypt(self.nonce(pn), payload, header)

    def open(self, pn: int, header: bytes, cipher: bytes) -> bytes:
        return self._aead.decrypt(self.nonce(pn), cipher, header)

    def hp_mask(self, sample: bytes) -> bytes:
        enc = Cipher(AES(self.hp), ECB()).encryptor()
        return enc.update(sample)[:5]


def initial_keys(dcid: bytes, is_server: bool) -> Tuple[DirectionKeys, DirectionKeys]:
    """(receive_keys, send_keys) for the Initial space (RFC 9001 §5.2):
    both directions derive from the client's first DCID."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    client = DirectionKeys(
        hkdf_expand_label(initial, "client in", b"", HASH_LEN)
    )
    server = DirectionKeys(
        hkdf_expand_label(initial, "server in", b"", HASH_LEN)
    )
    return (client, server) if is_server else (server, client)


class KeySchedule:
    """RFC 8446 §7.1 through the application secrets."""

    def __init__(self) -> None:
        zeros = b"\x00" * HASH_LEN
        self.early = hkdf_extract(zeros, zeros)
        self.hs: Optional[bytes] = None
        self.master: Optional[bytes] = None

    def set_psk(self, psk: bytes) -> None:
        """Seed the early secret from an external PSK (RFC 8446 §7.1:
        Early = HKDF-Extract(0, PSK)). Must run before handshake()."""
        self.early = hkdf_extract(b"\x00" * HASH_LEN, psk)

    def binder_key(self) -> bytes:
        """The external-PSK binder base key (§4.2.11.2 'ext binder')."""
        return derive_secret(self.early, "ext binder", b"")

    def handshake(self, ecdhe: bytes) -> None:
        derived = derive_secret(self.early, "derived", b"")
        self.hs = hkdf_extract(derived, ecdhe)

    def hs_traffic(self, transcript: bytes) -> Tuple[bytes, bytes]:
        return (
            derive_secret(self.hs, "c hs traffic", transcript),
            derive_secret(self.hs, "s hs traffic", transcript),
        )

    def derive_master(self) -> None:
        derived = derive_secret(self.hs, "derived", b"")
        self.master = hkdf_extract(derived, b"\x00" * HASH_LEN)

    def app_traffic(self, transcript: bytes) -> Tuple[bytes, bytes]:
        return (
            derive_secret(self.master, "c ap traffic", transcript),
            derive_secret(self.master, "s ap traffic", transcript),
        )


def finished_verify(base_secret: bytes, transcript: bytes) -> bytes:
    fk = hkdf_expand_label(base_secret, "finished", b"", HASH_LEN)
    return hmac.new(fk, hashlib.sha256(transcript).digest(),
                    hashlib.sha256).digest()


CERT_VERIFY_PREFIX = (
    b" " * 64 + b"TLS 1.3, server CertificateVerify" + b"\x00"
)


def cert_verify_content(transcript: bytes) -> bytes:
    return CERT_VERIFY_PREFIX + hashlib.sha256(transcript).digest()


# --- varint (RFC 9000 §16) -------------------------------------------------


def enc_varint(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return struct.pack(">H", v | 0x4000)
    if v < 0x40000000:
        return struct.pack(">I", v | 0x80000000)
    return struct.pack(">Q", v | 0xC000000000000000)


def dec_varint(data: bytes, off: int) -> Tuple[int, int]:
    first = data[off]
    kind = first >> 6
    if kind == 0:
        return first, off + 1
    if kind == 1:
        return struct.unpack_from(">H", data, off)[0] & 0x3FFF, off + 2
    if kind == 2:
        return struct.unpack_from(">I", data, off)[0] & 0x3FFFFFFF, off + 4
    return (
        struct.unpack_from(">Q", data, off)[0] & 0x3FFFFFFFFFFFFFFF,
        off + 8,
    )


# --- packet protection (seal/open whole packets) ---------------------------


def encode_pn(pn: int) -> bytes:
    """Always 2-byte packet-number encoding (both ends are ours and
    never fall behind by > 2^15 — the spec's minimal-length rule is an
    optimization, not a requirement)."""
    return struct.pack(">H", pn & 0xFFFF)


def protect(keys: DirectionKeys, header: bytes, pn: int,
            payload: bytes, pn_offset: int) -> bytes:
    """AEAD-seal + header-protect one packet whose plaintext header
    (with unprotected 2-byte pn at pn_offset) is given."""
    sealed = keys.seal(pn, header, payload)
    pkt = bytearray(header + sealed)
    sample = bytes(pkt[pn_offset + 4 : pn_offset + 20])
    mask = keys.hp_mask(sample)
    if pkt[0] & 0x80:
        pkt[0] ^= mask[0] & 0x0F
    else:
        pkt[0] ^= mask[0] & 0x1F
    pkt[pn_offset] ^= mask[1]
    pkt[pn_offset + 1] ^= mask[2]
    return bytes(pkt)


def unprotect(keys: DirectionKeys, pkt: bytes, pn_offset: int,
              largest_pn: int) -> Tuple[int, bytes]:
    """Remove header protection + AEAD-open; returns (pn, payload).
    Raises on auth failure."""
    buf = bytearray(pkt)
    sample = bytes(buf[pn_offset + 4 : pn_offset + 20])
    mask = keys.hp_mask(sample)
    if buf[0] & 0x80:
        buf[0] ^= mask[0] & 0x0F
    else:
        buf[0] ^= mask[0] & 0x1F
    pn_len = (buf[0] & 0x03) + 1
    for i in range(pn_len):
        buf[pn_offset + i] ^= mask[1 + i]
    truncated = int.from_bytes(buf[pn_offset : pn_offset + pn_len], "big")
    # RFC 9000 §A.3 packet number recovery
    window = 1 << (8 * pn_len)
    expected = largest_pn + 1
    candidate = (expected & ~(window - 1)) | truncated
    if candidate <= expected - window // 2 and candidate + window < (1 << 62):
        candidate += window
    elif candidate > expected + window // 2 and candidate >= window:
        candidate -= window
    header = bytes(buf[: pn_offset + pn_len])
    payload = keys.open(candidate, header, bytes(buf[pn_offset + pn_len:]))
    return candidate, payload
