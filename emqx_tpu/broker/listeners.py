"""Listener lifecycle: start/stop/update the set of configured
listeners (tcp/ssl/ws/wss).

The reference starts each configured listener through one dispatcher —
esockd for tcp/ssl, cowboy for ws/wss — keyed `{Type, Name}` with
per-listener bind/limits, and supports runtime add/remove/update with
restart-on-bind-change (apps/emqx/src/emqx_listeners.erl:444-455,657).
This manager does the same over broker.server.Server, which already
folds all four types into (ssl_context, websocket) flags.

Config shape (config/default_schema.py `listeners` root):
    listeners:
      tcp:  {default: {bind: "0.0.0.0:1883", enabled: true, ...}}
      ssl:  {default: {bind: "0.0.0.0:8883", certfile: ..., keyfile: ...}}
      ws:   {default: {bind: "0.0.0.0:8083", path: "/mqtt"}}
      wss:  {default: {bind: "0.0.0.0:8084", certfile: ..., keyfile: ...}}
"""

from __future__ import annotations

import asyncio
import logging
import ssl as ssl_mod
from typing import Dict, Optional, Tuple

from .limiter import ListenerLimits
from .pubsub import Broker
from .server import Server

log = logging.getLogger("emqx_tpu.listeners")

LISTENER_TYPES = ("tcp", "ssl", "ws", "wss", "quic")


def parse_bind(bind) -> Tuple[str, int]:
    """'1883' | ':1883' | 'host:1883' -> (host, port)."""
    if isinstance(bind, int):
        return "0.0.0.0", bind
    s = str(bind)
    if ":" in s:
        host, port = s.rsplit(":", 1)
        return host or "0.0.0.0", int(port)
    return "0.0.0.0", int(s)


def make_ssl_context(conf: Dict) -> ssl_mod.SSLContext:
    # accepts both the schema's ssl_-prefixed keys (listener_struct,
    # config/default_schema.py) and bare certfile/keyfile
    certfile = conf.get("certfile") or conf.get("ssl_certfile")
    keyfile = conf.get("keyfile") or conf.get("ssl_keyfile")
    ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    cacert = conf.get("cacertfile") or conf.get("ssl_cacertfile")
    if cacert:
        ctx.load_verify_locations(cacert)
    if conf.get("verify", conf.get("ssl_verify")) == "verify_peer":
        ctx.verify_mode = ssl_mod.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl_mod.CERT_NONE
    # CRL revocation checking, declared purely in listener config
    # (ref: emqx_crl_cache.erl wired via the listener ssl opts).
    # FAIL-CLOSED at build time: enabling the check with no URLs or an
    # unfetchable CRL refuses the listener rather than silently
    # accepting revoked certificates. A background task (Listeners)
    # re-fetches and re-arms the live context every refresh interval,
    # so post-start revocations take effect and the loaded CRL cannot
    # age past nextUpdate (which would fail every handshake).
    if conf.get("ssl_crl_check") or conf.get("enable_crl_check"):
        from .tls_extras import CrlCache

        urls = (
            conf.get("ssl_crl_cache_urls")
            or conf.get("crl_cache_urls")
            or []
        )
        if not urls:
            raise ValueError(
                "ssl_crl_check enabled but ssl_crl_cache_urls is empty"
            )
        cache = CrlCache(
            urls,
            refresh_interval=float(
                conf.get("ssl_crl_refresh_interval", 900) or 900
            ),
        )
        if not cache.pem():
            raise ValueError(
                "ssl_crl_check enabled but no CRL could be fetched from "
                + ", ".join(urls)
            )
        cache.apply(ctx)
        ctx.emqx_crl_cache = cache  # surfaced by the listener manager
    return ctx


def make_ocsp_cache(conf: Dict):
    """Per-listener OCSP responder cache (ref: emqx_ocsp_cache.erl),
    built from the listener's config. CPython's ssl module has no
    server-side stapling hook, so on TCP-TLS this cache serves the
    operator surface (status via the management API); the QUIC TLS
    stack staples from the same kind of store."""
    if not conf.get("ssl_ocsp_enable"):
        return None
    url = conf.get("ssl_ocsp_responder_url")
    issuer_file = conf.get("ssl_ocsp_issuer_certfile") or conf.get(
        "cacertfile"
    ) or conf.get("ssl_cacertfile")
    certfile = conf.get("certfile") or conf.get("ssl_certfile")
    if not (url and issuer_file and certfile):
        raise ValueError(
            "ssl_ocsp_enable requires ssl_ocsp_responder_url, a "
            "certfile and an issuer cert (ssl_ocsp_issuer_certfile "
            "or cacertfile)"
        )
    from cryptography.x509 import load_pem_x509_certificate

    from .tls_extras import OcspCache

    with open(certfile, "rb") as f:
        cert = load_pem_x509_certificate(f.read())
    with open(issuer_file, "rb") as f:
        issuer = load_pem_x509_certificate(f.read())
    return OcspCache(
        url, cert, issuer,
        refresh_interval=float(
            conf.get("ssl_ocsp_refresh_interval", 3600) or 3600
        ),
    )


MQTT_ZONE_KEYS = (
    "max_mqueue_len", "max_inflight", "max_awaiting_rel",
    "await_rel_timeout", "retry_interval", "upgrade_qos",
    "mqueue_priorities", "mqueue_default_priority", "mqueue_store_qos0",
    "server_keepalive", "keepalive_multiplier", "session_expiry_interval",
)


def zone_mqtt_conf(config, zone: str) -> Dict:
    """Resolve the zone-overlaid `mqtt` section into a flat dict the
    Channel consumes (emqx_config:get_zone_conf analog)."""
    if config is None:
        return {}
    out = {}
    for key in MQTT_ZONE_KEYS:
        try:
            v = config.get_zone(zone, key, None)
        except Exception:
            v = None
        if v is not None:
            out[key] = v
    return out


class _QuicListener:
    """Start/stop facade pairing the UDP endpoint with its MQTT seat
    so the registry/REST treat quic listeners like any other."""

    def __init__(self, seat: Server, quic):
        self.seat = seat
        self.quic = quic
        self.name = seat.name
        self.broker = seat.broker

    @property
    def listen_addr(self):
        return self.quic.listen_addr

    @property
    def _conns(self):
        return self.seat._conns

    @property
    def evicting(self):
        return self.seat.evicting

    def evict_hold(self):
        self.seat.evict_hold()

    def evict_release(self):
        self.seat.evict_release()

    async def start(self):
        await self.quic.start()
        if self.seat not in self.broker.servers:
            self.broker.servers.append(self.seat)

    async def stop(self):
        await self.quic.stop()
        if self.seat in self.broker.servers:
            self.broker.servers.remove(self.seat)
        for conn in list(self.seat._conns):
            try:
                conn.transport.close()
            except Exception:
                pass


class Listeners:
    """Named-listener registry over a shared Broker."""

    def __init__(self, broker: Broker, config=None, psk_store=None):
        self.broker = broker
        self.config = config  # typed Config for zone-aware session conf
        self._live: Dict[Tuple[str, str], Server] = {}
        self._conf: Dict[Tuple[str, str], Dict] = {}
        # node-wide TLS-PSK identity store (ref: apps/emqx_psk) — fed
        # from config by boot, consumed by QUIC listeners (psk_dhe_ke)
        self.psk_store = psk_store
        # per-listener OCSP caches for operator surfacing
        self.ocsp: Dict[Tuple[str, str], object] = {}
        self._crl_tasks: Dict[Tuple[str, str], object] = {}

    def _build(self, ltype: str, name: str, conf: Dict) -> Server:
        if ltype not in LISTENER_TYPES:
            raise ValueError(f"unknown listener type {ltype!r}")
        host, port = parse_bind(conf.get("bind", 0))
        if ltype == "quic":
            # MQTT-over-QUIC (emqx_listeners.erl:193-210): the MQTT
            # runtime seat is a Server that never opens TCP; the QUIC
            # endpoint owns the UDP socket and feeds it stream-0
            # transports. Listener limits gate accepts exactly like
            # the TCP path; certfile/keyfile feed the TLS 1.3 stack.
            from .quic import QuicServer

            seat = Server(
                self.broker,
                host=host,
                port=port,
                limits=ListenerLimits(
                    max_conn_rate=conf.get("max_conn_rate"),
                    messages_rate=conf.get("messages_rate"),
                    bytes_rate=conf.get("bytes_rate"),
                ),
                name=f"quic:{name}",
                mountpoint=conf.get("mountpoint", ""),
                mqtt_conf=zone_mqtt_conf(
                    self.config, conf.get("zone", "default")
                ),
                **(
                    {"max_packet_size": conf["max_packet_size"]}
                    if conf.get("max_packet_size")
                    else {}
                ),
            )
            cert = None
            if conf.get("certfile") and conf.get("keyfile"):
                from cryptography.hazmat.primitives.serialization import (
                    load_pem_private_key,
                )
                from cryptography.x509 import load_pem_x509_certificate
                from cryptography.hazmat.primitives.serialization import (
                    Encoding,
                )

                with open(conf["keyfile"], "rb") as f:
                    key = load_pem_private_key(f.read(), password=None)
                with open(conf["certfile"], "rb") as f:
                    der = load_pem_x509_certificate(f.read()).public_bytes(
                        Encoding.DER
                    )
                cert = (key, der)
            return _QuicListener(
                seat,
                QuicServer(
                    seat, host, port, cert=cert,
                    psk_store=self.psk_store,
                ),
            )
        limits = ListenerLimits(
            max_conn_rate=conf.get("max_conn_rate"),
            messages_rate=conf.get("messages_rate"),
            bytes_rate=conf.get("bytes_rate"),
        )
        ctx = make_ssl_context(conf) if ltype in ("ssl", "wss") else None
        return Server(
            self.broker,
            host=host,
            port=port,
            limits=limits,
            ssl_context=ctx,
            websocket=ltype in ("ws", "wss"),
            ws_path=conf.get("path", "/mqtt"),
            name=f"{ltype}:{name}",
            mountpoint=conf.get("mountpoint", ""),
            mqtt_conf=zone_mqtt_conf(self.config, conf.get("zone", "default")),
            **(
                {"max_packet_size": conf["max_packet_size"]}
                if conf.get("max_packet_size")
                else {}
            ),
        )

    async def start(self, ltype: str, name: str, conf: Dict) -> Server:
        key = (ltype, name)
        if key in self._live:
            raise ValueError(f"listener {ltype}:{name} already running")
        srv = self._build(ltype, name, conf)
        cache = make_ocsp_cache(conf) if ltype in ("ssl", "wss") else None
        await srv.start()
        if cache is not None:
            self.ocsp[key] = cache
        self._live[key] = srv
        self._conf[key] = dict(conf)
        crl = getattr(getattr(srv, "ssl_context", None), "emqx_crl_cache",
                      None)
        if crl is not None:
            self._crl_tasks[key] = asyncio.get_running_loop().create_task(
                self._crl_refresh_loop(key, srv.ssl_context, crl)
            )
        return srv

    async def _crl_refresh_loop(self, key, ctx, cache) -> None:
        """Periodically re-fetch the listener's CRLs and re-arm the
        LIVE context (load_verify_locations applies to new handshakes)
        — the reference's emqx_crl_cache timer refresh."""
        while True:
            await asyncio.sleep(max(30.0, cache.refresh_interval))
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: cache.refresh(force=True)
                )
                cache.apply(ctx)
            except Exception:
                log.exception("CRL refresh failed for listener %s", key)

    async def stop(self, ltype: str, name: str) -> bool:
        srv = self._live.pop((ltype, name), None)
        self.ocsp.pop((ltype, name), None)
        task = self._crl_tasks.pop((ltype, name), None)
        if task is not None:
            task.cancel()
        if srv is None:
            return False
        # the CONFIG survives a stop: a later start() without an
        # explicit config restores the listener as it was, instead of
        # rebinding on schema defaults
        await srv.stop()
        return True

    def conf_of(self, ltype: str, name: str) -> Optional[Dict]:
        c = self._conf.get((ltype, name))
        return dict(c) if c is not None else None

    async def update(self, ltype: str, name: str, conf: Dict) -> Server:
        """Restart-on-update (the reference restarts when bind or
        transport options change; we keep the simple uniform rule).
        The new config is validated by construction BEFORE the old
        listener stops, and a failed start rolls back to the previous
        config — a rejected change must not turn into an outage."""
        self._build(ltype, name, conf)  # validate (bind parse, certs)
        if ltype in ("ssl", "wss"):
            make_ocsp_cache(conf)  # validate OCSP opts before the stop
        old_conf = self._conf.get((ltype, name))
        was_running = (ltype, name) in self._live
        await self.stop(ltype, name)
        try:
            return await self.start(ltype, name, conf)
        except Exception:
            # roll back only what was RUNNING — a failed update must
            # never resurrect a deliberately-stopped listener
            if was_running and old_conf is not None:
                try:
                    await self.start(ltype, name, old_conf)
                except Exception:
                    log.exception(
                        "rollback of listener %s:%s failed", ltype, name
                    )
            raise

    async def start_all(self, conf: Dict) -> None:
        """Bring up every enabled listener from a `listeners` config
        root; errors abort startup (reference fails the boot when a
        listener cannot bind)."""
        for ltype, by_name in (conf or {}).items():
            for name, lconf in (by_name or {}).items():
                if lconf.get("enabled", lconf.get("enable", True)):
                    await self.start(ltype, name, lconf)

    async def stop_all(self) -> None:
        for ltype, name in list(self._live):
            await self.stop(ltype, name)

    def get(self, ltype: str, name: str) -> Optional[Server]:
        return self._live.get((ltype, name))

    def info(self) -> list:
        out = []
        for (ltype, name), srv in sorted(self._live.items()):
            out.append(
                {
                    "id": f"{ltype}:{name}",
                    "type": ltype,
                    "bind": (
                        f"{srv.listen_addr[0]}:{srv.listen_addr[1]}"
                        if srv.listen_addr
                        else None
                    ),
                    "running": srv._server is not None,
                    "current_connections": len(srv._conns),
                }
            )
        return out
