"""Hook registry — the in-process extension mechanism.

Parity with the reference's emqx_hooks (apps/emqx/src/emqx_hooks.erl):
named hookpoints hold priority-ordered callback chains;
`run` stops on 'stop', `run_fold` threads an accumulator which
callbacks may replace. Hookpoint names mirror
apps/emqx/src/emqx_hookpoints.erl:41-69 so reference plugins map 1:1.
"""

from __future__ import annotations

import bisect
from time import perf_counter
from typing import Any, Callable, Dict, List, Tuple

# Canonical hookpoints (emqx_hookpoints.erl:41-69)
HOOKPOINTS = [
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.authenticate",
    "client.authorize",
    "client.check_authz_complete",
    "client.check_authn_complete",
    "client.subscribe",
    "client.unsubscribe",
    "client.timeout",
    "client.monitored_process_down",
    "session.created",
    "session.subscribed",
    "session.unsubscribed",
    "session.resumed",
    "session.discarded",
    "session.takenover",
    "session.terminated",
    "message.publish",
    "message.puback",
    "message.delivered",
    "message.acked",
    "message.dropped",
    "message.transformation_failed",
    "schema.validation_failed",
    "delivery.dropped",
]

STOP = object()  # callback return: halt the chain (emqx_hooks 'stop')
OK = None  # continue


class Hooks:
    """Priority-ordered callback chains per hookpoint."""

    def __init__(self, strict: bool = True) -> None:
        self._hooks: Dict[str, List[Tuple[int, int, Callable]]] = {}
        self._seq = 0
        self._strict = strict
        # observability seam: per-hookpoint observers
        # fn(hookpoint, seconds, subject) called after a NON-EMPTY
        # chain run with the chain's wall time and its primary
        # argument (the flight recorder's hook tap). An empty dict —
        # the default — costs one truthiness check per run; a
        # hookpoint without an observer pays one dict probe. Keeping
        # the registration per-point lets the recorder skip the
        # per-delivery points (message.delivered/acked/puback) whose
        # call rate would otherwise dominate the timing cost.
        self.observers: Dict[str, Callable[[str, float, Any], None]] = {}
        # cb -> slow marker (bool, or zero-arg callable evaluated at
        # query time so a chain can become slow when e.g. a network
        # authz source is added after registration)
        self._slow: Dict[str, List[Tuple[Callable, Any]]] = {}

    def _check(self, name: str) -> None:
        if self._strict and name not in HOOKPOINTS:
            raise KeyError(f"unknown hookpoint {name!r}")

    def add(self, name: str, cb: Callable, priority: int = 0, slow: Any = False) -> None:
        """Register; higher priority runs first (emqx_hooks.erl:63-70
        sorts descending, ties keep registration order). `slow` marks a
        callback that may block on I/O (network authz source, out-of-
        proc exhook) — connection loops consult `has_slow` to decide
        whether the chain must run off the event loop."""
        self._check(name)
        chain = self._hooks.setdefault(name, [])
        self._seq += 1
        # sort key: -priority then insertion order
        entry = (-priority, self._seq, cb)
        bisect.insort(chain, entry, key=lambda e: (e[0], e[1]))
        # bisect.insort with key keeps chain sorted
        if slow:
            self._slow.setdefault(name, []).append((cb, slow))

    def delete(self, name: str, cb: Callable) -> None:
        # equality, not identity: `self._method` builds a FRESH bound-
        # method object on every attribute access, so `is` would never
        # match the one stored at add() time (== compares __self__ and
        # __func__; for plain functions it degrades to identity)
        chain = self._hooks.get(name, [])
        self._hooks[name] = [e for e in chain if e[2] != cb]
        if name in self._slow:
            self._slow[name] = [e for e in self._slow[name] if e[0] != cb]

    def has(self, name: str) -> bool:
        """True when any callback is registered (lets hot loops hoist
        the per-delivery chain walk; emqx runs chains unconditionally
        but BEAM call overhead is not Python call overhead)."""
        return bool(self._hooks.get(name))

    def has_slow(self, name: str) -> bool:
        """True when any registered callback may block on I/O."""
        for _cb, marker in self._slow.get(name, ()):
            if marker is True or (callable(marker) and marker()):
                return True
        return False

    def run(self, name: str, *args: Any) -> bool:
        """Run the chain; returns False if a callback returned STOP."""
        chain = self._hooks.get(name)
        if not chain:
            return True
        obs = self.observers.get(name) if self.observers else None
        if obs is None:
            for _, _, cb in chain:
                if cb(*args) is STOP:
                    return False
            return True
        ok = True
        t0 = perf_counter()
        try:
            for _, _, cb in chain:
                if cb(*args) is STOP:
                    ok = False
                    break
        finally:
            obs(name, perf_counter() - t0, args[0] if args else None)
        return ok

    def run_unobserved(self, name: str, *args: Any) -> bool:
        """run() minus the observer probe, for per-delivery hookpoints
        (message.delivered and friends — flight_recorder's
        UNTIMED_HOOKPOINTS): wide-fanout loops call the chain once PER
        DELIVERY, where even a ~100ns dict probe busts the recorder's
        <2% enabled-path budget. Semantically identical to run() for
        any hookpoint that never gets an observer."""
        for _, _, cb in self._hooks.get(name, ()):
            if cb(*args) is STOP:
                return False
        return True

    def run_fold(self, name: str, args: Tuple, acc: Any) -> Any:
        """Fold the accumulator through the chain. Callbacks receive
        (*args, acc) and return None (keep), (STOP, acc'), or acc'."""
        chain = self._hooks.get(name)
        if not chain:
            return acc
        obs = self.observers.get(name) if self.observers else None
        if obs is None:
            return self._fold(chain, args, acc)
        # the fold subject: message.publish passes the message as the
        # ACCUMULATOR (args empty), so fall back to it for correlation
        subject = args[0] if args else acc
        t0 = perf_counter()
        try:
            return self._fold(chain, args, acc)
        finally:
            obs(name, perf_counter() - t0, subject)

    @staticmethod
    def _fold(chain, args: Tuple, acc: Any) -> Any:
        for _, _, cb in chain:
            r = cb(*args, acc)
            if r is None:
                continue
            if isinstance(r, tuple) and len(r) == 2 and r[0] is STOP:
                return r[1]
            acc = r
        return acc
