"""The MQTT protocol state machine, transport-agnostic.

Parity with apps/emqx/src/emqx_channel.erl handle_in/2:361-531:
CONNECT (auth, session open/resume, will), PUBLISH QoS0/1/2 (QoS2
parks packet ids in awaiting_rel and publishes on first receipt,
emqx_channel.erl:705-746), SUBSCRIBE (authz + retained dispatch),
UNSUBSCRIBE, PING, DISCONNECT (normal discards the will). The server
feeds packets in; the channel returns packets to write out.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from .hooks import Hooks
from .message import Message
from .packet import (
    MQTT_V5,
    Auth,
    Connack,
    Connect,
    Disconnect,
    Pingreq,
    Pingresp,
    Puback,
    Publish,
    RC,
    Suback,
    Subscribe,
    Type,
    Unsuback,
    Unsubscribe,
    Will,
)
from .caps import CapError
from .pubsub import Broker, EXCLUSIVE_PREFIX, ExclusiveTaken
from .session import Session, SessionConfig


class ProtocolError(Exception):
    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg or hex(code))
        self.code = code


class Channel:
    def __init__(
        self,
        broker: Broker,
        peer: str = "?",
        mountpoint: str = "",
        max_packet_size: Optional[int] = None,
        mqtt_conf: Optional[dict] = None,
    ):
        self.broker = broker
        self.peer = peer
        self.client_id: Optional[str] = None
        self.username: Optional[str] = None
        self.proto_ver: int = 4
        self.session: Optional[Session] = None
        self.will: Optional[Will] = None
        self.keepalive: int = 0
        self.last_rx: float = time.time()
        self.connected = False
        self.clean_disconnect = False
        self.topic_aliases: dict = {}  # v5 inbound alias -> topic
        # per-listener mountpoint template; resolved at CONNECT
        # (emqx_mountpoint: applied to publish topics, filters, and the
        # will; stripped from deliveries in the outgoing path)
        self.mountpoint_tpl = mountpoint
        self.mountpoint = ""
        # the listener's inbound parser limit, advertised in CONNACK so
        # the client is never told a limit the parser will reject
        self.listener_max_packet = max_packet_size
        # the listener zone's checked `mqtt` section: session windows,
        # mqueue behavior, keepalive policy (emqx zone config)
        self.mqtt_conf = mqtt_conf or {}
        self.keepalive_multiplier = float(
            self.mqtt_conf.get("keepalive_multiplier", 1.5)
        )
        # client's advertised maximum packet size: outgoing PUBLISHes
        # exceeding it are dropped, not sent (MQTT-5 §3.1.2.11.4)
        self.client_max_packet: Optional[int] = None
        # (client_id, verdict) pre-computed by the connection layer's
        # off-loop authenticate run; consumed once by _handle_connect
        self.preauth = None
        # (client_id, verdict) of the pre-run 'client.connect' fold
        self.preconnect = None
        # (action, topic) -> verdict pre-computed off-loop by the
        # connection layer when a slow (network-backed) authorize chain
        # is installed; consumed by _handle_publish/_handle_subscribe
        self.preauthz: dict = {}
        # the client.subscribe fold result when the connection layer
        # already ran the chain off-loop (covers filter rewrites);
        # consumed once by _handle_subscribe so the chain runs ONCE
        self.presub_filters = None

    # --- inbound dispatch -------------------------------------------------

    def handle_packet(self, pkt) -> List[object]:
        self.last_rx = time.time()
        if not self.connected:
            if isinstance(pkt, Connect):
                return self._handle_connect(pkt)
            raise ProtocolError(RC.PROTOCOL_ERROR, "packet before CONNECT")
        if isinstance(pkt, Connect):
            raise ProtocolError(RC.PROTOCOL_ERROR, "duplicate CONNECT")
        if isinstance(pkt, Publish):
            return self._handle_publish(pkt)
        if isinstance(pkt, Puback):
            return self._handle_ack(pkt)
        if isinstance(pkt, Subscribe):
            return self._handle_subscribe(pkt)
        if isinstance(pkt, Unsubscribe):
            return self._handle_unsubscribe(pkt)
        if isinstance(pkt, Pingreq):
            return [Pingresp()]
        if isinstance(pkt, Disconnect):
            self.clean_disconnect = pkt.code == 0
            if (
                self.proto_ver == MQTT_V5
                and self.session is not None
                and "session_expiry_interval" in pkt.props
            ):
                self.session.cfg.session_expiry_interval = pkt.props[
                    "session_expiry_interval"
                ]
            return []
        if isinstance(pkt, Auth):
            raise ProtocolError(RC.BAD_AUTHENTICATION_METHOD, "AUTH unsupported")
        raise ProtocolError(RC.PROTOCOL_ERROR, f"unexpected {type(pkt).__name__}")

    # --- connect ----------------------------------------------------------

    def _handle_connect(self, pkt: Connect) -> List[object]:
        self.proto_ver = pkt.proto_ver
        client_id = pkt.client_id
        if not client_id:
            if not pkt.clean_start:
                return [
                    Connack(
                        False,
                        RC.CLIENT_IDENTIFIER_NOT_VALID
                        if self.proto_ver == MQTT_V5
                        else 2,
                    )
                ]
            client_id = f"auto-{id(self):x}-{int(time.time() * 1000) & 0xFFFFFF:x}"
        # 'client.connect' fold runs BEFORE authentication (the
        # reference's hook posture: license/quota gates and exhook
        # OnClientConnect see every CONNECT attempt). Acc True admits;
        # a reason-code accumulator rejects. The TCP server loop
        # pre-runs this fold (off-loop when a slow hook is registered)
        # and parks the verdict in `preconnect`; other transports run
        # it inline here.
        if self.preconnect is not None and self.preconnect[0] == pkt.client_id:
            ok = self.preconnect[1]
            self.preconnect = None
        elif self.broker.hooks.has("client.connect"):
            ok = self.broker.hooks.run_fold(
                "client.connect",
                (
                    dict(
                        client_id=client_id,
                        username=pkt.username,
                        proto_ver=self.proto_ver,
                        keepalive=pkt.keepalive,
                        clean_start=pkt.clean_start,
                        peer=self.peer,
                    ),
                ),
                True,
            )
        else:
            ok = True
        if ok is not True:
            code = (
                ok
                if isinstance(ok, int) and not isinstance(ok, bool)
                else (RC.UNSPECIFIED_ERROR if self.proto_ver == MQTT_V5 else 3)
            )
            if self.proto_ver != MQTT_V5 and code > 5:
                code = 3  # v3 range: map quota/other to server-unavailable
            return [Connack(False, code)]
        if self.preauth is not None and self.preauth[0] == pkt.client_id:
            # the connection layer ran the authenticate fold OFF-loop
            # (blocking providers like HTTP must not stall the broker)
            ok = self.preauth[1]
            self.preauth = None
        else:
            ok = self.broker.hooks.run_fold(
                "client.authenticate",
                (dict(client_id=client_id, username=pkt.username, password=pkt.password, peer=self.peer),),
                True,
            )
        if ok is not True:
            code = (
                ok
                if isinstance(ok, int) and not isinstance(ok, bool)
                else (RC.NOT_AUTHORIZED if self.proto_ver == MQTT_V5 else 5)
            )
            if self.proto_ver != MQTT_V5 and code > 5:
                code = 5  # v3 CONNACK codes are 0-5; map v5 reasons down
            self.broker.metrics.inc("client.auth.failure")
            return [Connack(False, code)]

        if len(client_id) > self.broker.caps.max_clientid_len:
            return [
                Connack(
                    False,
                    RC.CLIENT_IDENTIFIER_NOT_VALID
                    if self.proto_ver == MQTT_V5
                    else 2,
                )
            ]
        self.mountpoint = (
            self.mountpoint_tpl.replace("${clientid}", client_id).replace(
                "${username}", pkt.username or ""
            )
            if self.mountpoint_tpl
            else ""
        )
        mc = self.mqtt_conf

        def _secs(key, default_s):
            v = mc.get(key)
            return default_s if v is None else float(v) / 1000.0

        # schema encodes the default priority as "lowest"/"highest"
        dp = mc.get("mqueue_default_priority", 0)
        if dp == "lowest":
            dp = 0
        elif dp == "highest":
            dp = 255
        cfg = SessionConfig(
            max_mqueue_len=mc.get("max_mqueue_len", 1000),
            receive_maximum=mc.get("max_inflight", 32),
            max_awaiting_rel=mc.get("max_awaiting_rel", 100),
            await_rel_timeout=_secs("await_rel_timeout", 300.0),
            retry_interval=_secs("retry_interval", 30.0),
            upgrade_qos=mc.get("upgrade_qos", False),
            mqueue_priorities={
                k: int(v) for k, v in (mc.get("mqueue_priorities") or {}).items()
            },
            mqueue_default_priority=int(dp),
            mqueue_store_qos0=mc.get("mqueue_store_qos0", True),
        )
        # the zone's session_expiry_interval caps what clients may ask
        zone_expiry = _secs("session_expiry_interval", float("inf"))
        expiry_adjusted = False
        if self.proto_ver == MQTT_V5:
            asked = pkt.props.get("session_expiry_interval", 0)
            cfg.session_expiry_interval = min(float(asked), zone_expiry)
            expiry_adjusted = cfg.session_expiry_interval != float(asked)
            # the zone inflight cap bounds the client's receive_maximum
            # ask — a 65535 request must not defeat the operator limit
            cfg.receive_maximum = min(
                pkt.props.get("receive_maximum", cfg.receive_maximum),
                cfg.receive_maximum,
            )
            self.client_max_packet = pkt.props.get("maximum_packet_size")
        else:
            # v3: clean_start=False persists up to the zone cap
            cfg.session_expiry_interval = 0 if pkt.clean_start else zone_expiry
        session, present = self.broker.open_session(
            client_id, pkt.clean_start, cfg
        )
        session.mountpoint = self.mountpoint  # hooks (auto-subscribe) read it
        self.session = session
        self.client_id = client_id
        self.username = pkt.username
        self.keepalive = pkt.keepalive
        # v5 server keepalive OVERRIDES the client's ask (advertised in
        # CONNACK, emqx zone mqtt.server_keepalive)
        server_ka = mc.get("server_keepalive")
        if server_ka is not None and self.proto_ver == MQTT_V5:
            self.keepalive = int(server_ka)
        self.will = pkt.will
        self.connected = True
        self.broker.metrics.inc("client.connected")
        self.broker.hooks.run(
            "client.connected", client_id, self.proto_ver, self.peer
        )
        props = (
            self.broker.caps.connack_props(
                cfg.max_awaiting_rel, self.listener_max_packet
            )
            if self.proto_ver == MQTT_V5
            else {}
        )
        if server_ka is not None and self.proto_ver == MQTT_V5:
            props["server_keep_alive"] = int(server_ka)
        if expiry_adjusted:
            # MQTT-5 §3.2.2.3.2: a server using a DIFFERENT expiry than
            # the client asked must say so in CONNACK
            props["session_expiry_interval"] = int(cfg.session_expiry_interval)
        out: List[object] = [Connack(present, 0, props=props)]
        if present:
            out.extend(session.on_reconnect())
        return out

    # --- publish (inbound) -------------------------------------------------

    def _resolve_alias(self, pkt: Publish) -> str:
        if self.proto_ver != MQTT_V5:
            return pkt.topic
        alias = pkt.props.get("topic_alias")
        if alias is None:
            return pkt.topic
        if pkt.topic:
            self.topic_aliases[alias] = pkt.topic
            return pkt.topic
        topic = self.topic_aliases.get(alias)
        if topic is None:
            raise ProtocolError(RC.TOPIC_ALIAS_INVALID, "unknown topic alias")
        return topic

    def _handle_publish(self, pkt: Publish) -> List[object]:
        topic = self._resolve_alias(pkt)
        try:
            from ..ops.topic import validate_name

            validate_name(topic)
        except ValueError:
            raise ProtocolError(RC.TOPIC_NAME_INVALID, topic)
        try:
            self.broker.caps.check_pub(pkt.qos, pkt.retain)
        except CapError as e:
            raise ProtocolError(e.code, topic)
        # authorize on the UNMOUNTED topic — ACLs must see the same
        # namespace on publish and subscribe (mount happens after, like
        # the reference's packet_to_message)
        allowed = self.preauthz.get(("publish", topic))
        if allowed is None:
            allowed = self.broker.hooks.run_fold(
                "client.authorize",
                (self.client_id, "publish", topic),
                True,
            )
        if self.mountpoint:
            topic = self.mountpoint + topic
        if allowed is not True:
            self.broker.metrics.inc("packets.publish.auth_error")
            if pkt.qos == 1:
                return [Puback(Type.PUBACK, pkt.packet_id, RC.NOT_AUTHORIZED)]
            if pkt.qos == 2:
                return [Puback(Type.PUBREC, pkt.packet_id, RC.NOT_AUTHORIZED)]
            return []
        msg = Message(
            topic=topic,
            payload=pkt.payload,
            qos=pkt.qos,
            retain=pkt.retain,
            from_client=self.client_id or "",
            props={
                k: v
                for k, v in pkt.props.items()
                if k in ("message_expiry_interval", "content_type",
                         "response_topic", "correlation_data",
                         "payload_format_indicator", "user_property")
            },
            # publisher identity rides broker-internal headers (the
            # reference's #message.headers), never the wire props
            headers={"username": self.username or "", "peerhost": self.peer},
        )
        if pkt.qos == 0:
            self.broker.publish(msg)
            return []
        if pkt.qos == 1:
            n = self.broker.publish(msg)
            code = 0 if n else RC.NO_MATCHING_SUBSCRIBERS
            return [Puback(Type.PUBACK, pkt.packet_id, code if self.proto_ver == MQTT_V5 else 0)]
        # QoS2: publish on first receipt, park until PUBREL
        assert self.session is not None
        try:
            fresh = self.session.await_rel(pkt.packet_id)
        except OverflowError:
            raise ProtocolError(RC.RECEIVE_MAXIMUM_EXCEEDED, "too many inflight QoS2")
        code = 0
        if fresh:
            n = self.broker.publish(msg)
            if not n and self.proto_ver == MQTT_V5:
                code = RC.NO_MATCHING_SUBSCRIBERS
        elif self.proto_ver == MQTT_V5:
            code = RC.PACKET_IDENTIFIER_IN_USE
        return [Puback(Type.PUBREC, pkt.packet_id, code)]

    # --- acks (outbound flow control) --------------------------------------

    def _handle_ack(self, pkt: Puback) -> List[object]:
        # sampled ack-sweep attribution (obs/sentinel): 1/sample_n ack
        # packets wall-time the inflight bookkeeping + drain below into
        # the `ack_sweep` delivery sub-stage — QoS1/2 ack traffic shows
        # up in the decomposition instead of hiding in socket reads
        st = getattr(self.broker, "sentinel", None)
        clock = st.maybe_ack_clock() if st is not None else None
        if clock is None:
            return self._handle_ack_inner(pkt)
        t0 = clock()
        try:
            return self._handle_ack_inner(pkt)
        finally:
            st.observe_delivery("ack_sweep", clock() - t0)

    def _handle_ack_inner(self, pkt: Puback) -> List[object]:
        assert self.session is not None
        s = self.session
        out: List[object] = []
        if pkt.type == Type.PUBACK:
            if s.on_puback(pkt.packet_id):
                self.broker.hooks.run("message.acked", self.client_id, pkt.packet_id)
            out.extend(s.drain())
        elif pkt.type == Type.PUBREC:
            if s.on_pubrec(pkt.packet_id):
                out.append(Puback(Type.PUBREL, pkt.packet_id))
            else:
                out.append(
                    Puback(
                        Type.PUBREL,
                        pkt.packet_id,
                        RC.PACKET_IDENTIFIER_NOT_FOUND
                        if self.proto_ver == MQTT_V5
                        else 0,
                    )
                )
        elif pkt.type == Type.PUBREL:
            found = s.release_rel(pkt.packet_id)
            out.append(
                Puback(
                    Type.PUBCOMP,
                    pkt.packet_id,
                    0
                    if found or self.proto_ver != MQTT_V5
                    else RC.PACKET_IDENTIFIER_NOT_FOUND,
                )
            )
        elif pkt.type == Type.PUBCOMP:
            if s.on_pubcomp(pkt.packet_id):
                self.broker.hooks.run("message.acked", self.client_id, pkt.packet_id)
            out.extend(s.drain())
        return out

    # --- subscribe / unsubscribe -------------------------------------------

    def _handle_subscribe(self, pkt: Subscribe) -> List[object]:
        assert self.session is not None
        codes: List[int] = []
        out: List[object] = []
        if self.presub_filters is not None:
            filters = self.presub_filters
            self.presub_filters = None
        else:
            acc = self.broker.hooks.run_fold(
                "client.subscribe", (self.client_id,), pkt.filters
            )
            filters = acc if acc is not None else pkt.filters
        reader = self._begin_retained_batch(filters)
        for flt, opts in filters:
            # get, not pop: one SUBSCRIBE may list the same filter twice
            # and both occurrences must hit the pre-resolved verdict.
            # A miss (client.subscribe hook rewrote the filter) falls
            # back to the inline fold
            allowed = self.preauthz.get(("subscribe", flt))
            if allowed is None:
                allowed = self.broker.hooks.run_fold(
                    "client.authorize", (self.client_id, "subscribe", flt), True
                )
            if allowed is not True:
                codes.append(RC.NOT_AUTHORIZED if self.proto_ver == MQTT_V5 else 0x80)
                continue
            exclusive = flt.startswith(EXCLUSIVE_PREFIX)
            try:
                self.broker.caps.check_sub(
                    flt[len(EXCLUSIVE_PREFIX):] if exclusive else flt
                )
            except CapError as e:
                codes.append(e.code if self.proto_ver == MQTT_V5 else 0x80)
                continue
            try:
                retained = self.broker.subscribe(
                    self.session, self._mount_filter(flt), opts,
                    retained_reader=reader,
                )
            except ExclusiveTaken:
                codes.append(
                    RC.QUOTA_EXCEEDED if self.proto_ver == MQTT_V5 else 0x80
                )
                continue
            except ValueError:
                codes.append(
                    RC.TOPIC_FILTER_INVALID if self.proto_ver == MQTT_V5 else 0x80
                )
                continue
            codes.append(opts.qos)
            for m in retained:
                rm = Message(**{**m.__dict__})
                rm.retain = True
                ropts = type(opts)(
                    qos=opts.qos,
                    no_local=opts.no_local,
                    retain_as_published=True,  # retained reads keep the flag
                    retain_handling=opts.retain_handling,
                )
                out.extend(self.session.deliver(rm, ropts))
        return [Suback(pkt.packet_id, codes)] + out

    def _begin_retained_batch(self, filters):
        """Launch ONE batched retained lookup for the whole SUBSCRIBE
        packet (broker.retained_read_begin) before the subscribe loop
        runs authz/route work — the device probe and its D2H copy ride
        under that host work. Returns a reader(real) -> messages for
        Broker.subscribe, or None when the device leg is off or a
        single-filter packet makes batching pointless. Over-fetch
        (e.g. a filter later rejected by caps) is harmless: retained
        reads are side-effect-free."""
        retainer = self.broker.retainer
        if not getattr(retainer, "device_enabled", False) or len(filters) < 2:
            return None
        from ..ops.topic import parse_share

        reals = []
        for flt, opts in filters:
            if opts.retain_handling == 2:
                continue
            f = flt[len(EXCLUSIVE_PREFIX):] if flt.startswith(
                EXCLUSIVE_PREFIX
            ) else flt
            try:
                group, real = parse_share(self._mount_filter(f))
            except Exception:
                continue
            if group is None:  # no retained delivery for shared subs
                reals.append(real)
        if not reals:
            return None
        begun = retainer.retained_read_begin(reals)
        cache: dict = {}

        def reader(real):
            if not cache:
                for r, msgs in zip(
                    reals, retainer.retained_read_finish(begun)
                ):
                    cache.setdefault(r, msgs)
                cache.setdefault("", [])  # finished marker
            hit = cache.get(real)
            # a hook-rewritten or duplicate filter outside the batch
            # takes the single-read path
            return hit if hit is not None else retainer.read(real)

        return reader

    def _handle_unsubscribe(self, pkt: Unsubscribe) -> List[object]:
        assert self.session is not None
        # fold first (topic-rewrite etc. must transform filters the
        # same way the subscribe fold did, emqx_channel process_unsubscribe)
        acc = self.broker.hooks.run_fold(
            "client.unsubscribe", (self.client_id,), pkt.filters
        )
        filters = acc if acc is not None else pkt.filters
        codes = []
        for flt in filters:
            ok = self.broker.unsubscribe(self.session, self._mount_filter(flt))
            codes.append(0 if ok else RC.NO_SUBSCRIPTION_EXISTED)
        return [Unsuback(pkt.packet_id, codes)]

    def _mount_filter(self, flt: str) -> str:
        from ..ops.topic import mount_filter

        return mount_filter(self.mountpoint, flt)

    # --- lifecycle -----------------------------------------------------------

    def keepalive_expired(self, now: Optional[float] = None) -> bool:
        if not self.keepalive:
            return False
        now = now if now is not None else time.time()
        return now - self.last_rx > self.keepalive * self.keepalive_multiplier

    def on_close(self) -> None:
        """Socket gone: publish the will unless cleanly disconnected,
        keep or drop the session per expiry (emqx_channel terminate)."""
        if not self.connected:
            return
        self.connected = False
        self.broker.metrics.inc("client.disconnected")
        if self.will is not None and not self.clean_disconnect:
            self.broker.publish(
                Message(
                    topic=self.mountpoint + self.will.topic,
                    payload=self.will.payload,
                    qos=self.will.qos,
                    retain=self.will.retain,
                    from_client=self.client_id or "",
                )
            )
        self.will = None
        if self.session is not None:
            if self.session.cfg.session_expiry_interval > 0:
                self.session.on_disconnect()
            else:
                self.broker.close_session(self.session)
        self.broker.hooks.run(
            "client.disconnected", self.client_id, "normal" if self.clean_disconnect else "closed"
        )
