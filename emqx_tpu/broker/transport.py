"""Transport adapters: plain TCP and WebSocket (RFC 6455) byte streams.

The reference runs MQTT over four transports — tcp/ssl via esockd
(apps/emqx/src/emqx_listeners.erl:444), ws/wss via cowboy websocket
callbacks (apps/emqx/src/emqx_ws_connection.erl:1-1122). Here the
Channel/Parser stack is byte-oriented and transport-agnostic, so each
transport is a thin adapter with the same four operations; WS framing
(handshake, masking, fragmentation, ping/pong/close) lives entirely in
this module. TLS is not an adapter at all: the TCP listener passes an
`ssl.SSLContext` to asyncio and reads the same byte stream.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import struct
from typing import Optional, Tuple

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# ws opcodes
OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

MAX_WS_HEADER = 8192  # upgrade-request size cap
MAX_WS_FRAME = 16 * 1024 * 1024


class TcpTransport:
    """Plain byte stream (also used under TLS — asyncio wraps it)."""

    ws = False

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer

    def peername(self):
        return self.writer.get_extra_info("peername")

    async def read(self) -> bytes:
        return await self.reader.read(65536)

    def write(self, data: bytes) -> None:
        self.writer.write(data)

    async def drain(self) -> None:
        await self.writer.drain()

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


def ws_accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + WS_GUID).encode()).digest()
    ).decode()


def ws_encode_frame(opcode: int, payload: bytes, mask: Optional[bytes] = None) -> bytes:
    """One ws frame (FIN set). Servers send unmasked; clients pass a
    4-byte mask (RFC 6455 §5.3)."""
    head = bytearray([0x80 | opcode])
    mbit = 0x80 if mask else 0
    n = len(payload)
    if n < 126:
        head.append(mbit | n)
    elif n < 65536:
        head.append(mbit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mbit | 127)
        head += struct.pack(">Q", n)
    if mask:
        head += mask
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return bytes(head) + payload


class WsError(Exception):
    pass


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    data = await reader.readexactly(n)
    return data


async def ws_read_frame(reader: asyncio.StreamReader) -> Tuple[int, bool, bytes]:
    """Read one frame -> (opcode, fin, payload) with unmasking."""
    h = await _read_exact(reader, 2)
    fin = bool(h[0] & 0x80)
    opcode = h[0] & 0x0F
    masked = bool(h[1] & 0x80)
    n = h[1] & 0x7F
    if n == 126:
        n = struct.unpack(">H", await _read_exact(reader, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", await _read_exact(reader, 8))[0]
    if n > MAX_WS_FRAME:
        raise WsError("frame too large")
    mask = await _read_exact(reader, 4) if masked else None
    payload = await _read_exact(reader, n) if n else b""
    if mask:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, fin, payload


class WsTransport:
    """Server side of MQTT-over-WebSocket: binary frames carry the MQTT
    byte stream; fragmentation is reassembled; PING answered inline;
    CLOSE (or EOF) surfaces as an empty read, which the connection loop
    treats as peer disconnect (emqx_ws_connection handles the same
    events via cowboy callbacks)."""

    ws = True

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._closed = False

    @classmethod
    async def handshake(
        cls, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
        path: str = "/mqtt",
    ) -> Optional["WsTransport"]:
        """HTTP/1.1 upgrade for the MQTT listener. Returns None (after
        writing an error response) if the request is not a well-formed
        ws upgrade for `path`; advertises the `mqtt` subprotocol when
        offered."""
        got = await cls.handshake_ex(
            reader, writer,
            path_ok=lambda p: p == path,
            subprotocols=("mqtt",),
        )
        return got[0] if got else None

    @classmethod
    async def handshake_ex(
        cls, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
        path_ok, subprotocols: tuple = (),
    ):
        """Generalized upgrade (gateways ride this with their own path
        shapes and subprotocols, e.g. OCPP's /ocpp/{clientid} +
        ocpp1.6). Returns (transport, request_path, chosen_subprotocol)
        or None. When the client offers subprotocols, one of
        `subprotocols` must match (RFC 6455 §1.9); offering none is
        accepted with no subprotocol header."""
        try:
            raw = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return None
        if len(raw) > MAX_WS_HEADER:
            return None
        lines = raw.decode("latin-1").split("\r\n")
        try:
            method, req_path, _ver = lines[0].split(" ", 2)
        except ValueError:
            return None
        headers = {}
        for ln in lines[1:]:
            if ":" in ln:
                k, v = ln.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get("sec-websocket-key")
        bare_path = req_path.split("?")[0]
        if (
            method != "GET"
            or not path_ok(bare_path)
            or "websocket" not in headers.get("upgrade", "").lower()
            or key is None
        ):
            writer.write(b"HTTP/1.1 400 Bad Request\r\ncontent-length: 0\r\n\r\n")
            return None
        proto_hdr = ""
        chosen = None
        offered = [
            p.strip()
            for p in headers.get("sec-websocket-protocol", "").split(",")
            if p.strip()
        ]
        if offered:
            chosen = next((p for p in offered if p in subprotocols), None)
            if chosen is None:
                writer.write(b"HTTP/1.1 400 Bad Request\r\ncontent-length: 0\r\n\r\n")
                return None
            proto_hdr = f"Sec-WebSocket-Protocol: {chosen}\r\n"
        writer.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {ws_accept_key(key)}\r\n"
                f"{proto_hdr}\r\n"
            ).encode()
        )
        return cls(reader, writer), req_path, chosen

    def peername(self):
        return self.writer.get_extra_info("peername")

    async def read(self) -> bytes:
        """Next chunk of MQTT bytes (reassembled across continuation
        frames); b'' on close/EOF."""
        buf = b""
        while True:
            try:
                opcode, fin, payload = await ws_read_frame(self.reader)
            except (asyncio.IncompleteReadError, ConnectionError, WsError):
                return b""
            if opcode in (OP_BINARY, OP_CONT, OP_TEXT):
                buf += payload
                # cumulative cap: MAX_WS_FRAME bounds the reassembled
                # message too, or an endless fin=0 continuation stream
                # would grow buf without ever reaching the MQTT
                # parser's own packet-size check
                if len(buf) > MAX_WS_FRAME:
                    return b""
                if fin and buf:
                    return buf
                if fin:
                    continue  # empty complete message: keep waiting
            elif opcode == OP_PING:
                try:
                    self.writer.write(ws_encode_frame(OP_PONG, payload))
                    # drain here: a ping flood from a client that never
                    # reads must hit backpressure, not grow the
                    # transmit buffer (the outer loop only drains after
                    # read() returns)
                    await self.writer.drain()
                except Exception:
                    return b""
            elif opcode == OP_CLOSE:
                if not self._closed:
                    try:
                        self.writer.write(ws_encode_frame(OP_CLOSE, payload[:2]))
                    except Exception:
                        pass
                    self._closed = True
                return b""
            # OP_PONG: ignore

    def write(self, data: bytes) -> None:
        self.writer.write(ws_encode_frame(OP_BINARY, data))

    async def drain(self) -> None:
        await self.writer.drain()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.writer.write(ws_encode_frame(OP_CLOSE, b"\x03\xe8"))
            except Exception:
                pass
        try:
            self.writer.close()
        except Exception:
            pass
