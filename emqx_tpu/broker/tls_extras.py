"""TLS hardening surfaces: PSK identity store, CRL cache, OCSP cache.

References:
  * apps/emqx_psk/src/emqx_psk.erl — identity -> shared-secret store,
    bootstrapped from an init file of "identity:secret" lines and
    served to listeners' PSK lookups. Here it feeds the QUIC TLS
    stack's psk_dhe_ke handshake (broker/quic_tls.py; CPython 3.12's
    ssl module has no PSK callbacks for the TCP listener).
  * apps/emqx/src/emqx_crl_cache.erl — per-URL CRL fetch + refresh
    cache; revoked client certs must fail the mTLS handshake. Applied
    to TCP listeners through ssl.SSLContext VERIFY_CRL_CHECK_LEAF.
  * apps/emqx/src/emqx_ocsp_cache.erl — OCSP responder fetch + cache
    of the listener certificate's status (stapling store).
"""

from __future__ import annotations

import logging
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

log = logging.getLogger("emqx_tpu.tls_extras")


class PskStore:
    """identity -> key table with file bootstrap (emqx_psk.erl
    init_file: one "identity<separator>secret" per line, '#' comments;
    the separator is configurable like the reference's
    psk_authentication.chunk separator, default ':')."""

    def __init__(
        self,
        init_file: Optional[str] = None,
        enable: bool = True,
        separator: str = ":",
    ):
        self.enable = enable
        self.separator = separator or ":"
        self._table: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        if init_file:
            self.import_file(init_file)

    @staticmethod
    def _b(v) -> bytes:
        return v.encode() if isinstance(v, str) else bytes(v)

    def import_file(self, path: str) -> int:
        sep = self.separator
        n = 0
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or sep not in line:
                    continue
                ident, _, secret = line.partition(sep)
                self.insert(ident, secret)
                n += 1
        return n

    def insert(self, identity, key) -> None:
        with self._lock:
            self._table[self._b(identity)] = self._b(key)

    def delete(self, identity) -> bool:
        with self._lock:
            return self._table.pop(self._b(identity), None) is not None

    def lookup(self, identity) -> Optional[bytes]:
        if not self.enable:
            return None
        with self._lock:
            return self._table.get(self._b(identity))

    def all(self) -> List[str]:
        with self._lock:
            return sorted(i.decode("utf-8", "replace") for i in self._table)

    def __len__(self) -> int:
        return len(self._table)


class CrlCache:
    """Fetch-and-refresh cache of certificate revocation lists.

    `pem()` returns the concatenated PEM CRLs for loading into an
    ssl.SSLContext (with VERIFY_CRL_CHECK_LEAF); `revoked_serials()`
    feeds hand-rolled verifiers. Refresh is lazy: any read past
    refresh_interval re-fetches (the reference refreshes on a timer,
    emqx_crl_cache.erl:66 — lazy-on-read gives the same staleness
    bound without a background thread)."""

    def __init__(self, urls: List[str], refresh_interval: float = 900.0,
                 http_timeout: float = 10.0,
                 fetcher: Optional[Callable[[str], bytes]] = None):
        self.urls = list(urls)
        self.refresh_interval = refresh_interval
        self.http_timeout = http_timeout
        self._fetch = fetcher or self._http_fetch
        self._crls: Dict[str, object] = {}  # url -> x509.CRL
        self._fetched_at: Dict[str, float] = {}
        self._lock = threading.Lock()

    def _http_fetch(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.http_timeout) as r:
            return r.read()

    def _load(self, der_or_pem: bytes):
        from cryptography import x509

        if der_or_pem.lstrip().startswith(b"-----BEGIN"):
            return x509.load_pem_x509_crl(der_or_pem)
        return x509.load_der_x509_crl(der_or_pem)

    def refresh(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            due = [
                u for u in self.urls
                if force or now - self._fetched_at.get(u, 0) >= (
                    self.refresh_interval
                )
            ]
            # claim the fetch windows up front so concurrent readers
            # don't pile onto the same URLs
            for u in due:
                self._fetched_at[u] = now
        # network I/O OUTSIDE the lock: a slow responder must not
        # stall every reader (or the event loop) for 10s per URL
        fetched = {}
        for url in due:
            try:
                fetched[url] = self._load(self._fetch(url))
            except Exception as e:
                # keep serving the stale CRL rather than dropping
                # revocation data (fail-open on fetch is the
                # reference's evict/keep policy knob)
                log.warning("CRL fetch failed for %s: %s", url, e)
        if fetched:
            with self._lock:
                self._crls.update(fetched)

    def pem(self) -> bytes:
        from cryptography.hazmat.primitives.serialization import Encoding

        self.refresh()
        with self._lock:
            return b"".join(
                crl.public_bytes(Encoding.PEM) for crl in self._crls.values()
            )

    def revoked_serials(self) -> set:
        self.refresh()
        out = set()
        with self._lock:
            for crl in self._crls.values():
                for rev in crl:
                    out.add(rev.serial_number)
        return out

    def is_revoked(self, cert) -> bool:
        return cert.serial_number in self.revoked_serials()

    def apply(self, ssl_context) -> None:
        """Arm an ssl.SSLContext for revocation checking of client
        certificates (mTLS listeners). CPython's cadata= path accepts
        only certificates, so the CRL PEM goes through a temp file."""
        import os
        import ssl
        import tempfile

        data = self.pem()
        if not data:
            return
        fd, path = tempfile.mkstemp(suffix=".crl.pem")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            ssl_context.load_verify_locations(cafile=path)
            ssl_context.verify_flags |= ssl.VERIFY_CRL_CHECK_LEAF
        finally:
            os.unlink(path)


class OcspCache:
    """OCSP response cache for the listener certificate (stapling
    store). Builds the OCSPRequest with the cryptography lib, POSTs it
    to the responder, caches the DER response until its nextUpdate
    (minus a slack) or max_age."""

    def __init__(self, responder_url: str, cert, issuer,
                 refresh_interval: float = 3600.0,
                 http_timeout: float = 10.0,
                 fetcher: Optional[Callable[[str, bytes], bytes]] = None):
        self.responder_url = responder_url
        self.cert, self.issuer = cert, issuer
        self.refresh_interval = refresh_interval
        self.http_timeout = http_timeout
        self._fetch = fetcher or self._http_post
        self._der: Optional[bytes] = None
        self._fetched_at = 0.0
        self._inflight = False
        self._lock = threading.Lock()

    def _http_post(self, url: str, body: bytes) -> bytes:
        req = urllib.request.Request(
            url, data=body,
            headers={"content-type": "application/ocsp-request"},
        )
        with urllib.request.urlopen(req, timeout=self.http_timeout) as r:
            return r.read()

    def build_request(self) -> bytes:
        from cryptography.hazmat.primitives.hashes import SHA256
        from cryptography.x509 import ocsp

        b = ocsp.OCSPRequestBuilder().add_certificate(
            self.cert, self.issuer, SHA256()
        )
        from cryptography.hazmat.primitives.serialization import Encoding

        return b.build().public_bytes(Encoding.DER)

    def response_der(self, force: bool = False) -> Optional[bytes]:
        """The cached DER OCSPResponse (fetches when stale). None when
        the responder is unreachable and nothing is cached."""
        with self._lock:
            fresh = (
                self._der is not None
                and time.time() - self._fetched_at < self.refresh_interval
            )
            if fresh and not force:
                return self._der
            if self._inflight and not force:
                # one fetcher at a time — cold-start stampedes would
                # otherwise all POST the responder concurrently.
                # force=True keeps its always-fetch contract even if
                # that means a concurrent duplicate
                return self._der
            self._inflight = True
            claimed_at = time.time()
            # claim the window; network I/O happens OUTSIDE the lock
            self._fetched_at = claimed_at
        try:
            der = self._fetch(self.responder_url, self.build_request())
            # sanity: parses as an OCSP response
            from cryptography.x509 import ocsp

            ocsp.load_der_ocsp_response(der)
        except Exception as e:
            log.warning("OCSP fetch failed: %s", e)
            der = None
        with self._lock:
            self._inflight = False
            if der is not None:
                self._der = der
            elif self._fetched_at == claimed_at:
                # FAILED refresh must not hold the claim for a whole
                # interval: the next reader retries immediately (an
                # aging response could outlive its nextUpdate and a
                # revoked cert would keep stapling GOOD)
                self._fetched_at = 0.0
            return self._der

    def status(self):
        """Decoded certificate status of the cached response."""
        from cryptography.x509 import ocsp

        der = self.response_der()
        if der is None:
            return None
        resp = ocsp.load_der_ocsp_response(der)
        if resp.response_status != ocsp.OCSPResponseStatus.SUCCESSFUL:
            return resp.response_status.name
        return resp.certificate_status.name
