"""Minimal TLS 1.3 handshake for QUIC (RFC 8446 + RFC 9001 §4).

Covers exactly the profile our two endpoints negotiate:
TLS_AES_128_GCM_SHA256, x25519, ecdsa_secp256r1_sha256 self-signed
server certificates (generated at runtime), ALPN, and the QUIC
transport_parameters extension (0x39) carried opaquely. Handshake
messages flow through QUIC CRYPTO frames — this module only builds/
consumes the TLS byte stream and hands traffic secrets back to the
connection layer at each level switch.

External PSK (psk_dhe_ke, RFC 8446 §4.2.11) authenticates clients
against a listener PskStore: binder verification on the truncated
ClientHello, certificate-free server flight on acceptance, and a
clean fallback to certificate auth for unknown identities.
Client certificates, HelloRetryRequest, resumption tickets, and any
other cipher/group are out of scope; an endpoint offering only those
gets a clean handshake failure."""

from __future__ import annotations

import datetime
import os
import struct
from typing import Dict, List, Optional, Tuple

from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey,
)
from cryptography.hazmat.primitives.hashes import SHA256
from cryptography.hazmat.primitives.serialization import (
    Encoding, PublicFormat,
)
from cryptography import x509
from cryptography.x509.oid import NameOID

from .quic_crypto import (
    KeySchedule, cert_verify_content, finished_verify,
)

TLS_AES_128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_ECDSA_P256 = 0x0403

HS_CLIENT_HELLO = 1
HS_SERVER_HELLO = 2
HS_ENCRYPTED_EXTENSIONS = 8
HS_CERTIFICATE = 11
HS_CERTIFICATE_VERIFY = 15
HS_FINISHED = 20

EXT_SERVER_NAME = 0
EXT_SUPPORTED_GROUPS = 10
EXT_SIG_ALGS = 13
EXT_ALPN = 16
EXT_SUPPORTED_VERSIONS = 43
EXT_PRE_SHARED_KEY = 41
EXT_PSK_MODES = 45
EXT_KEY_SHARE = 51
EXT_QUIC_TP = 0x39
PSK_DHE_KE = 1

TLS13 = 0x0304


class TlsError(Exception):
    pass


def _normalized(fn):
    """Attacker-controlled bytes are indexed/unpacked with no bounds
    checks below; normalize ANY parse failure to TlsError so truncated
    or malformed handshakes take the documented clean CONNECTION_CLOSE
    path (quic.py _crypto_in catches TlsError only) instead of
    escaping as IndexError/struct.error into the catch-all UDP log."""

    def wrap(*args, **kw):
        try:
            return fn(*args, **kw)
        except TlsError:
            raise
        except Exception as e:
            raise TlsError(
                f"malformed TLS message: {type(e).__name__}: {e}"
            ) from e

    return wrap


def _u16(v: int) -> bytes:
    return struct.pack(">H", v)


def _vec(data: bytes, n: int) -> bytes:
    return len(data).to_bytes(n, "big") + data


def _hs_msg(t: int, body: bytes) -> bytes:
    return bytes([t]) + len(body).to_bytes(3, "big") + body


def _exts(pairs: List[Tuple[int, bytes]]) -> bytes:
    out = b"".join(_u16(t) + _vec(v, 2) for t, v in pairs)
    return _vec(out, 2)


def _parse_exts(data: bytes) -> Dict[int, bytes]:
    (total,) = struct.unpack_from(">H", data, 0)
    off = 2
    end = 2 + total
    out = {}
    while off < end:
        t, ln = struct.unpack_from(">HH", data, off)
        off += 4
        out[t] = data[off : off + ln]
        off += ln
    return out


def make_server_cert():
    """Runtime self-signed EC P-256 certificate (the test/dev story;
    production feeds PEMs through the listener config)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "emqx-tpu")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=365))
        .sign(key, SHA256())
    )
    return key, cert.public_bytes(Encoding.DER)


class _MsgBuf:
    """Reassembles TLS handshake messages from the CRYPTO stream."""

    def __init__(self) -> None:
        self.buf = b""

    def feed(self, data: bytes) -> List[Tuple[int, bytes, bytes]]:
        self.buf += data
        out = []
        while len(self.buf) >= 4:
            t = self.buf[0]
            ln = int.from_bytes(self.buf[1:4], "big")
            if len(self.buf) < 4 + ln:
                break
            raw = self.buf[: 4 + ln]
            out.append((t, raw[4:], raw))
            self.buf = self.buf[4 + ln:]
        return out


class TlsServer:
    """Drives the server handshake. Outputs per call: a list of
    (level, bytes) to send as CRYPTO data, where level is 'initial' |
    'handshake'. Secrets surface via the callbacks set by the
    connection layer."""

    def __init__(self, transport_params: bytes, alpn: str = "mqtt",
                 cert: Optional[Tuple[object, bytes]] = None,
                 psk_lookup=None):
        self.tp = transport_params
        self.alpn = alpn
        # identity -> key resolver (broker/psk.py PskStore.lookup);
        # psk_dhe_ke only: the ECDHE exchange stays in, PSK replaces
        # certificate authentication (RFC 8446 §2.2, §4.2.11)
        self.psk_lookup = psk_lookup
        self.psk_identity: Optional[bytes] = None
        self.schedule = KeySchedule()
        self.transcript = b""
        self.buf = _MsgBuf()
        self.priv = X25519PrivateKey.generate()
        # cert = (EC private key, DER): shared per listener — per-
        # connection keygen+signing would hand attackers free CPU burn
        self.cert_key, self.cert_der = cert or make_server_cert()
        self.client_hs_secret = None
        self.server_hs_secret = None
        self.client_app_secret = None
        self.server_app_secret = None
        self.peer_transport_params: Optional[bytes] = None
        self.alpn_selected: Optional[str] = None
        self.handshake_complete = False
        self._sent_flight = False

    # --- client hello -> full server flight ---------------------------

    @_normalized
    def feed_initial(self, data: bytes) -> List[Tuple[str, bytes]]:
        out: List[Tuple[str, bytes]] = []
        for t, body, raw in self.buf.feed(data):
            if t != HS_CLIENT_HELLO or self._sent_flight:
                raise TlsError(f"unexpected handshake message {t}")
            psk = self._select_psk(body, raw)
            self.transcript += raw
            out += self._server_flight(body, psk)
        return out

    def _select_psk(self, ch: bytes, raw: bytes):
        """Parse pre_shared_key (if offered), resolve + verify the
        binder. Returns the accepted (index, identity) or None (fall
        back to certificate auth). A WRONG binder is fatal — it proves
        the client holds a different key for a known identity."""
        if self.psk_lookup is None:
            return None
        off = 2 + 32
        off += 1 + ch[off]
        (cs_len,) = struct.unpack_from(">H", ch, off)
        off += 2 + cs_len
        off += 1 + ch[off]
        exts_blob = ch[off:]
        exts = _parse_exts(exts_blob)
        psk_ext = exts.get(EXT_PRE_SHARED_KEY)
        if psk_ext is None:
            return None
        modes = exts.get(EXT_PSK_MODES, b"")
        if PSK_DHE_KE not in modes[1 : 1 + (modes[0] if modes else 0)]:
            raise TlsError("psk offered without psk_dhe_ke mode")
        (id_total,) = struct.unpack_from(">H", psk_ext, 0)
        p = 2
        identities = []
        while p < 2 + id_total:
            (iln,) = struct.unpack_from(">H", psk_ext, p)
            identities.append(bytes(psk_ext[p + 2 : p + 2 + iln]))
            p += 2 + iln + 4  # + obfuscated_ticket_age
        (b_total,) = struct.unpack_from(">H", psk_ext, p)
        binders = []
        q = p + 2
        while q < p + 2 + b_total:
            bln = psk_ext[q]
            binders.append(bytes(psk_ext[q + 1 : q + 1 + bln]))
            q += 1 + bln
        # binder transcript: the CH (incl. handshake header) truncated
        # before the binders list (§4.2.11.2); pre_shared_key MUST be
        # the last extension, so the binders are the message tail
        trunc = raw[: len(raw) - (2 + b_total)]
        for i, ident in enumerate(identities):
            key = self.psk_lookup(ident)
            if key is None:
                continue
            sched = KeySchedule()
            sched.set_psk(key)
            want = finished_verify(sched.binder_key(), self.transcript + trunc)
            if i >= len(binders) or binders[i] != want:
                raise TlsError("psk binder verification failed")
            self.schedule = sched
            self.psk_identity = ident
            return (i, ident)
        return None

    def _server_flight(self, ch: bytes, psk=None) -> List[Tuple[str, bytes]]:
        off = 2 + 32  # legacy_version + random
        sid_len = ch[off]
        session_id = ch[off + 1 : off + 1 + sid_len]
        off += 1 + sid_len
        (cs_len,) = struct.unpack_from(">H", ch, off)
        suites = [
            struct.unpack_from(">H", ch, off + 2 + i)[0]
            for i in range(0, cs_len, 2)
        ]
        off += 2 + cs_len
        off += 1 + ch[off]  # compression methods
        exts = _parse_exts(ch[off:])
        if TLS_AES_128_GCM_SHA256 not in suites:
            raise TlsError("no common cipher suite")
        sv = exts.get(EXT_SUPPORTED_VERSIONS, b"")
        if TLS13 not in [
            struct.unpack_from(">H", sv, 1 + i)[0]
            for i in range(0, sv[0] if sv else 0, 2)
        ]:
            raise TlsError("client does not offer TLS 1.3")
        ks = exts.get(EXT_KEY_SHARE)
        if ks is None:
            raise TlsError("no key_share")
        (ks_total,) = struct.unpack_from(">H", ks, 0)
        p = 2
        client_pub = None
        while p < 2 + ks_total:
            grp, ln = struct.unpack_from(">HH", ks, p)
            p += 4
            if grp == GROUP_X25519:
                client_pub = ks[p : p + ln]
            p += ln
        if client_pub is None:
            raise TlsError("no x25519 key share")
        if EXT_QUIC_TP in exts:
            self.peer_transport_params = exts[EXT_QUIC_TP]
        alpn_ext = exts.get(EXT_ALPN)
        if alpn_ext is not None:
            (al_total,) = struct.unpack_from(">H", alpn_ext, 0)
            p = 2
            offered = []
            while p < 2 + al_total:
                ln = alpn_ext[p]
                offered.append(alpn_ext[p + 1 : p + 1 + ln].decode())
                p += 1 + ln
            if self.alpn not in offered:
                raise TlsError(f"no common ALPN in {offered}")
            self.alpn_selected = self.alpn

        ecdhe = self.priv.exchange(X25519PublicKey.from_public_bytes(client_pub))
        self.schedule.handshake(ecdhe)

        my_pub = self.priv.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw
        )
        sh_exts = [
            (EXT_SUPPORTED_VERSIONS, _u16(TLS13)),
            (EXT_KEY_SHARE, _u16(GROUP_X25519) + _vec(my_pub, 2)),
        ]
        if psk is not None:
            sh_exts.append((EXT_PRE_SHARED_KEY, _u16(psk[0])))
        sh_body = (
            _u16(0x0303) + os.urandom(32) + _vec(session_id, 1)
            + _u16(TLS_AES_128_GCM_SHA256) + b"\x00"
            + _exts(sh_exts)
        )
        sh = _hs_msg(HS_SERVER_HELLO, sh_body)
        self.transcript += sh
        c_hs, s_hs = self.schedule.hs_traffic(self.transcript)
        self.client_hs_secret, self.server_hs_secret = c_hs, s_hs

        ee_pairs = [(EXT_QUIC_TP, self.tp)]
        if self.alpn_selected:
            a = self.alpn_selected.encode()
            ee_pairs.insert(0, (EXT_ALPN, _vec(_vec(a, 1), 2)))
        ee = _hs_msg(HS_ENCRYPTED_EXTENSIONS, _exts(ee_pairs))
        self.transcript += ee
        if psk is None:
            cert = _hs_msg(
                HS_CERTIFICATE,
                b"\x00" + _vec(_vec(self.cert_der, 3) + _u16(0), 3),
            )
            self.transcript += cert
            sig = self.cert_key.sign(
                cert_verify_content(self.transcript), ec.ECDSA(SHA256())
            )
            cv = _hs_msg(
                HS_CERTIFICATE_VERIFY, _u16(SIG_ECDSA_P256) + _vec(sig, 2)
            )
            self.transcript += cv
            mid = cert + cv
        else:
            # PSK authenticates the peer: no Certificate/Verify (§2.2)
            mid = b""
        fin = _hs_msg(
            HS_FINISHED, finished_verify(s_hs, self.transcript)
        )
        self.transcript += fin
        # application secrets derive from the transcript through the
        # server Finished (RFC 8446 §7.1)
        self.schedule.derive_master()
        self.client_app_secret, self.server_app_secret = (
            self.schedule.app_traffic(self.transcript)
        )
        self._sent_flight = True
        return [("initial", sh), ("handshake", ee + mid + fin)]

    # --- client finished ------------------------------------------------

    @_normalized
    def feed_handshake(self, data: bytes) -> None:
        for t, body, raw in self.buf.feed(data):
            if t != HS_FINISHED:
                raise TlsError(f"unexpected handshake message {t}")
            want = finished_verify(self.client_hs_secret, self.transcript)
            if body != want:
                raise TlsError("bad client Finished")
            self.transcript += raw
            self.handshake_complete = True


class TlsClient:
    """Client side (the in-repo MQTT-over-QUIC client + tests)."""

    def __init__(self, transport_params: bytes, alpn: str = "mqtt",
                 server_name: str = "emqx-tpu",
                 psk_identity: Optional[bytes] = None,
                 psk: Optional[bytes] = None):
        self.tp = transport_params
        self.alpn = alpn
        self.server_name = server_name
        self.psk_identity = (
            psk_identity.encode() if isinstance(psk_identity, str)
            else psk_identity
        )
        self.psk = psk
        self._psk_active = False
        self.schedule = KeySchedule()
        if psk is not None:
            self.schedule.set_psk(psk)
        self.transcript = b""
        self.buf = _MsgBuf()
        self.priv = X25519PrivateKey.generate()
        self.client_hs_secret = None
        self.server_hs_secret = None
        self.client_app_secret = None
        self.server_app_secret = None
        self.peer_transport_params: Optional[bytes] = None
        self.handshake_complete = False
        self._fin_out: Optional[bytes] = None

    def client_hello(self) -> bytes:
        my_pub = self.priv.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw
        )
        sni = _vec(_vec(b"\x00" + _vec(self.server_name.encode(), 2), 2)[2:], 2)
        a = self.alpn.encode()
        pairs = [
            (EXT_SERVER_NAME, sni),
            (EXT_SUPPORTED_GROUPS, _vec(_u16(GROUP_X25519), 2)),
            (EXT_SIG_ALGS, _vec(_u16(SIG_ECDSA_P256), 2)),
            (EXT_SUPPORTED_VERSIONS, b"\x02" + _u16(TLS13)),
            (EXT_ALPN, _vec(_vec(a, 1), 2)),
            (EXT_KEY_SHARE, _vec(_u16(GROUP_X25519) + _vec(my_pub, 2), 2)),
            (EXT_QUIC_TP, self.tp),
        ]
        prefix = (
            _u16(0x0303) + os.urandom(32) + _vec(b"", 1)
            + _vec(_u16(TLS_AES_128_GCM_SHA256), 2) + _vec(b"\x00", 1)
        )
        if self.psk is None:
            body = prefix + _exts(pairs)
            ch = _hs_msg(HS_CLIENT_HELLO, body)
            self.transcript += ch
            return ch
        # PSK offer: psk_key_exchange_modes + pre_shared_key LAST
        # (RFC 8446 §4.2.11); the binder HMACs the truncated hello
        # (incl. the 4-byte handshake header) with the ext-binder key
        pairs.append((EXT_PSK_MODES, bytes([1, PSK_DHE_KE])))
        identity = _vec(self.psk_identity or b"", 2) + b"\x00" * 4
        binders = _vec(_vec(b"\x00" * 32, 1), 2)  # placeholder
        psk_ext = _vec(identity, 2) + binders
        pairs.append((EXT_PRE_SHARED_KEY, psk_ext))
        body = prefix + _exts(pairs)
        ch = _hs_msg(HS_CLIENT_HELLO, body)
        trunc = ch[: len(ch) - 35]  # 2(list len) + 1 + 32 binder bytes
        binder = finished_verify(self.schedule.binder_key(), trunc)
        ch = trunc + _vec(_vec(binder, 1), 2)
        self.transcript += ch
        return ch

    @_normalized
    def feed_initial(self, data: bytes) -> None:
        for t, body, raw in self.buf.feed(data):
            if t != HS_SERVER_HELLO:
                raise TlsError(f"unexpected message {t} in initial")
            self._on_server_hello(body, raw)

    def _on_server_hello(self, sh: bytes, raw: bytes) -> None:
        off = 2 + 32
        off += 1 + sh[off]  # session id echo
        (suite,) = struct.unpack_from(">H", sh, off)
        if suite != TLS_AES_128_GCM_SHA256:
            raise TlsError("server chose unsupported suite")
        off += 3  # suite + compression
        exts = _parse_exts(sh[off:])
        ks = exts.get(EXT_KEY_SHARE)
        if ks is None:
            raise TlsError("server sent no key_share")
        grp, ln = struct.unpack_from(">HH", ks, 0)
        if grp != GROUP_X25519:
            raise TlsError("server chose unsupported group")
        server_pub = ks[4 : 4 + ln]
        if EXT_PRE_SHARED_KEY in exts:
            if self.psk is None:
                raise TlsError("server selected a psk we never offered")
            self._psk_active = True
        elif self.psk is not None:
            # server declined the offer (unknown identity): fall back
            # to certificate auth with the zero-PSK early secret
            self.schedule = KeySchedule()
        self.transcript += raw
        ecdhe = self.priv.exchange(
            X25519PublicKey.from_public_bytes(server_pub)
        )
        self.schedule.handshake(ecdhe)
        self.client_hs_secret, self.server_hs_secret = (
            self.schedule.hs_traffic(self.transcript)
        )

    @_normalized
    def feed_handshake(self, data: bytes) -> Optional[bytes]:
        """Returns the client Finished bytes once the server flight
        fully verified (send at handshake level), else None."""
        for t, body, raw in self.buf.feed(data):
            if t == HS_ENCRYPTED_EXTENSIONS:
                exts = _parse_exts(body)
                if EXT_QUIC_TP in exts:
                    self.peer_transport_params = exts[EXT_QUIC_TP]
                self.transcript += raw
            elif t == HS_CERTIFICATE:
                if self._psk_active:
                    raise TlsError("certificate in a PSK handshake")
                # self-signed dev certs: presence checked, chain trust
                # is the deployment's concern (reference: verify none
                # by default on quic listeners)
                self.transcript += raw
                self._cert_raw = raw
            elif t == HS_CERTIFICATE_VERIFY:
                (alg,) = struct.unpack_from(">H", body, 0)
                if alg != SIG_ECDSA_P256:
                    raise TlsError("unsupported CertificateVerify alg")
                # signature covers the transcript UP TO Certificate
                content = cert_verify_content(self.transcript)
                (slen,) = struct.unpack_from(">H", body, 2)
                sig = body[4 : 4 + slen]
                cert_body = self._cert_raw[4:]
                (clen,) = (int.from_bytes(cert_body[1:4], "big"),)
                der = cert_body[4 + 3 : 4 + 3 + int.from_bytes(
                    cert_body[4:7], "big"
                )]
                from cryptography.x509 import load_der_x509_certificate

                cert = load_der_x509_certificate(der)
                cert.public_key().verify(sig, content, ec.ECDSA(SHA256()))
                self.transcript += raw
            elif t == HS_FINISHED:
                want = finished_verify(self.server_hs_secret, self.transcript)
                if body != want:
                    raise TlsError("bad server Finished")
                self.transcript += raw
                self.schedule.derive_master()
                self.client_app_secret, self.server_app_secret = (
                    self.schedule.app_traffic(self.transcript)
                )
                fin = _hs_msg(
                    HS_FINISHED,
                    finished_verify(self.client_hs_secret, self.transcript),
                )
                self.transcript += fin
                self.handshake_complete = True
                return fin
            else:
                raise TlsError(f"unexpected message {t} in handshake")
        return None
