"""MQTT wire codec: incremental parser + serializer for 3.1/3.1.1/5.0.

The counterpart of the reference's emqx_frame
(apps/emqx/src/emqx_frame.erl:130-158 incremental parse state machine,
:243-255 per-type dispatch, plus the v5 property codec) — rebuilt over
bytes/memoryview. `Parser.feed()` accepts arbitrary byte chunks and
yields complete packets; `serialize()` is the inverse. Round-trip
property-tested in tests/test_frame.py (the analog of
prop_emqx_frame.erl).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from .packet import (
    MQTT_V3,
    MQTT_V4,
    MQTT_V5,
    Auth,
    Connack,
    Connect,
    Disconnect,
    Packet,
    Pingreq,
    Pingresp,
    Properties,
    Puback,
    Publish,
    Suback,
    SubOpts,
    Subscribe,
    Type,
    Unsuback,
    Unsubscribe,
    Will,
)

MAX_REMAINING_LEN = 268_435_455  # 4-byte varint max
DEFAULT_MAX_PACKET_SIZE = 1 << 20


class FrameError(Exception):
    def __init__(self, msg: str, code: int = 0x81):  # MALFORMED_PACKET
        super().__init__(msg)
        self.code = code


# --- property codec -----------------------------------------------------

_BYTE, _U16, _U32, _VARINT, _BIN, _UTF8, _PAIR = range(7)

# id -> (name, type); MQTT 5.0 §2.2.2.2
_PROPS = {
    0x01: ("payload_format_indicator", _BYTE),
    0x02: ("message_expiry_interval", _U32),
    0x03: ("content_type", _UTF8),
    0x08: ("response_topic", _UTF8),
    0x09: ("correlation_data", _BIN),
    0x0B: ("subscription_identifier", _VARINT),
    0x11: ("session_expiry_interval", _U32),
    0x12: ("assigned_client_identifier", _UTF8),
    0x13: ("server_keep_alive", _U16),
    0x15: ("authentication_method", _UTF8),
    0x16: ("authentication_data", _BIN),
    0x17: ("request_problem_information", _BYTE),
    0x18: ("will_delay_interval", _U32),
    0x19: ("request_response_information", _BYTE),
    0x1A: ("response_information", _UTF8),
    0x1C: ("server_reference", _UTF8),
    0x1F: ("reason_string", _UTF8),
    0x21: ("receive_maximum", _U16),
    0x22: ("topic_alias_maximum", _U16),
    0x23: ("topic_alias", _U16),
    0x24: ("maximum_qos", _BYTE),
    0x25: ("retain_available", _BYTE),
    0x26: ("user_property", _PAIR),
    0x27: ("maximum_packet_size", _U32),
    0x28: ("wildcard_subscription_available", _BYTE),
    0x29: ("subscription_identifier_available", _BYTE),
    0x2A: ("shared_subscription_available", _BYTE),
}
_PROP_IDS = {name: (pid, typ) for pid, (name, typ) in _PROPS.items()}


class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: memoryview, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def remaining(self) -> int:
        return self.end - self.pos

    def need(self, n: int) -> None:
        if self.end - self.pos < n:
            raise FrameError("truncated packet")

    def u8(self) -> int:
        self.need(1)
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        self.need(2)
        v = (self.buf[self.pos] << 8) | self.buf[self.pos + 1]
        self.pos += 2
        return v

    def u32(self) -> int:
        self.need(4)
        (v,) = struct.unpack_from(">I", self.buf, self.pos)
        self.pos += 4
        return v

    def varint(self) -> int:
        mult, val = 1, 0
        for _ in range(4):
            b = self.u8()
            val += (b & 0x7F) * mult
            if not b & 0x80:
                return val
            mult <<= 7
        raise FrameError("varint too long")

    def bin(self) -> bytes:
        n = self.u16()
        self.need(n)
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v

    def utf8(self) -> str:
        raw = self.bin()
        try:
            s = raw.decode("utf-8")
        except UnicodeDecodeError:
            raise FrameError("invalid UTF-8 string")
        if "\x00" in s:
            raise FrameError("NUL in UTF-8 string")
        return s

    def rest(self) -> bytes:
        v = bytes(self.buf[self.pos : self.end])
        self.pos = self.end
        return v


def _read_props(r: _Reader) -> Properties:
    n = r.varint()
    sub = _Reader(r.buf, r.pos, r.pos + n)
    r.need(n)
    r.pos += n
    props: Properties = {}
    while sub.remaining() > 0:
        pid = sub.varint()
        spec = _PROPS.get(pid)
        if spec is None:
            raise FrameError(f"unknown property id {pid}")
        name, typ = spec
        if typ == _BYTE:
            val = sub.u8()
        elif typ == _U16:
            val = sub.u16()
        elif typ == _U32:
            val = sub.u32()
        elif typ == _VARINT:
            val = sub.varint()
        elif typ == _BIN:
            val = sub.bin()
        elif typ == _UTF8:
            val = sub.utf8()
        else:  # _PAIR
            val = (sub.utf8(), sub.utf8())
        if name == "user_property":
            props.setdefault("user_property", []).append(val)
        elif name == "subscription_identifier" and name in props:
            cur = props[name]
            props[name] = (cur if isinstance(cur, list) else [cur]) + [val]
        elif name in props:
            raise FrameError(f"duplicate property {name}", 0x82)
        else:
            props[name] = val
    return props


def _varint_bytes(n: int) -> bytes:
    if n < 0 or n > MAX_REMAINING_LEN:
        raise FrameError("varint out of range")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _utf8_bytes(s: str) -> bytes:
    raw = s.encode("utf-8")
    if len(raw) > 0xFFFF:
        raise FrameError("string too long")
    return struct.pack(">H", len(raw)) + raw


def _bin_bytes(b: bytes) -> bytes:
    if len(b) > 0xFFFF:
        raise FrameError("binary too long")
    return struct.pack(">H", len(b)) + b


def _props_bytes(props: Optional[Properties]) -> bytes:
    body = bytearray()
    for name, val in (props or {}).items():
        pid, typ = _PROP_IDS[name]
        vals = val if name == "user_property" or (
            name == "subscription_identifier" and isinstance(val, list)
        ) else [val]
        for v in vals:
            body += _varint_bytes(pid)
            if typ == _BYTE:
                body.append(v & 0xFF)
            elif typ == _U16:
                body += struct.pack(">H", v)
            elif typ == _U32:
                body += struct.pack(">I", v)
            elif typ == _VARINT:
                body += _varint_bytes(v)
            elif typ == _BIN:
                body += _bin_bytes(v)
            elif typ == _UTF8:
                body += _utf8_bytes(v)
            else:  # _PAIR
                body += _utf8_bytes(v[0]) + _utf8_bytes(v[1])
    return _varint_bytes(len(body)) + bytes(body)


# --- parser -------------------------------------------------------------

_PROTO_NAMES = {("MQIsdp", 3), ("MQTT", 4), ("MQTT", 5)}


class Parser:
    """Incremental MQTT stream parser (emqx_frame:parse/2 analog).

    feed(chunk) -> list of packets parsed so far. Protocol version is
    latched from the CONNECT packet so later packets decode with the
    right property rules; pass proto_ver to pre-pin (e.g. server side
    of a takeover)."""

    def __init__(
        self,
        max_packet_size: int = DEFAULT_MAX_PACKET_SIZE,
        proto_ver: Optional[int] = None,
    ):
        self.max_packet_size = max_packet_size
        self.proto_ver = proto_ver
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Packet]:
        self._buf += data
        out = []
        while True:
            pkt, consumed = self._try_parse_one()
            if pkt is None:
                break
            del self._buf[:consumed]
            out.append(pkt)
        return out

    def _try_parse_one(self) -> Tuple[Optional[Packet], int]:
        buf = self._buf
        if len(buf) < 2:
            return None, 0
        # remaining-length varint
        rl, mult, i = 0, 1, 1
        while True:
            if i >= len(buf):
                return None, 0
            b = buf[i]
            rl += (b & 0x7F) * mult
            i += 1
            if not b & 0x80:
                break
            if i > 4:
                raise FrameError("remaining length varint too long")
            mult <<= 7
        if 1 + (i - 1) + rl > self.max_packet_size:
            raise FrameError("packet too large", 0x95)
        if len(buf) < i + rl:
            return None, 0
        header = buf[0]
        ptype, flags = header >> 4, header & 0x0F
        r = _Reader(memoryview(bytes(buf[i : i + rl])))
        pkt = self._parse_body(ptype, flags, r)
        if r.remaining():
            raise FrameError("trailing bytes in packet")
        return pkt, i + rl

    def _v5(self) -> bool:
        return self.proto_ver == MQTT_V5

    def _parse_body(self, ptype: int, flags: int, r: _Reader) -> Packet:
        if ptype == Type.CONNECT:
            return self._parse_connect(r)
        if ptype == Type.CONNACK:
            flags_ = r.u8()
            code = r.u8()
            props = _read_props(r) if self._v5() and r.remaining() else {}
            return Connack(bool(flags_ & 1), code, props)
        if ptype == Type.PUBLISH:
            qos = (flags >> 1) & 0x3
            if qos == 3:
                raise FrameError("invalid QoS 3")
            topic = r.utf8()
            pid = r.u16() if qos else None
            props = _read_props(r) if self._v5() else {}
            return Publish(
                topic=topic,
                payload=r.rest(),
                qos=qos,
                retain=bool(flags & 1),
                dup=bool(flags & 8),
                packet_id=pid,
                props=props,
            )
        if ptype in (Type.PUBACK, Type.PUBREC, Type.PUBREL, Type.PUBCOMP):
            if ptype == Type.PUBREL and flags != 0x2:
                raise FrameError("bad PUBREL flags")
            pid = r.u16()
            code, props = 0, {}
            if self._v5() and r.remaining():
                code = r.u8()
                if r.remaining():
                    props = _read_props(r)
            return Puback(Type(ptype), pid, code, props)
        if ptype == Type.SUBSCRIBE:
            if flags != 0x2:
                raise FrameError("bad SUBSCRIBE flags")
            pid = r.u16()
            props = _read_props(r) if self._v5() else {}
            filters = []
            while r.remaining():
                f = r.utf8()
                o = r.u8()
                opts = SubOpts(
                    qos=o & 0x3,
                    no_local=bool(o & 0x4),
                    retain_as_published=bool(o & 0x8),
                    retain_handling=(o >> 4) & 0x3,
                )
                if opts.qos == 3 or (o >> 6):
                    raise FrameError("bad subscription options")
                filters.append((f, opts))
            if not filters:
                raise FrameError("SUBSCRIBE with no filters", 0x82)
            return Subscribe(pid, filters, props)
        if ptype == Type.SUBACK:
            pid = r.u16()
            props = _read_props(r) if self._v5() else {}
            return Suback(pid, list(r.rest()), props)
        if ptype == Type.UNSUBSCRIBE:
            if flags != 0x2:
                raise FrameError("bad UNSUBSCRIBE flags")
            pid = r.u16()
            props = _read_props(r) if self._v5() else {}
            filters = []
            while r.remaining():
                filters.append(r.utf8())
            if not filters:
                raise FrameError("UNSUBSCRIBE with no filters", 0x82)
            return Unsubscribe(pid, filters, props)
        if ptype == Type.UNSUBACK:
            pid = r.u16()
            props = _read_props(r) if self._v5() else {}
            return Unsuback(pid, list(r.rest()) if self._v5() else [], props)
        if ptype == Type.PINGREQ:
            return Pingreq()
        if ptype == Type.PINGRESP:
            return Pingresp()
        if ptype == Type.DISCONNECT:
            code, props = 0, {}
            if self._v5() and r.remaining():
                code = r.u8()
                if r.remaining():
                    props = _read_props(r)
            return Disconnect(code, props)
        if ptype == Type.AUTH:
            code, props = 0, {}
            if r.remaining():
                code = r.u8()
                if r.remaining():
                    props = _read_props(r)
            return Auth(code, props)
        raise FrameError(f"unknown packet type {ptype}")

    def _parse_connect(self, r: _Reader) -> Connect:
        name = r.utf8()
        ver = r.u8()
        if (name, ver) not in _PROTO_NAMES:
            raise FrameError(f"bad protocol {name!r} v{ver}", 0x84)
        cflags = r.u8()
        if cflags & 0x01:
            raise FrameError("reserved connect flag set")
        keepalive = r.u16()
        self.proto_ver = ver
        props = _read_props(r) if ver == MQTT_V5 else {}
        client_id = r.utf8()
        will = None
        if cflags & 0x04:
            wprops = _read_props(r) if ver == MQTT_V5 else {}
            wtopic = r.utf8()
            wpayload = r.bin()
            will = Will(
                topic=wtopic,
                payload=wpayload,
                qos=(cflags >> 3) & 0x3,
                retain=bool(cflags & 0x20),
                props=wprops,
            )
            if will.qos == 3:
                raise FrameError("bad will QoS")
        elif cflags & 0x38:
            raise FrameError("will flags without will")
        username = r.utf8() if cflags & 0x80 else None
        password = r.bin() if cflags & 0x40 else None
        return Connect(
            proto_name=name,
            proto_ver=ver,
            clean_start=bool(cflags & 0x02),
            keepalive=keepalive,
            client_id=client_id,
            will=will,
            username=username,
            password=password,
            props=props,
        )


# --- serializer ---------------------------------------------------------

def _fixed(ptype: int, flags: int, body: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + _varint_bytes(len(body)) + body


def serialize(pkt: Packet, proto_ver: int = MQTT_V4) -> bytes:
    # wide-fanout fast path: a packet carrying a `_wire` dict memoizes
    # its wire form per protocol version, so one shared QoS0 PUBLISH
    # serializes once and every subscriber's sink writes cached bytes
    # (the fanout loop of emqx_broker.erl:726-760 pays serialization
    # per subscriber; we pay it per distinct protocol version)
    cache = getattr(pkt, "_wire", None)
    if cache is not None:
        hit = cache.get(proto_ver)
        if hit is not None:
            return hit
        data = _serialize_uncached(pkt, proto_ver)
        cache[proto_ver] = data
        return data
    return _serialize_uncached(pkt, proto_ver)


def _serialize_uncached(pkt: Packet, proto_ver: int = MQTT_V4) -> bytes:
    v5 = proto_ver == MQTT_V5
    if isinstance(pkt, Connect):
        v5c = pkt.proto_ver == MQTT_V5
        body = bytearray()
        body += _utf8_bytes(pkt.proto_name)
        body.append(pkt.proto_ver)
        cflags = 0
        if pkt.clean_start:
            cflags |= 0x02
        if pkt.will:
            cflags |= 0x04 | (pkt.will.qos << 3) | (0x20 if pkt.will.retain else 0)
        if pkt.username is not None:
            cflags |= 0x80
        if pkt.password is not None:
            cflags |= 0x40
        body.append(cflags)
        body += struct.pack(">H", pkt.keepalive)
        if v5c:
            body += _props_bytes(pkt.props)
        body += _utf8_bytes(pkt.client_id)
        if pkt.will:
            if v5c:
                body += _props_bytes(pkt.will.props)
            body += _utf8_bytes(pkt.will.topic)
            body += _bin_bytes(pkt.will.payload)
        if pkt.username is not None:
            body += _utf8_bytes(pkt.username)
        if pkt.password is not None:
            body += _bin_bytes(pkt.password)
        return _fixed(Type.CONNECT, 0, bytes(body))
    if isinstance(pkt, Connack):
        body = bytes([1 if pkt.session_present else 0, pkt.code])
        if v5:
            body += _props_bytes(pkt.props)
        return _fixed(Type.CONNACK, 0, body)
    if isinstance(pkt, Publish):
        flags = (0x8 if pkt.dup else 0) | (pkt.qos << 1) | (1 if pkt.retain else 0)
        body = bytearray(_utf8_bytes(pkt.topic))
        if pkt.qos:
            if pkt.packet_id is None:
                raise FrameError("qos>0 PUBLISH without packet id")
            body += struct.pack(">H", pkt.packet_id)
        if v5:
            body += _props_bytes(pkt.props)
        body += pkt.payload
        return _fixed(Type.PUBLISH, flags, bytes(body))
    if isinstance(pkt, Puback):
        flags = 0x2 if pkt.type == Type.PUBREL else 0
        body = struct.pack(">H", pkt.packet_id)
        if v5 and (pkt.code or pkt.props):
            body += bytes([pkt.code])
            if pkt.props:
                body += _props_bytes(pkt.props)
        return _fixed(pkt.type, flags, body)
    if isinstance(pkt, Subscribe):
        body = bytearray(struct.pack(">H", pkt.packet_id))
        if v5:
            body += _props_bytes(pkt.props)
        for f, o in pkt.filters:
            body += _utf8_bytes(f)
            body.append(
                o.qos
                | (0x4 if o.no_local else 0)
                | (0x8 if o.retain_as_published else 0)
                | (o.retain_handling << 4)
            )
        return _fixed(Type.SUBSCRIBE, 0x2, bytes(body))
    if isinstance(pkt, Suback):
        body = struct.pack(">H", pkt.packet_id)
        if v5:
            body += _props_bytes(pkt.props)
        body += bytes(pkt.codes)
        return _fixed(Type.SUBACK, 0, body)
    if isinstance(pkt, Unsubscribe):
        body = bytearray(struct.pack(">H", pkt.packet_id))
        if v5:
            body += _props_bytes(pkt.props)
        for f in pkt.filters:
            body += _utf8_bytes(f)
        return _fixed(Type.UNSUBSCRIBE, 0x2, bytes(body))
    if isinstance(pkt, Unsuback):
        body = struct.pack(">H", pkt.packet_id)
        if v5:
            body += _props_bytes(pkt.props)
            body += bytes(pkt.codes)
        return _fixed(Type.UNSUBACK, 0, body)
    if isinstance(pkt, Pingreq):
        return _fixed(Type.PINGREQ, 0, b"")
    if isinstance(pkt, Pingresp):
        return _fixed(Type.PINGRESP, 0, b"")
    if isinstance(pkt, Disconnect):
        if v5 and (pkt.code or pkt.props):
            body = bytes([pkt.code]) + (_props_bytes(pkt.props) if pkt.props else b"")
        else:
            body = b""
        return _fixed(Type.DISCONNECT, 0, body)
    if isinstance(pkt, Auth):
        body = b""
        if pkt.code or pkt.props:
            body = bytes([pkt.code]) + _props_bytes(pkt.props)
        return _fixed(Type.AUTH, 0, body)
    raise FrameError(f"cannot serialize {type(pkt).__name__}")
