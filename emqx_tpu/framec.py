"""MQTT frame codec seam: native wire codec with Python fallback.

The reference broker's `emqx_frame` serializer is a per-message cost
the delivery path cannot amortize — every PUBLISH fanned out to a
fresh (session, proto_ver) pair pays it once.  This seam is the wire
analog of the `jsonc` payload seam: `native/frame.cc`
(`_emqx_frame.so`) encodes/decodes exactly the hot surface — PUBLISH,
the PUBACK family (PUBACK/PUBREC/PUBREL/PUBCOMP) and SUBACK, all
property-free (v5 packets get the empty ``\\x00`` property block the
Python codec writes for ``props={}``) — and everything outside it
falls back to `broker/frame.py`, counted, never silently wrong:

  * packets with properties, or any other packet type → Python codec;
  * native raising ValueError (malformed input, out-of-range fields)
    → replayed on the Python codec so callers see the exact
    `FrameError` (message + MQTT reason code);
  * no toolchain / `EMQX_TPU_NO_FRAMEC` → Python codec for the
    process.

The ledger is process-global like jsonc's: the `emqx_frame_*`
families render on EVERY scrape with zero defaults.  Static gate:
tests/test_static_gate.py pins the native ABI and keeps this module
the only `_emqx_frame` caller; tests/test_delivery_engine.py holds
the byte-parity corpus.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess
from typing import Any, List, Optional, Tuple

from .broker import frame as _pyframe
from .broker.packet import (
    MQTT_V4,
    MQTT_V5,
    Puback,
    Publish,
    Suback,
    Type,
)

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "native")
)
_SO = os.path.join(_NATIVE_DIR, "_emqx_frame.so")

_mod = None
_tried = False

FrameError = _pyframe.FrameError


class FrameMetrics:
    """Process-global wire-codec ledger (`emqx_frame_*` families).

    Plain unlocked ints, same discipline as jsonc.JsonMetrics: the
    increments ride the per-packet hot path and stay atomic enough
    under the GIL; tests assert deltas."""

    def __init__(self) -> None:
        self.native_encodes = 0
        self.native_decodes = 0
        self.fallback_encodes = 0
        self.fallback_decodes = 0

    def snapshot(self) -> dict:
        return {
            "native_encodes": self.native_encodes,
            "native_decodes": self.native_decodes,
            "fallback_encodes": self.fallback_encodes,
            "fallback_decodes": self.fallback_decodes,
            "native_enabled": 1 if (_mod is not None and _enabled) else 0,
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        node = f'node="{node_name}"'
        enabled = 1 if (_mod is not None and _enabled) else 0
        return [
            "# TYPE emqx_frame_native_enabled gauge",
            f"emqx_frame_native_enabled{{{node}}} {enabled}",
            "# TYPE emqx_frame_native_encodes_total counter",
            f"emqx_frame_native_encodes_total{{{node}}} {self.native_encodes}",
            "# TYPE emqx_frame_native_decodes_total counter",
            f"emqx_frame_native_decodes_total{{{node}}} {self.native_decodes}",
            "# TYPE emqx_frame_fallback_encodes_total counter",
            f"emqx_frame_fallback_encodes_total{{{node}}} "
            f"{self.fallback_encodes}",
            "# TYPE emqx_frame_fallback_decodes_total counter",
            f"emqx_frame_fallback_decodes_total{{{node}}} "
            f"{self.fallback_decodes}",
        ]


FRAME_METRICS = FrameMetrics()

_enabled = True


def set_native_enabled(flag: bool) -> None:
    """Config seam for the `broker.perf.frame_native` knob."""
    global _enabled
    _enabled = bool(flag)


def native_enabled() -> bool:
    return _enabled and load() is not None


def _probe(mod) -> bool:
    """Byte-parity probe covering every native leg: a committed .so
    for a foreign ABI fails the import; a miscompiled codec fails
    here, byte-for-byte against the Python serializer."""
    pub = Publish(topic="a/b/é", payload=b"\x00\x01payload", qos=1,
                  retain=True, dup=True, packet_id=77)
    pub0 = Publish(topic="t", payload=b"x", qos=0)
    ack = Puback(Type.PUBREL, 515, 0x92)
    sub = Suback(9, [0, 1, 0x80])
    for ver in (MQTT_V4, MQTT_V5):
        v5 = 1 if ver == MQTT_V5 else 0
        if mod.encode_publish(
            pub.topic, pub.payload, pub.qos, 1, 1, pub.packet_id, v5
        ) != _pyframe._serialize_uncached(pub, ver):
            return False
        if mod.encode_publish(
            pub0.topic, pub0.payload, 0, 0, 0, None, v5
        ) != _pyframe._serialize_uncached(pub0, ver):
            return False
        if mod.encode_puback(
            int(ack.type), ack.packet_id, ack.code, v5
        ) != _pyframe._serialize_uncached(ack, ver):
            return False
        if mod.encode_suback(
            sub.packet_id, bytes(sub.codes), v5
        ) != _pyframe._serialize_uncached(sub, ver):
            return False
        # decode leg: round-trip the wire form it just produced
        wire = _pyframe._serialize_uncached(pub, ver)
        got = mod.decode(wire, v5, 1 << 20)
        if got[:7] != (3, pub.topic, pub.payload, 1, 1, 1, 77):
            return False
        if mod.decode(wire[:3], v5, 1 << 20) is not None:
            return False
    # malformed input must raise, not mis-parse
    try:
        mod.decode(b"\x36\x02\x00\x05", 0, 1 << 20)  # QoS 3
        return False
    except ValueError:
        pass
    return True


def load(build: bool = True):
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    if os.environ.get("EMQX_TPU_NO_FRAMEC"):
        _tried = True
        return None
    _tried = True
    if build:
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "_emqx_frame.so"],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            pass
    if not os.path.exists(_SO):
        return None
    try:
        loader = importlib.machinery.ExtensionFileLoader("_emqx_frame", _SO)
        spec = importlib.util.spec_from_file_location(
            "_emqx_frame", _SO, loader=loader
        )
        assert spec is not None
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        if not _probe(mod):
            return None
        _mod = mod
    except Exception:
        _mod = None
    return _mod


def _encode_uncached(pkt: Any, proto_ver: int) -> bytes:
    mod = _mod if _tried else load()
    m = FRAME_METRICS
    if mod is not None and _enabled:
        v5 = 1 if proto_ver == MQTT_V5 else 0
        try:
            if type(pkt) is Publish:
                if not pkt.props:
                    out = mod.encode_publish(
                        pkt.topic,
                        pkt.payload,
                        pkt.qos,
                        1 if pkt.retain else 0,
                        1 if pkt.dup else 0,
                        pkt.packet_id,
                        v5,
                    )
                    m.native_encodes += 1
                    return out
            elif type(pkt) is Puback:
                if not pkt.props:
                    out = mod.encode_puback(
                        int(pkt.type), pkt.packet_id, pkt.code, v5
                    )
                    m.native_encodes += 1
                    return out
            elif type(pkt) is Suback:
                if not pkt.props:
                    out = mod.encode_suback(
                        pkt.packet_id, bytes(pkt.codes), v5
                    )
                    m.native_encodes += 1
                    return out
        except (ValueError, TypeError):
            # out-of-range fields, bad payload types: replay on the
            # Python codec so callers get the exact FrameError
            pass
    m.fallback_encodes += 1
    return _pyframe._serialize_uncached(pkt, proto_ver)


def serialize(pkt: Any, proto_ver: int = MQTT_V4) -> bytes:
    """Drop-in for broker.frame.serialize with the same per-proto-ver
    `_wire` memoization (the wide-fanout shared-PUBLISH fast path)."""
    cache = getattr(pkt, "_wire", None)
    if cache is not None:
        hit = cache.get(proto_ver)
        if hit is not None:
            return hit
        data = _encode_uncached(pkt, proto_ver)
        cache[proto_ver] = data
        return data
    return _encode_uncached(pkt, proto_ver)


class Parser(_pyframe.Parser):
    """broker.frame.Parser with the native first-parse leg: complete
    property-free PUBLISH/ack/SUBACK frames decode in C; anything else
    (other packet types, v5 properties, malformed input) re-parses on
    the Python state machine, counted, with its exact FrameError."""

    def _try_parse_one(self) -> Tuple[Optional[Any], int]:
        mod = _mod if _tried else load()
        if mod is None or not _enabled:
            return super()._try_parse_one()
        m = FRAME_METRICS
        try:
            got = mod.decode(
                self._buf,
                1 if self.proto_ver == MQTT_V5 else 0,
                self.max_packet_size,
            )
        except ValueError:
            m.fallback_decodes += 1
            return super()._try_parse_one()
        if got is None:
            return None, 0
        if got is False:
            m.fallback_decodes += 1
            return super()._try_parse_one()
        m.native_decodes += 1
        ptype = got[0]
        if ptype == Type.PUBLISH:
            _, topic, payload, qos, retain, dup, pid, consumed = got
            return (
                Publish(
                    topic=topic,
                    payload=payload,
                    qos=qos,
                    retain=bool(retain),
                    dup=bool(dup),
                    packet_id=pid,
                ),
                consumed,
            )
        if ptype == Type.SUBACK:
            _, pid, codes, consumed = got
            return Suback(pid, list(codes)), consumed
        _, pid, code, consumed = got
        return Puback(Type(ptype), pid, code), consumed
