"""Mesh microscope — per-dispatch decomposition of every mesh match /
sync dispatch into first-class sub-stages (ISSUE 20).

ROADMAP item 2 demands a monotone 1→8 curve "or the measured per-leg
excuse committed", but the r15 blame (N serialized per-shard program
launches, O(N) flat all_gather buffers) was inferred from totals, not
measured per leg. This module is the instrument: launch/land clock
pairs around the begin halves plus a FetchTicket land hook decompose
the dispatch wall into

    host_encode        host-side batch pad (mesh.pad_topics)
    h2d_stage          device_put of the padded batch onto the mesh
    program_launch     host dispatch span of the jitted shard_map call
                       (the N-serialized per-shard launch overhead,
                       measured directly)
    shard_compute      device span minus the combine leg
    combine_collective all_gather + recompaction + psum, isolated by a
                       sampled combine-only probe dispatch
    d2h_transfer       residual blocking wait paid at finish
                       (FetchTicket.waited)

self-checked against the dispatch wall with the PR 17 discipline: the
stage sum must land within DECOMP_TOLERANCE of the wall, in/out-of-band
counters + a last-ratio gauge make decomposition drift a dashboard
fact instead of a silent lie.

The combine leg cannot be host-timed inside one dispatch (XLA fuses
the whole shard_map program), so it is measured *differentially*: every
`sample_n`-th dispatch, after its real measurement completes, the scope
re-dispatches a combine-only probe kernel with the same (n_sub, mh)
reduction shape (parallel.sharded_match.make_combine_probe_kernel) and
uses its device span as the collective cost; unsampled dispatches split
their device span by the last measured fraction. Probes run only at
shapes pre-warmed through `warm_probe` (warmup_escalated calls it), so
`recompiles_at_serve_total` stays 0 — an unwarmed shape skips the split
and counts `emqx_xla_mesh_scope_split_skipped_total`.

Collective-cost ledger per dispatch: gathered-buffer bytes
(dp * n_sub * mh * 2 int32 lanes — the O(N) flat gather item 2 names),
max_hits vs actual-hits occupancy (the ragged-combine headroom), and
sampled per-shard hit skew. Plus the per-chip generalization of PR 17's
ring timeline: launch→land spans credited to every serving chip
(`emqx_xla_mesh_ring_occupancy_ratio{chip}`), evacuated chips stop
accruing.

Attachment is a None-seam on ShardedDeviceTable (`table.scope`), the
same zero-cost-when-disabled contract as the chaos fault injector: with
`broker.perf.tpu_mesh_scope_enable=false` the attribute stays None and
the served path pays one attribute read per dispatch, no clocks, no
land hooks.
"""

from __future__ import annotations

from time import perf_counter
from typing import Any, Dict, List, Optional

from .kernel_telemetry import (
    CountHistogram,
    StreamingHistogram,
    render_histogram_lines,
)

# the mesh dispatch sub-stage taxonomy; every name must have a live
# recording site (tests/test_static_gate.py extends the no-orphan-stage
# leg to this tuple) and lint coverage
MESH_STAGES = (
    "host_encode",
    "h2d_stage",
    "program_launch",
    "shard_compute",
    "combine_collective",
    "d2h_transfer",
)

# PR 17 discipline: stage sum within 10% of the dispatch wall, checked
# on every ticketed dispatch
DECOMP_TOLERANCE = 0.10


class _Record:
    """One in-flight instrumented dispatch (begin → finish)."""

    __slots__ = (
        "kind", "nchips", "t0", "t_last", "launch_end", "laps", "sampled",
    )

    def __init__(self, kind: str, nchips: int, t0: float, sampled: bool):
        self.kind = kind
        self.nchips = nchips
        self.t0 = t0
        self.t_last = t0
        self.launch_end = t0
        self.laps: Dict[str, float] = {}
        self.sampled = sampled


class MeshScope:
    """Per-dispatch mesh decomposition + collective-cost ledger."""

    def __init__(self, telemetry=None, sample_n: int = 64) -> None:
        self.telemetry = telemetry
        self.sample_n = max(1, int(sample_n or 1))
        self.clock = perf_counter
        self.dispatches = 0
        self.splits_sampled = 0
        self.split_skipped = 0
        # decomposition self-check (sentinel's in/out-of-band shape)
        self.decomp_in_band = 0
        self.decomp_out_of_band = 0
        self.decomp_last_ratio = 0.0
        # (stage, nchips) -> StreamingHistogram; nchips -> wall hist
        self.stage_hist: Dict[tuple, StreamingHistogram] = {}
        self.wall_hist: Dict[int, StreamingHistogram] = {}
        # collective-cost ledger
        self.gather_bytes_total = 0
        self.gather_bytes_last = 0
        self.occupancy_hist: Dict[int, CountHistogram] = {}
        self.occupancy_last = 0.0
        self.combine_frac: Dict[int, float] = {}
        self.shard_skew: Optional[Dict[str, float]] = None
        # per-chip busy ledger: chip id -> [busy_s, last_busy_end]
        self.chips: Dict[int, List[float]] = {}
        self._track_t0: Optional[float] = None
        # probe shapes proven warm: (shard_gen, mh)
        self._probe_warm: set = set()
        self._chip_cache: tuple = (-1, ())

    # --- begin-half hooks (clock laps only — never force host values) -----

    def begin(self, kind: str, nchips: int) -> _Record:
        self.dispatches += 1
        sampled = kind != "sync" and (self.dispatches % self.sample_n == 0)
        return _Record(kind, nchips, self.clock(), sampled)

    def lap(self, rec: _Record, stage: str) -> None:
        """Fold the span since the previous mark into `stage`."""
        now = self.clock()
        rec.laps[stage] = rec.laps.get(stage, 0.0) + (now - rec.t_last)
        rec.t_last = now

    def attach(self, rec: _Record, ticket) -> None:
        """Install the land hook on a just-issued FetchTicket: the
        engine's ready() polls (every _RING_POLL_S) stamp the land
        time, giving the launch/land clock pair the device-span split
        rests on."""
        rec.launch_end = rec.t_last
        ticket.land_clock = self.clock

    # --- finish-half ------------------------------------------------------

    def _observe_stage(self, rec: _Record, stage: str, seconds: float) -> None:
        key = (stage, rec.nchips)
        h = self.stage_hist.get(key)
        if h is None:
            h = self.stage_hist[key] = StreamingHistogram()
        h.observe(max(0.0, seconds))

    def finish(
        self,
        rec: _Record,
        table,
        ticket,
        mh: int,
        hits: int,
        shard_ids=None,
    ) -> None:
        """Complete a ticketed match dispatch: split the device span,
        fold the ledger, credit the chips, self-check against the
        wall."""
        t_land = ticket.landed_at
        waited = ticket.waited
        now = self.clock()
        if t_land is None:  # hook lost (host-fallback arrays) — bound it
            t_land = now - waited
        dev_span = max(0.0, t_land - rec.launch_end)
        n_sub = int(table.mesh.devices.shape[-1])
        dp = rec.nchips // max(1, n_sub)
        # combine split: sampled dispatches re-measure via the probe;
        # the rest reuse the last measured fraction for this width
        if rec.sampled:
            probe_s = self._probe_span(table, mh)
            if probe_s is not None:
                self.splits_sampled += 1
                if dev_span > 0:
                    self.combine_frac[rec.nchips] = max(
                        0.0, min(1.0, probe_s / dev_span)
                    )
        frac = self.combine_frac.get(rec.nchips)
        combine_s = dev_span * frac if frac is not None else 0.0
        self._observe_stage(rec, "shard_compute", dev_span - combine_s)
        self._observe_stage(rec, "combine_collective", combine_s)
        self._observe_stage(rec, "d2h_transfer", waited)
        for stage, s in rec.laps.items():
            self._observe_stage(rec, stage, s)
        # --- collective ledger -------------------------------------------
        gb = dp * n_sub * mh * 2 * 4  # two int32 lanes, gathered flat
        self.gather_bytes_total += gb
        self.gather_bytes_last = gb
        occ = hits / float(max(1, dp * mh))
        self.occupancy_last = occ
        oh = self.occupancy_hist.get(rec.nchips)
        if oh is None:
            oh = self.occupancy_hist[rec.nchips] = CountHistogram()
        oh.observe(occ)
        if shard_ids is not None and len(shard_ids):
            import numpy as np

            per = np.bincount(
                np.clip(shard_ids, 0, n_sub - 1), minlength=n_sub
            )
            self.shard_skew = {
                "min": int(per.min()),
                "median": float(np.median(per)),
                "max": int(per.max()),
            }
        # --- per-chip busy (launch→land credited to serving chips) --------
        self._credit_chips(table, rec.launch_end, t_land)
        # --- wall self-check ----------------------------------------------
        wall = max(1e-9, (t_land - rec.t0) + waited)
        stage_sum = (
            sum(rec.laps.values()) + dev_span + waited
        )
        self.decomp_last_ratio = stage_sum / wall
        if abs(stage_sum - wall) <= DECOMP_TOLERANCE * wall:
            self.decomp_in_band += 1
        else:
            self.decomp_out_of_band += 1
        wh = self.wall_hist.get(rec.nchips)
        if wh is None:
            wh = self.wall_hist[rec.nchips] = StreamingHistogram()
        wh.observe(wall)

    def finish_sync(self, rec: _Record) -> None:
        """Complete a sync dispatch: lap stages only (no ticket, no
        device-span split — the donated outputs never transfer back)."""
        for stage, s in rec.laps.items():
            self._observe_stage(rec, stage, s)
        wall = max(1e-9, self.clock() - rec.t0)
        wh = self.wall_hist.get(rec.nchips)
        if wh is None:
            wh = self.wall_hist[rec.nchips] = StreamingHistogram()
        wh.observe(wall)

    # --- combine probe ----------------------------------------------------

    def warm_probe(self, table, mh: int) -> int:
        """Pre-build + pre-dispatch the combine-only probe for this
        layout/mh so serve-time sampled splits hit a warm cache
        (recompiles_at_serve_total == 0 discipline). Idempotent."""
        key = (table.shard_gen, mh)
        if key in self._probe_warm:
            return 0
        tel = self.telemetry
        if tel is not None:
            n_sub = int(table.mesh.devices.shape[-1])
            tel.record_shape("mesh_scope_probe", (n_sub, mh))
        k = table._combine_probe(mh)
        import jax.numpy as jnp

        k(jnp.int32(0))  # compile + one throwaway dispatch
        self._probe_warm.add(key)
        return 1

    def _probe_span(self, table, mh: int) -> Optional[float]:
        """Device span of one combine-only dispatch at the live
        reduction shape, or None when the shape was never warmed (the
        split is skipped, counted, and the last fraction keeps
        serving)."""
        if (table.shard_gen, mh) not in self._probe_warm:
            self.split_skipped += 1
            return None
        from ..ops import transfer as transfer_ops
        import jax.numpy as jnp

        k = table._combine_probe(mh)
        # salt defeats the relay's identical-computation memoization
        salt = jnp.int32(self.dispatches & 0x7FFFFFFF)
        out = k(salt)
        t_launched = self.clock()
        tk = transfer_ops.start_fetch(out)
        tk.land_clock = self.clock
        tk.wait()
        land = tk.landed_at if tk.landed_at is not None else self.clock()
        return max(0.0, land - t_launched)

    # --- per-chip timeline ------------------------------------------------

    def _chips_of(self, table) -> tuple:
        gen = table.shard_gen
        if self._chip_cache[0] != gen:
            ids = tuple(
                int(d.id) for d in table.mesh.devices.reshape(-1)
            )
            self._chip_cache = (gen, ids)
        return self._chip_cache[1]

    def _credit_chips(self, table, t_launch: float, t_land: float) -> None:
        if self._track_t0 is None:
            self._track_t0 = t_launch
        for cid in self._chips_of(table):
            ent = self.chips.get(cid)
            if ent is None:
                ent = self.chips[cid] = [0.0, 0.0]
            # overlapped ring slots must not double-count busy time
            start = max(t_launch, ent[1])
            if t_land > start:
                ent[0] += t_land - start
                ent[1] = t_land

    def chip_ratios(self) -> Dict[int, float]:
        out = {}
        t0 = self._track_t0
        for cid, (busy, last_end) in sorted(self.chips.items()):
            elapsed = max(1e-9, last_end - (t0 if t0 is not None else last_end))
            out[cid] = min(1.0, busy / elapsed) if elapsed > 1e-9 else 0.0
        return out

    # --- surfaces ---------------------------------------------------------

    def stage_wall_ratio(self, nchips: int) -> float:
        """Sum of recorded stage seconds over recorded wall seconds for
        one mesh width — the committed-artifact gate asserts >= 0.9."""
        wh = self.wall_hist.get(nchips)
        if wh is None or wh.sum <= 0:
            return 0.0
        ssum = sum(
            h.sum for (st, n), h in self.stage_hist.items() if n == nchips
        )
        return ssum / wh.sum

    def status(self) -> Dict[str, Any]:
        widths = sorted(self.wall_hist)
        total = self.decomp_in_band + self.decomp_out_of_band
        return {
            "enabled": True,
            "sample_n": self.sample_n,
            "dispatches": self.dispatches,
            "splits_sampled": self.splits_sampled,
            "split_skipped": self.split_skipped,
            "decomp": {
                "tolerance": DECOMP_TOLERANCE,
                "in_band": self.decomp_in_band,
                "out_of_band": self.decomp_out_of_band,
                "in_band_ratio": (
                    self.decomp_in_band / total if total else 1.0
                ),
                "last_ratio": round(self.decomp_last_ratio, 4),
            },
            "stages": {
                str(n): {
                    st: self.stage_hist[(st, n)].snapshot()
                    for st in MESH_STAGES
                    if (st, n) in self.stage_hist
                }
                for n in widths
            },
            "wall": {
                str(n): self.wall_hist[n].snapshot() for n in widths
            },
            "stage_wall_ratio": {
                str(n): round(self.stage_wall_ratio(n), 4) for n in widths
            },
            "collective": {
                "gather_bytes_total": self.gather_bytes_total,
                "gather_bytes_last": self.gather_bytes_last,
                "occupancy_last": round(self.occupancy_last, 6),
                "occupancy": {
                    str(n): h.snapshot()
                    for n, h in sorted(self.occupancy_hist.items())
                },
                "combine_frac": {
                    str(n): round(f, 4)
                    for n, f in sorted(self.combine_frac.items())
                },
            },
            "shard_skew": self.shard_skew,
            "chips": {
                str(c): round(r, 4) for c, r in self.chip_ratios().items()
            },
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        """emqx_xla_mesh_* scope families. Labeled histograms render
        here (the collector has no labeled-histogram surface), same
        pattern as the sentinel's stage exposition."""
        node = f'node="{node_name}"'
        lines: List[str] = []
        if self.stage_hist:
            fam = "emqx_xla_mesh_stage_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for (st, n) in sorted(self.stage_hist):
                render_histogram_lines(
                    lines, fam,
                    f'{node},nchips="{n}",stage="{st}"',
                    self.stage_hist[(st, n)], emit_type=False,
                )
        if self.wall_hist:
            fam = "emqx_xla_mesh_dispatch_wall_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for n in sorted(self.wall_hist):
                render_histogram_lines(
                    lines, fam, f'{node},nchips="{n}"',
                    self.wall_hist[n], emit_type=False,
                )
        if self.occupancy_hist:
            fam = "emqx_xla_mesh_combine_occupancy"
            lines.append(f"# TYPE {fam} histogram")
            for n in sorted(self.occupancy_hist):
                render_histogram_lines(
                    lines, fam, f'{node},nchips="{n}"',
                    self.occupancy_hist[n], emit_type=False,
                )
        for fam, val in (
            ("emqx_xla_mesh_decomp_in_band_total", self.decomp_in_band),
            ("emqx_xla_mesh_decomp_out_of_band_total",
             self.decomp_out_of_band),
            ("emqx_xla_mesh_collective_gather_bytes_total",
             self.gather_bytes_total),
            ("emqx_xla_mesh_scope_samples_total", self.splits_sampled),
            ("emqx_xla_mesh_scope_split_skipped_total", self.split_skipped),
        ):
            lines.append(f"# TYPE {fam} counter")
            lines.append(f"{fam}{{{node}}} {val}")
        fam = "emqx_xla_mesh_decomp_last_ratio"
        lines.append(f"# TYPE {fam} gauge")
        lines.append(f"{fam}{{{node}}} {round(self.decomp_last_ratio, 6)}")
        if self.shard_skew is not None:
            fam = "emqx_xla_mesh_shard_skew_hits"
            lines.append(f"# TYPE {fam} gauge")
            for stat in ("min", "median", "max"):
                lines.append(
                    f'{fam}{{{node},stat="{stat}"}} '
                    f"{self.shard_skew[stat]}"
                )
        ratios = self.chip_ratios()
        if ratios:
            fam = "emqx_xla_mesh_ring_occupancy_ratio"
            lines.append(f"# TYPE {fam} gauge")
            for cid, r in ratios.items():
                lines.append(
                    f'{fam}{{{node},chip="{cid}"}} {round(r, 6)}'
                )
        return lines
