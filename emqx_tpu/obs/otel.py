"""External tracing seam + OpenTelemetry exporter.

Reference: apps/emqx/src/emqx_external_trace.erl (provider behaviour
whose callbacks wrap the broker's route/forward/dispatch call sites,
:29-123) registered by apps/emqx_opentelemetry/src/emqx_otel_trace.erl.
Here the seam is `broker.tracer` — None costs one attribute check on
the hot path; a registered tracer gets hierarchical spans:

    mqtt.publish (root, per inbound message)
      ├── broker.route     (match_routes: filters matched)
      ├── broker.dispatch  (local fanout: deliveries)
      └── broker.forward   (per remote node, cluster leg)

OtelTracer batches finished spans and exports OTLP/HTTP JSON
(opentelemetry-proto trace service shape) to a collector endpoint; a
drop counter surfaces exporter backpressure instead of unbounded
buffering. Trace ids derive from the message id so one message's
spans correlate across nodes (the reference propagates tracecontext
the same way, emqx_otel_trace.erl)."""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import time
import urllib.request
from typing import Any, Dict, List, Optional

log = logging.getLogger("emqx_tpu.obs.otel")

MAX_BUFFER = 4096


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attrs",
    )

    def __init__(self, name: str, trace_id: str, parent_id: str = ""):
        self.name = name
        self.trace_id = trace_id
        self.span_id = secrets.token_hex(8)
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attrs: Dict[str, Any] = {}

    def set(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def end(self) -> None:
        self.end_ns = time.time_ns()


class Tracer:
    """Provider behaviour: subclasses receive finished spans."""

    def start_span(self, name: str, trace_id: str, parent: Optional[Span]) -> Span:
        return Span(name, trace_id, parent.span_id if parent else "")

    def finish(self, span: Span) -> None:
        raise NotImplementedError


def trace_id_of(msg) -> str:
    """Message id -> 16-byte hex trace id (stable across nodes)."""
    h = getattr(msg, "id", "") or secrets.token_hex(8)
    return trace_id_of_str(str(h))


def trace_id_of_str(h: str) -> str:
    """Raw message id -> trace id (the flight recorder stores ids on
    its hot path and derives trace ids only at read/export time)."""
    import hashlib

    return hashlib.md5(h.encode()).hexdigest()


class OtelTracer(Tracer):
    """Batches spans; a background task posts OTLP/HTTP JSON."""

    def __init__(
        self,
        endpoint: str = "http://127.0.0.1:4318/v1/traces",
        service_name: str = "emqx_tpu",
        flush_interval: float = 2.0,
        timeout: float = 5.0,
    ):
        self.endpoint = endpoint
        self.service_name = service_name
        self.flush_interval = flush_interval
        self.timeout = timeout
        self._buf: List[Span] = []
        self.dropped = 0
        self.exported = 0
        self._task: Optional[asyncio.Task] = None

    def finish(self, span: Span) -> None:
        span.end()
        if len(self._buf) >= MAX_BUFFER:
            self.dropped += 1
            return
        self._buf.append(span)

    # --- export ----------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._flush_loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _flush_loop(self) -> None:
        # the buffer DETACHES on the event loop (where finish() runs),
        # so the executor only ever serializes a batch no writer holds;
        # swapping inside the executor raced finish() appends against
        # json serialization of the same list
        while True:
            try:
                await asyncio.sleep(self.flush_interval)
                batch = self._swap()
                await asyncio.get_running_loop().run_in_executor(
                    None, self._export, batch
                )
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001
                log.warning("otel export failed: %s", e)

    def _swap(self) -> List[Span]:
        batch = self._buf
        self._buf = []
        return batch

    def flush(self) -> int:
        """Synchronous swap+export (tests, shutdown drain)."""
        return self._export(self._swap())

    def _export(self, batch: List[Span]) -> int:
        if not batch:
            return 0
        body = json.dumps(self._otlp(batch)).encode()
        req = urllib.request.Request(
            self.endpoint, data=body,
            headers={"content-type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except Exception:
            # a failed export IS a drop: the batch is already detached
            # and will not be retried — count it so backpressure is
            # visible on the scrape (emqx_otel_spans_dropped), then
            # re-raise for the caller's logging
            self.dropped += len(batch)
            raise
        self.exported += len(batch)
        return len(batch)

    def _otlp(self, spans: List[Span]) -> dict:
        def attr(k, v):
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        return {
            "resourceSpans": [{
                "resource": {
                    "attributes": [attr("service.name", self.service_name)]
                },
                "scopeSpans": [{
                    "scope": {"name": "emqx_tpu.broker"},
                    "spans": [
                        {
                            "traceId": s.trace_id,
                            "spanId": s.span_id,
                            **(
                                {"parentSpanId": s.parent_id}
                                if s.parent_id else {}
                            ),
                            "name": s.name,
                            "kind": 1,
                            "startTimeUnixNano": str(s.start_ns),
                            "endTimeUnixNano": str(s.end_ns),
                            "attributes": [
                                attr(k, v) for k, v in s.attrs.items()
                            ],
                        }
                        for s in spans
                    ],
                }],
            }]
        }


class MemoryTracer(Tracer):
    """Test/debug sink: keeps finished spans in memory."""

    def __init__(self) -> None:
        self.spans: List[Span] = []

    def finish(self, span: Span) -> None:
        span.end()
        self.spans.append(span)
