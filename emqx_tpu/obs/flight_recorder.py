"""Flight recorder — anomaly-triggered black-box diagnostics.

PR 1's kernel telemetry answers "what is the dispatch p99 *now*";
this module answers the question every production incident actually
asks: "what were the last N events before it went wrong". The design
is the black-box recorder of serious serving stacks (and the moral
analog of the reference's sys_mon/busy-port event log plus the
emqx_mgmt trace download): an always-on preallocated ring of
structured events fed by cheap taps, a trigger engine of declarative
anomaly rules, and a bounded rotated snapshot directory the frozen
ring dumps into when a rule fires.

Event sources (each a None-seam costing one attribute read when the
recorder is off, same contract as `broker.tracer`):

  * broker hookpoints — `Hooks.observer` times every non-empty
    run/run_fold chain per hookpoint and reports here; durations
    accumulate into per-hookpoint StreamingHistograms exported as
    `emqx_hook_duration_seconds`, and each run lands in the ring with
    the message's trace id (obs/otel.trace_id_of) so one publish
    correlates across otel spans, hook samples, and ring events;
  * the device match path — KernelTelemetry.record_dispatch forwards
    each leg sample as an `xla.<leg>` event (hash/dense/fallback/
    encode/unpack/sync: the SAME stage names as the PR-1 histograms
    and spans), for both DeviceTable and ShardedDeviceTable since both
    report through the one collector seam;
  * bridge retry/fallback paths — bridges/resource.py emits
    bridge.retry / bridge.failed / bridge.queue_drop / bridge.reconnect
    through the module-global seam (`set_global`/`emit`);
  * alarm transitions — an Alarms listener records activate/deactivate
    and fires the `alarm` trigger rule immediately.

Trigger rules are declarative (name, check, cooldown): dispatch p99
over threshold in a sliding window, recompile-count delta (shape
churn), cuckoo slot load factor, bridge fallback burst, slow-subs
breach, alarm raised. A firing rule freezes the ring (writers drop,
counted), persists a snapshot bundle — ring events + kernel-telemetry
dump + hook-duration histograms + monitor series tail + slow-subs
top-k + active alarms + a config/topology fingerprint — then thaws.
Per-rule cooldowns stop a storm from snapshot-spamming; the store
rotates oldest-first above `max_snapshots` so the directory is
bounded no matter what.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from .kernel_telemetry import StreamingHistogram, render_histogram_lines

log = logging.getLogger("emqx_tpu.obs.flight_recorder")

DEFAULT_CAPACITY = 2048

# device legs whose samples feed the sliding-window p99 rule (the
# "match p99" legs of kernel_telemetry.dispatch_percentile)
_DISPATCH_KINDS = ("xla.hash", "xla.dense", "xla.fallback")

# hookpoints NOT timed: these fire once per DELIVERY, so even a
# ~100ns observer probe would dominate the wide-fanout hot loop and
# bust the <2% enabled-path budget; per-delivery latency already has
# its own surface (obs/slow_subs)
UNTIMED_HOOKPOINTS = frozenset(
    {"message.delivered", "message.acked", "message.puback"}
)


class FlightRecorder:
    """Preallocated ring of (ns timestamp, kind, trace_id, attrs)
    events. `record` is the always-on hot-path cost: one time_ns, one
    tuple, two integer ops — no allocation beyond the event itself.
    Freezing makes the ring read-only so a snapshot captures the
    moments *before* the anomaly, not the dump traffic after it."""

    __slots__ = (
        "capacity", "_ring", "_pos", "frozen",
        "events_total", "dropped_while_frozen",
    )

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._ring: List[Optional[tuple]] = [None] * capacity
        self._pos = 0
        self.frozen = False
        self.events_total = 0
        self.dropped_while_frozen = 0

    def record(
        self, kind: str, trace_id: str = "", attrs: Optional[Dict] = None
    ) -> None:
        if self.frozen:
            self.dropped_while_frozen += 1
            return
        pos = self._pos
        self._ring[pos] = (time.time_ns(), kind, trace_id, attrs)
        self._pos = 0 if pos + 1 == self.capacity else pos + 1
        self.events_total += 1

    def freeze(self) -> None:
        self.frozen = True

    def unfreeze(self) -> None:
        self.frozen = False

    def events(self, limit: Optional[int] = None) -> List[tuple]:
        """Raw events, oldest first (bounded by `limit` newest)."""
        ring, pos = self._ring, self._pos
        out = [e for e in ring[pos:] if e is not None]
        out.extend(e for e in ring[:pos] if e is not None)
        if limit is not None and limit < len(out):
            out = out[-limit:]
        return out

    def iter_newest(self, limit: int):
        """Yield up to `limit` events newest-first WITHOUT building the
        full ring list — the trigger rules' poll-cadence scan."""
        ring, pos, cap = self._ring, self._pos, self.capacity
        for k in range(1, min(limit, cap) + 1):
            e = ring[pos - k]  # negative index wraps, matching the ring
            if e is None:
                return
            yield e

    def recent(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """JSON-able view, oldest first. Hook events are stored in
        their cheap hot-path shape (`hook:<point>` kind, raw message
        id, bare seconds float) and normalized — including the id →
        trace-id derivation the hot path deferred — here."""
        from .otel import trace_id_of_str

        out = []
        for ts, kind, tid, attrs in self.events(limit):
            if kind.startswith("hook:"):
                out.append({
                    "ts_ns": ts,
                    "kind": "hook",
                    "trace_id": trace_id_of_str(tid) if tid else "",
                    "attrs": {"hook": kind[5:], "ms": round(attrs * 1e3, 6)},
                })
            else:
                out.append(
                    {"ts_ns": ts, "kind": kind, "trace_id": tid,
                     "attrs": attrs}
                )
        return out


class TriggerRule:
    """One declarative anomaly rule. `check(control)` returns a
    details dict when the anomaly holds (→ snapshot) or None. The
    per-rule cooldown is enforced by the control, so a sustained
    breach yields one bundle per cooldown window, not per poll."""

    __slots__ = ("name", "check", "cooldown")

    def __init__(
        self,
        name: str,
        check: Callable[["FlightControl"], Optional[Dict]],
        cooldown: float = 30.0,
    ):
        self.name = name
        self.check = check
        self.cooldown = cooldown


def default_rules(
    p99_ms: float = 5.0,
    p99_window_s: float = 60.0,
    p99_min_samples: int = 8,
    recompile_delta: int = 8,
    load_factor: float = 0.85,
    fallback_burst: int = 10,
    burst_window_s: float = 60.0,
    slow_subs_n: int = 1,
    cooldown: float = 30.0,
    cache_collapse_ratio: float = 0.5,
    cache_min_lookups: int = 64,
    cache_cooldown: float = 60.0,
    fanout_rebuild_rate: int = 64,
    fanout_cooldown: float = 60.0,
    breaker_cooldown: float = 60.0,
) -> List[TriggerRule]:
    """The stock rule set; every threshold is a constructor knob so
    config/tests can tighten or disable individual rules."""

    def dispatch_p99(ctl: "FlightControl") -> Optional[Dict]:
        samples = ctl.recent_dispatch_samples(p99_window_s)
        if len(samples) < p99_min_samples:
            return None
        samples.sort()
        p99 = samples[min(len(samples) - 1, int(0.99 * len(samples)))]
        if p99 * 1e3 > p99_ms:
            return {
                "p99_ms": round(p99 * 1e3, 4),
                "threshold_ms": p99_ms,
                "samples": len(samples),
            }
        return None

    # recompile-count delta is stateful: compare against the value at
    # the previous poll, so the rule sees churn RATE, not lifetime sum
    recompile_state = {"last": None}

    def recompile_storm(ctl: "FlightControl") -> Optional[Dict]:
        tel = ctl.telemetry
        if tel is None:
            return None
        cur = tel.counters.get("recompiles_total", 0)
        last, recompile_state["last"] = recompile_state["last"], cur
        if last is not None and cur - last >= recompile_delta:
            return {"recompiles_delta": cur - last, "total": cur}
        return None

    def cuckoo_load(ctl: "FlightControl") -> Optional[Dict]:
        tel = ctl.telemetry
        if tel is None:
            return None
        lf = tel.gauges.get("slot_load_factor", 0.0)
        if lf > load_factor:
            return {"slot_load_factor": lf, "threshold": load_factor}
        return None

    def bridge_burst(ctl: "FlightControl") -> Optional[Dict]:
        cutoff = time.time_ns() - int(burst_window_s * 1e9)
        n = 0
        for ts, kind, _tid, _attrs in ctl.recorder.iter_newest(256):
            if ts < cutoff:
                break
            if kind.startswith("bridge."):
                n += 1
        if n >= fallback_burst:
            return {"bridge_events": n, "window_s": burst_window_s}
        return None

    # match-cache hit-ratio collapse is delta-based like the recompile
    # rule: compare hit/miss counters against the previous poll so the
    # rule sees the ratio of THIS window — a route-churn storm that
    # suddenly orphans the hot set fires it even when the lifetime
    # ratio still looks healthy
    cache_state = {"hits": None, "misses": None}

    def cache_hit_collapse(ctl: "FlightControl") -> Optional[Dict]:
        tel = ctl.telemetry
        if tel is None:
            return None
        hits = tel.counters.get("match_cache_hits", 0)
        misses = tel.counters.get("match_cache_misses", 0)
        ph, pm = cache_state["hits"], cache_state["misses"]
        cache_state["hits"], cache_state["misses"] = hits, misses
        if ph is None:
            return None
        dh, dm = hits - ph, misses - pm
        n = dh + dm
        if n < cache_min_lookups:
            return None
        ratio = dh / n
        if ratio < cache_collapse_ratio:
            return {
                "hit_ratio": round(ratio, 4),
                "lookups": n,
                "threshold": cache_collapse_ratio,
            }
        return None

    # fanout-plan rebuild storm: delta-based like the cache-collapse
    # rule — a churn wave that keeps re-staling plans (misses + stale
    # discards) fires on the rebuild RATE of this poll window, not the
    # lifetime sum; per-filter stamps should make this rare, so a
    # breach usually means something is thrashing one hot filter set
    fanout_state = {"last": None}

    def fanout_plan_storm(ctl: "FlightControl") -> Optional[Dict]:
        tel = ctl.telemetry
        if tel is None:
            return None
        cur = tel.counters.get("fanout_plan_misses", 0) + tel.counters.get(
            "fanout_plan_stale", 0
        )
        last, fanout_state["last"] = fanout_state["last"], cur
        if last is not None and cur - last >= fanout_rebuild_rate:
            return {
                "plan_rebuilds": cur - last,
                "threshold": fanout_rebuild_rate,
                "total": cur,
            }
        return None

    def slow_subs_breach(ctl: "FlightControl") -> Optional[Dict]:
        ss = ctl.slow_subs
        if ss is None:
            return None
        top = ss.topk()
        if len(top) >= slow_subs_n:
            return {"tracked": len(top), "worst": top[0]}
        return None

    return [
        TriggerRule("dispatch_p99", dispatch_p99, cooldown),
        TriggerRule("recompile_storm", recompile_storm, cooldown),
        TriggerRule("cuckoo_load", cuckoo_load, cooldown),
        TriggerRule("bridge_fallback_burst", bridge_burst, cooldown),
        # own (longer) cooldown: a churn storm keeps the ratio low for
        # its whole duration — one bundle per window is the record,
        # more is noise
        TriggerRule("cache_hit_collapse", cache_hit_collapse, cache_cooldown),
        # own cooldown for the same reason as cache_hit_collapse: one
        # bundle per rebuild storm is the record, more is noise
        TriggerRule("fanout_plan_storm", fanout_plan_storm, fanout_cooldown),
        TriggerRule("slow_subs_breach", slow_subs_breach, cooldown),
        # event-driven (fired by the Alarms listener, never polled);
        # registered so its cooldown is declared alongside the rest
        TriggerRule("alarm", lambda ctl: None, cooldown),
        # event-driven: the publish sentinel's shadow-oracle audit
        # fires this the moment a served result diverges from the host
        # oracle (obs/sentinel.py) — the one anomaly where the ring's
        # pre-breach events ARE the forensic record of the bad serve
        TriggerRule("audit_divergence", lambda ctl: None, cooldown),
        # event-driven: the dispatch engine fires this the moment its
        # device circuit breaker trips (broker/dispatch_engine.py) —
        # the ring then holds the exact device-leg samples and failed
        # batches that consumed the failure budget. Own (longer)
        # cooldown: an outage is one incident, a flapping device must
        # not snapshot-spam its way through the store rotation.
        TriggerRule(
            "device_breaker_trip", lambda ctl: None, breaker_cooldown
        ),
        # event-driven: the chaos scenario engine (emqx_tpu/chaos)
        # stamps every injected fault with a bundle, so the forensic
        # record of a chaos window carries the injection alongside the
        # detections it provoked — inject and detect correlate by ring
        # order, not by guesswork
        TriggerRule("chaos_fault", lambda ctl: None, cooldown),
        # event-driven: a durable-tier shard FAIL-STOPPED (failed
        # fsync / ENOSPC / EIO — ds/storage.py) — the bundle pins the
        # traffic the broker was serving when the disk went bad, which
        # is exactly what the post-incident "what did we lose?" audit
        # replays against the WAL
        TriggerRule("ds_shard_failed", lambda ctl: None, cooldown),
    ]


class SnapshotStore:
    """Bounded, rotated snapshot directory: flight-<seq>-<rule>.json
    bundles, oldest unlinked above `max_snapshots` — a trigger storm
    can grow the directory to the bound and no further."""

    def __init__(self, directory: str, max_snapshots: int = 8):
        self.directory = directory
        self.max_snapshots = max_snapshots
        self._seq = 0

    def _files(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(n for n in names if n.startswith("flight-"))

    def persist(self, rule: str, bundle: Dict[str, Any]) -> str:
        os.makedirs(self.directory, exist_ok=True)
        self._seq += 1
        safe_rule = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in rule
        )
        name = f"flight-{int(time.time() * 1000):013d}-{self._seq:04d}-{safe_rule}.json"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)  # readers never see a partial bundle
        files = self._files()
        while len(files) > self.max_snapshots:
            try:
                os.unlink(os.path.join(self.directory, files.pop(0)))
            except OSError:
                break
        return path

    def list(self) -> List[Dict[str, Any]]:
        out = []
        for name in self._files():
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append(
                {"name": name, "size": st.st_size, "mtime": st.st_mtime}
            )
        return out

    def read(self, name: str) -> Dict[str, Any]:
        if (
            "/" in name or "\\" in name or not name.startswith("flight-")
            or not name.endswith(".json")
        ):
            raise KeyError(name)
        path = os.path.join(self.directory, name)
        if not os.path.isfile(path):
            raise KeyError(name)
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)


class FlightControl:
    """Wires the ring, the trigger engine, and the snapshot store to
    the live subsystems. Sources are optional — bench runs attach only
    the kernel-telemetry collector; a booted node attaches everything."""

    def __init__(
        self,
        snapshot_dir: str,
        broker=None,
        telemetry=None,
        monitor=None,
        slow_subs=None,
        alarms=None,
        config=None,
        node_name: str = "emqx@127.0.0.1",
        capacity: int = DEFAULT_CAPACITY,
        max_snapshots: int = 8,
        eval_interval: float = 0.5,
        rules: Optional[List[TriggerRule]] = None,
    ):
        self.recorder = FlightRecorder(capacity)
        self.store = SnapshotStore(snapshot_dir, max_snapshots)
        self.broker = broker
        self.telemetry = telemetry
        self.monitor = monitor
        self.slow_subs = slow_subs
        self.alarms = alarms
        self.config = config
        self.node_name = node_name
        self.eval_interval = eval_interval
        self.rules = default_rules() if rules is None else rules
        self.hook_hist: Dict[str, StreamingHistogram] = {}
        # optional sampling profiler (obs/profiler.py): a snapshot
        # auto-arms it for profile_arm_s so every anomaly bundle ships
        # with the stacks that caused it, and the bundle attaches the
        # profiler's stage-bucketed top stacks
        self.profiler = None
        self.profile_arm_s = 10.0
        self.snapshots_total = 0
        self.triggers_total: Dict[str, int] = {}
        self._last_fired: Dict[str, float] = {}
        self._next_eval = 0.0
        self._installed = False

    # --- wiring -----------------------------------------------------------

    def install(self) -> None:
        """Attach every available seam. Idempotent."""
        if self._installed:
            return
        self._installed = True
        if self.broker is not None:
            from ..broker.hooks import HOOKPOINTS

            observers = self.broker.hooks.observers
            for point in HOOKPOINTS:
                if point not in UNTIMED_HOOKPOINTS:
                    observers[point] = self.on_hook
            if self.telemetry is None:
                self.telemetry = getattr(
                    self.broker.router, "telemetry", None
                )
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.flight = self.recorder
        if self.alarms is not None:
            self.alarms.listeners.append(self.on_alarm)
        set_global(self.recorder)

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if self.broker is not None:
            observers = self.broker.hooks.observers
            for point in [
                p for p, cb in observers.items() if cb == self.on_hook
            ]:
                del observers[point]
        tel = self.telemetry
        if tel is not None and getattr(tel, "flight", None) is self.recorder:
            tel.flight = None
        if self.alarms is not None and self.on_alarm in self.alarms.listeners:
            self.alarms.listeners.remove(self.on_alarm)
        if _GLOBAL is self.recorder:
            set_global(None)

    # --- taps -------------------------------------------------------------

    def on_hook(self, name: str, seconds: float, subject) -> None:
        """Hooks.observer sink: per-hookpoint duration histogram + a
        ring event. The hot path stores the RAW message id and bare
        seconds — recent() derives the trace id (the correlation key
        that makes otel spans, hook samples, and ring events one
        chain) and the display shape at read time, keeping this tap to
        a histogram bisect + one tuple."""
        h = self.hook_hist.get(name)
        if h is None:
            h = self.hook_hist[name] = StreamingHistogram()
        h.observe(seconds)
        mid = getattr(subject, "id", None) if subject is not None else None
        self.recorder.record("hook:" + name, mid or "", seconds)
        self.poll()

    def on_alarm(self, kind: str, rec: Dict[str, Any]) -> None:
        """Alarms listener: record the transition; an activation IS an
        anomaly, so it triggers immediately (through the rule cooldown
        rather than the poll loop)."""
        self.recorder.record(
            f"alarm.{kind}", "", {"name": rec.get("name", "")}
        )
        if kind == "activate":
            self.maybe_trigger(
                "alarm", {"name": rec.get("name", ""), "message": rec.get("message", "")}
            )

    def recent_dispatch_samples(
        self, window_s: float, scan_limit: int = 512
    ) -> List[float]:
        """Device-leg latency samples (seconds) within the sliding
        window — the data the dispatch_p99 rule evaluates. Bounded by
        `scan_limit` newest events and only walked at poll cadence."""
        cutoff = time.time_ns() - int(window_s * 1e9)
        out: List[float] = []
        for ts, kind, _tid, attrs in self.recorder.iter_newest(scan_limit):
            if ts < cutoff:
                break
            if kind in _DISPATCH_KINDS and attrs is not None:
                s = attrs.get("s")
                if s is not None:
                    out.append(s)
        return out

    # --- trigger engine ---------------------------------------------------

    def poll(self) -> None:
        """Cheap per-event entry: a time read and one compare until
        the eval interval elapses, then one pass over the rules."""
        now = time.monotonic()
        if now < self._next_eval:
            return
        self._next_eval = now + self.eval_interval
        self.evaluate()

    def evaluate(self) -> List[str]:
        """Run every rule once; returns the snapshot paths written."""
        if self.recorder.frozen:
            return []
        paths = []
        for rule in self.rules:
            if self._cooling(rule.name, rule.cooldown):
                continue
            try:
                details = rule.check(self)
            except Exception:
                log.exception("flight rule %s check failed", rule.name)
                continue
            if details:
                p = self._fire(rule.name, details)
                if p:
                    paths.append(p)
        return paths

    def _cooling(self, name: str, cooldown: float) -> bool:
        last = self._last_fired.get(name)
        return last is not None and time.monotonic() - last < cooldown

    def maybe_trigger(self, name: str, details: Dict) -> Optional[str]:
        """Event-driven trigger path (alarms): same cooldown contract
        as polled rules."""
        cooldown = next(
            (r.cooldown for r in self.rules if r.name == name), 30.0
        )
        if self._cooling(name, cooldown):
            return None
        return self._fire(name, details)

    def _fire(self, name: str, details: Dict) -> Optional[str]:
        self._last_fired[name] = time.monotonic()
        self.triggers_total[name] = self.triggers_total.get(name, 0) + 1
        try:
            path = self.snapshot(reason=name, details=details)
        except Exception:
            log.exception("flight snapshot for rule %s failed", name)
            return None
        log.warning(
            "flight recorder triggered by %s (%s) -> %s", name, details, path
        )
        return path

    # --- snapshot bundles -------------------------------------------------

    def fingerprint(self) -> Dict[str, Any]:
        """Config/topology fingerprint: enough to tell two bundles
        apart ("same node, same table shape, different config?")
        without shipping the whole config."""
        fp: Dict[str, Any] = {"node": self.node_name}
        if self.broker is not None:
            fp["router"] = self.broker.router.stats()
            fp["sessions"] = len(self.broker.sessions)
            fp["subscriptions"] = len(self.broker.suboptions)
        if self.config is not None:
            try:
                blob = json.dumps(
                    self.config.to_dict(), sort_keys=True, default=str
                )
                fp["config_sha256"] = hashlib.sha256(
                    blob.encode()
                ).hexdigest()
            except Exception:
                fp["config_sha256"] = None
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            fp["shape_buckets"] = tel.shape_buckets()
        return fp

    def bundle(
        self, reason: str, details: Optional[Dict] = None
    ) -> Dict[str, Any]:
        tel = self.telemetry
        return {
            "reason": reason,
            "details": details or {},
            "captured_at": time.time(),
            "fingerprint": self.fingerprint(),
            "ring": {
                "capacity": self.recorder.capacity,
                "events_total": self.recorder.events_total,
                "dropped_while_frozen": self.recorder.dropped_while_frozen,
            },
            "events": self.recorder.recent(),
            "hook_durations": {
                name: h.snapshot()
                for name, h in sorted(self.hook_hist.items())
            },
            "kernel_telemetry": (
                tel.snapshot()
                if tel is not None and getattr(tel, "enabled", False)
                else None
            ),
            "monitor_tail": (
                self.monitor.window(64) if self.monitor is not None else []
            ),
            "slow_subs": (
                self.slow_subs.topk() if self.slow_subs is not None else []
            ),
            "alarms": (
                self.alarms.get_alarms("activated")
                if self.alarms is not None
                else []
            ),
            "profile": (
                self.profiler.snapshot()
                if self.profiler is not None
                else None
            ),
        }

    def snapshot(
        self, reason: str = "manual", details: Optional[Dict] = None
    ) -> str:
        """Freeze, bundle, persist, thaw. The freeze keeps concurrent
        writers (hook taps on other coroutines, bridge pumps) from
        rotating the pre-anomaly events out from under the dump."""
        if self.profiler is not None:
            # arm the sampler for the post-anomaly window: this bundle
            # carries whatever stacks were already aggregated; the NEXT
            # bundle (or GET /api/v5/xla/profile) sees the anomaly's
            # aftermath sampled at full rate
            try:
                self.profiler.arm_for(self.profile_arm_s)
            except Exception:
                log.exception("profiler auto-arm failed")
        self.recorder.freeze()
        try:
            path = self.store.persist(reason, self.bundle(reason, details))
        finally:
            self.recorder.unfreeze()
        self.snapshots_total += 1
        self.recorder.record("flight.snapshot", "", {"reason": reason})
        return path

    # --- export surfaces --------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """JSON status for GET /api/v5/xla/flight + the ctl command."""
        return {
            "enabled": True,
            "frozen": self.recorder.frozen,
            "capacity": self.recorder.capacity,
            "events_total": self.recorder.events_total,
            "dropped_while_frozen": self.recorder.dropped_while_frozen,
            "snapshots_total": self.snapshots_total,
            "snapshot_dir": self.store.directory,
            "max_snapshots": self.store.max_snapshots,
            "triggers": dict(sorted(self.triggers_total.items())),
            "rules": [
                {"name": r.name, "cooldown_s": r.cooldown}
                for r in self.rules
            ],
            "hookpoints_timed": sorted(self.hook_hist),
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        """`emqx_flight_*` + `emqx_hook_duration_seconds` families,
        appended to the broker scrape by obs/prometheus.py."""
        node = f'node="{node_name}"'
        rec = self.recorder
        lines = [
            "# TYPE emqx_flight_events_total counter",
            f"emqx_flight_events_total{{{node}}} {rec.events_total}",
            "# TYPE emqx_flight_dropped_while_frozen_total counter",
            f"emqx_flight_dropped_while_frozen_total{{{node}}} "
            f"{rec.dropped_while_frozen}",
            "# TYPE emqx_flight_snapshots_total counter",
            f"emqx_flight_snapshots_total{{{node}}} {self.snapshots_total}",
            "# TYPE emqx_flight_frozen gauge",
            f"emqx_flight_frozen{{{node}}} {int(rec.frozen)}",
        ]
        if self.triggers_total:
            lines.append("# TYPE emqx_flight_triggers_total counter")
            for rule in sorted(self.triggers_total):
                lines.append(
                    f'emqx_flight_triggers_total{{{node},rule="{rule}"}} '
                    f"{self.triggers_total[rule]}"
                )
        if self.hook_hist:
            fam = "emqx_hook_duration_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for hook in sorted(self.hook_hist):
                render_histogram_lines(
                    lines, fam, f'{node},hook="{hook}"',
                    self.hook_hist[hook], emit_type=False,
                )
        return lines


# --- module-global seam for deep call sites (bridge pumps) ----------------
#
# BufferWorkers are constructed layers below anything that knows about
# the obs bundle; threading a recorder through every bridge constructor
# would touch dozens of signatures for one diagnostic tap. Instead the
# FlightControl installs the process-wide recorder here and call sites
# emit through it — `emit` is a no-op (one global read + branch) when
# no recorder is installed, the same disabled-path discipline as the
# None tracer seam.

_GLOBAL: Optional[FlightRecorder] = None


def set_global(recorder: Optional[FlightRecorder]) -> None:
    global _GLOBAL
    _GLOBAL = recorder


def emit(kind: str, trace_id: str = "", attrs: Optional[Dict] = None) -> None:
    fr = _GLOBAL
    if fr is not None:
        fr.record(kind, trace_id, attrs)
