"""Observability: the reference's orthogonal L9 layer (SURVEY.md §5).

  * sys        — $SYS heartbeat topics (emqx_sys.erl);
  * alarm      — activate/deactivate alarms with $SYS + hook fan-out
                 (emqx_alarm.erl);
  * slow_subs  — top-k delivery-latency tracker (apps/emqx_slow_subs);
  * trace      — client/topic/ip traces to files with text or json
                 formatting (apps/emqx/src/emqx_trace);
  * prometheus — text exposition of metrics/stats
                 (apps/emqx_prometheus).
"""

from .alarm import Alarms  # noqa: F401
from .prometheus import prometheus_text  # noqa: F401
from .slow_subs import SlowSubs  # noqa: F401
from .sys import SysHeartbeat  # noqa: F401
from .trace import TraceManager  # noqa: F401
