"""Observability: the reference's orthogonal L9 layer (SURVEY.md §5).

  * sys        — $SYS heartbeat topics (emqx_sys.erl);
  * alarm      — activate/deactivate alarms with $SYS + listener
                 fan-out (emqx_alarm.erl);
  * slow_subs  — top-k delivery-latency tracker (apps/emqx_slow_subs);
  * trace      — client/topic/ip traces to files with text or json
                 formatting (apps/emqx/src/emqx_trace);
  * prometheus — text exposition of metrics/stats
                 (apps/emqx_prometheus);
  * topic_metrics — per-topic message counters
                 (apps/emqx_modules/emqx_topic_metrics), registered
                 here so the REST surface and the Prometheus scrape
                 share one instance;
  * kernel_telemetry — device hot-path collector: dispatch-latency
                 histograms, recompile tracking, DeviceTable gauges,
                 exported as emqx_xla_* families (no reference analog:
                 this is the TPU layer the reproduction adds);
  * flight_recorder — anomaly-triggered black-box: always-on event
                 ring over broker hooks + device legs + bridges +
                 alarms, trigger rules, rotated snapshot bundles
                 (the sys_mon/trace-download diagnostics analog);
  * sentinel   — publish-path watchdog: shadow-oracle audit of served
                 device results, per-stage latency attribution, SLO
                 burn-rate alarms (obs/sentinel.py — the served-path
                 correctness leg the bench/test oracles can't cover).

`Observability` bundles the per-broker pieces and installs the hook
taps, the emqx_sup-analog wiring.
"""

from __future__ import annotations

from typing import Optional

from .alarm import AlarmError, Alarms  # noqa: F401
from .flight_recorder import (  # noqa: F401
    FlightControl,
    FlightRecorder,
    SnapshotStore,
    TriggerRule,
    default_rules,
)
from .kernel_telemetry import (  # noqa: F401
    NULL as NULL_TELEMETRY,
    KernelTelemetry,
    NullKernelTelemetry,
    StreamingHistogram,
)
from .prometheus import prometheus_text  # noqa: F401
from .sentinel import PublishSentinel, SloObjective, StageSpan  # noqa: F401
from .slow_subs import SlowSubs  # noqa: F401
from .sys import SysHeartbeat  # noqa: F401
from .topic_metrics import TopicMetrics  # noqa: F401
from .profiler import (  # noqa: F401
    DELIVERY_STAGES,
    STAGE_MARK,
    LoopLagMonitor,
    SamplingProfiler,
)
from .trace import TraceManager  # noqa: F401


class Observability:
    def __init__(
        self,
        broker,
        node_name: str = "emqx@127.0.0.1",
        trace_dir: str = "/tmp/emqx_tpu_trace",
        slow_threshold_ms: float = 500.0,
        slow_top_k: int = 10,
        flight: bool = True,
        flight_dir: Optional[str] = None,
        sentinel: bool = True,
        config=None,
    ):
        self.broker = broker
        self.node_name = node_name
        self.sys = SysHeartbeat(broker, node_name)
        self.alarms = Alarms(broker, node_name)
        self.slow_subs = SlowSubs(
            threshold_ms=slow_threshold_ms, top_k=slow_top_k
        )
        self.traces = TraceManager(trace_dir)
        # one TopicMetrics shared by REST + scrape (hooks install on
        # first register, so an unused registry costs nothing)
        self.topic_metrics = TopicMetrics(broker)
        self.slow_subs.install(broker.hooks)
        self.traces.install(broker.hooks)
        self.flight: Optional[FlightControl] = None
        if flight:
            self.flight = FlightControl(
                snapshot_dir=flight_dir or "/tmp/emqx_tpu_flight",
                broker=broker,
                slow_subs=self.slow_subs,
                alarms=self.alarms,
                config=config,
                node_name=node_name,
            )
            self.flight.install()
        # publish sentinel: attached alongside the kernel-telemetry
        # collector so every booted node audits its own served path.
        # Knobs ride broker.perf.* when a config is wired; the
        # constructor defaults serve the bare test/bench brokers.
        self.sentinel: Optional[PublishSentinel] = None
        if sentinel:
            self.sentinel = PublishSentinel(
                broker,
                sample_n=_cfg(
                    config, "broker.perf.tpu_audit_sample_n", 1024
                ),
                quarantine=_cfg(
                    config, "broker.perf.tpu_audit_quarantine", True
                ),
                alarms=self.alarms,
                flight=self.flight,
                slo_publish_ms=_cfg(
                    config, "broker.perf.tpu_slo_publish_p99_ms", 50.0
                ),
                slo_publish_target=_cfg(
                    config, "broker.perf.tpu_slo_publish_target", 0.999
                ),
                slo_audit_target=_cfg(
                    config, "broker.perf.tpu_slo_audit_target", 0.999
                ),
                slo_fast_window_s=_cfg(
                    config, "broker.perf.tpu_slo_fast_window_s", 300.0
                ),
                slo_slow_window_s=_cfg(
                    config, "broker.perf.tpu_slo_slow_window_s", 3600.0
                ),
                slo_burn_threshold=_cfg(
                    config, "broker.perf.tpu_slo_burn_threshold", 10.0
                ),
                warmup_spans=_cfg(
                    config, "broker.perf.tpu_warmup_sample_skip", 2
                ),
            )
            broker.sentinel = self.sentinel
        # delivery-path microscope (obs/profiler.py): the sampling
        # profiler is constructed whenever delivery-stage attribution
        # is on, but only RUNS continuously when tpu_profiler_enable
        # is set — otherwise it stays parked until a flight bundle
        # auto-arms it or the API/ctl starts it on demand
        self.profiler = SamplingProfiler(
            hz=_cfg(config, "broker.perf.tpu_profiler_hz", 100.0)
        )
        self.profiler_enabled = bool(
            _cfg(config, "broker.perf.tpu_profiler_enable", False)
        )
        self.loop_lag = LoopLagMonitor(
            interval_s=_cfg(
                config, "broker.perf.tpu_loop_lag_interval_ms", 100.0
            ) / 1e3
        )
        if self.flight is not None:
            self.flight.profiler = self.profiler
        if not _cfg(config, "broker.perf.tpu_delivery_stages", True):
            # delivery sub-stage attribution off: spans stop carrying
            # subs by zeroing the sentinel histograms' feed at the
            # engine seam (the spans themselves stay — publish-stage
            # attribution is a separate, older contract)
            if self.sentinel is not None:
                self.sentinel.delivery_stages_enabled = False

    def prometheus_text(self) -> str:
        return prometheus_text(self.broker, self.node_name, obs=self)

    def start(self, sys_interval: float = 30.0) -> None:
        self.sys.start(sys_interval)
        if self.profiler_enabled:
            self.profiler.start()
        # needs a running loop; boot calls start() from async context.
        # Synchronous callers (bench setup) just skip the ticker.
        self.loop_lag.start()

    def stop(self) -> None:
        self.sys.stop()
        self.loop_lag.stop()
        self.profiler.stop()
        if self.sentinel is not None and self.broker.sentinel is self.sentinel:
            self.broker.sentinel = None
        if self.flight is not None:
            self.flight.uninstall()
        self.traces.close()
        self.traces.uninstall()
        self.slow_subs.uninstall()


def _cfg(config, key: str, default):
    """Config read tolerant of absent config objects (bench/tests
    construct Observability without one)."""
    if config is None:
        return default
    try:
        v = config.get(key)
    except Exception:
        return default
    return default if v is None else v
