"""Alarm registry (apps/emqx/src/emqx_alarm.erl:1-492).

activate/deactivate named alarms; active table + bounded deactivated
history; each transition publishes `$SYS/brokers/<node>/alarms/
activate|deactivate` with a JSON body, exactly the reference's
do_actions publish leg. The 'systems.alarm' hook analog is a plain
callback list (the reference routes through emqx_hooks 'alarm.*' from
plugins; we keep it local to avoid widening the strict hookpoint set).
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

from ..broker.message import Message


class AlarmError(Exception):
    pass


class Alarms:
    def __init__(
        self,
        broker=None,
        node_name: str = "emqx@127.0.0.1",
        size_limit: int = 1000,
        validity_period: float = 86400.0,
    ):
        self.broker = broker
        self.node_name = node_name
        self.size_limit = size_limit
        self.validity_period = validity_period
        self._active: Dict[str, Dict[str, Any]] = {}
        # append-only, time-ordered (list: equal-timestamp deactivations
        # must not overwrite each other)
        self._history: List[Dict[str, Any]] = []
        self.listeners: List[Callable[[str, Dict[str, Any]], None]] = []

    # --- transitions ----------------------------------------------------

    def activate(
        self, name: str, details: Optional[Dict[str, Any]] = None, message: str = ""
    ) -> None:
        """Raise an alarm; already-active raises (emqx_alarm.erl returns
        {error, already_existed})."""
        if name in self._active:
            raise AlarmError(f"alarm already active: {name}")
        rec = {
            "name": name,
            "details": details or {},
            "message": message or name,
            "activate_at": time.time(),
        }
        self._active[name] = rec
        self._notify("activate", rec)

    def ensure(self, name: str, details=None, message: str = "") -> None:
        """activate if not already active (safe_activate). An already-
        active alarm refreshes its details/message in place — no
        re-notify, no $SYS re-publish — so long-burning alarms (SLO
        burn rates, audit divergence) read current, not stale, state."""
        rec = self._active.get(name)
        if rec is None:
            self.activate(name, details, message)
            return
        if details:
            rec["details"] = details
        if message:
            rec["message"] = message

    def deactivate(self, name: str, details=None, message: str = "") -> None:
        rec = self._active.pop(name, None)
        if rec is None:
            raise AlarmError(f"alarm not active: {name}")
        rec = dict(rec)
        rec["deactivate_at"] = time.time()
        if details:
            rec["details"] = details
        if message:
            rec["message"] = message
        self._gc()
        self._history.append(rec)
        self._notify("deactivate", rec)

    def ensure_deactivated(self, name: str) -> None:
        if name in self._active:
            self.deactivate(name)

    def delete_all_deactivated(self) -> None:
        self._history = []

    # --- views ----------------------------------------------------------

    def get_alarms(self, which: str = "all") -> List[Dict[str, Any]]:
        self._gc()
        if which == "activated":
            return list(self._active.values())
        if which == "deactivated":
            return list(self._history)
        return list(self._active.values()) + list(self._history)

    def is_active(self, name: str) -> bool:
        return name in self._active

    def fired_since(self, ts: float) -> List[str]:
        """Names of alarms whose activation landed at/after `ts`,
        whether still active or already cleared — the chaos scenario
        contract's "did the system page during this window" view."""
        names = {
            r["name"]
            for r in self._active.values()
            if r["activate_at"] >= ts
        }
        names.update(
            r["name"] for r in self._history if r["activate_at"] >= ts
        )
        return sorted(names)

    # --- internals ------------------------------------------------------

    def _gc(self) -> None:
        cutoff = time.time() - self.validity_period
        while self._history and (
            self._history[0]["deactivate_at"] < cutoff
            or len(self._history) >= self.size_limit
        ):
            self._history.pop(0)

    def _notify(self, kind: str, rec: Dict[str, Any]) -> None:
        for cb in self.listeners:
            cb(kind, rec)
        if self.broker is not None:
            topic = f"$SYS/brokers/{self.node_name}/alarms/{kind}"
            self.broker.publish(
                Message(topic=topic, payload=json.dumps(rec).encode())
            )
