"""Delivery-path microscope — continuous sampling profiler + loop-lag
ticker + the stage-mark seam the sampler attributes stacks with.

ROADMAP item 1's frontier is the Python-side delivery path: the match
kernel does 8.6M topics/s while every soak sustains 4-6k pub/s, and
until this module the entire session/fanout/ack walk hid inside the
sentinel's one opaque `queue` bucket. Three pieces make it visible
without per-call probes (the PR 2/PR 5 <=2% discipline):

  * **SamplingProfiler** — a daemon thread wakes `hz` times a second,
    walks `sys._current_frames()` for the target thread (the event
    loop's), and folds the stack into a bounded frame table. No
    tracing hooks, no per-call instrumentation: the served path pays
    NOTHING while the sampler sleeps, and one dict fold per sample
    while it runs. Stacks aggregate per delivery sub-stage (see
    STAGE_MARK below) and render as collapsed-stack flamegraph text
    (Brendan Gregg format) through GET /api/v5/xla/profile and
    `ctl profile`. A sample is counted as on-CPU when process CPU
    time advanced by at least half the sampling interval since the
    previous sample — a process-level approximation, honestly
    labeled, that separates "the loop is busy" from "the loop is
    parked in epoll".

  * **STAGE_MARK** — one module-global cell the instrumented delivery
    path stamps with the sub-stage it is entering (`dispatch_loop`,
    `session_write`, ...). The hot-path cost is a single attribute
    store per stage TRANSITION (per batch / per publish, never per
    subscriber); the sampler reads it to bucket each stack under the
    sub-stage that was live when the sample hit. The emqx analog is
    system_monitor's long_schedule attribution: the scheduler tells
    you WHERE it was when the gap happened.

  * **LoopLagMonitor** — the sentinel-stage accounting fix (ISSUE 17
    satellite): `queue` used to absorb event-loop scheduling delay
    from unrelated co-tenant tasks. A sampled ticker sleeps a fixed
    interval and records the overshoot (actual - requested) into
    `emqx_xla_loop_lag_seconds`, so co-tenant load has its own series
    instead of polluting the delivery sub-stages.

The profiler auto-arms for `arm_s` seconds whenever the flight
recorder freezes a bundle (obs/flight_recorder.py), so every anomaly
snapshot ships with the stacks that caused it.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from .kernel_telemetry import StreamingHistogram

# Delivery sub-stages (ISSUE 17): the first-class decomposition of the
# sentinel's queue+deliver wall, exported as
# emqx_xla_delivery_stage_seconds{stage=..}. Order is pipeline order:
#   submit_wait   — engine submit() -> the batch flush fires
#   coalesce      — flush start -> this publish's hook fold completed
#   plan_resolve  — fanout-plan cache probe / build / split
#   dispatch_loop — the per-subscriber fan walk minus writes/acks
#   session_write — packet serialize + sink/socket writes
#   ack_sweep     — QoS1/2 inflight bookkeeping + puback/retry sweeps
DELIVERY_STAGES = (
    "submit_wait", "coalesce", "plan_resolve", "dispatch_loop",
    "session_write", "ack_sweep",
)

# frame-table bounds: unique stacks and frames are interned; past the
# caps new stacks fold into one explicit overflow bucket so a stack
# storm cannot grow the table without bound (counted, never silent)
MAX_STACKS = 8192
MAX_DEPTH = 64

_OVERFLOW_KEY = ("<overflow>",)


class _StageMark:
    """The one-cell stage register the delivery path stamps and the
    sampler reads. A plain attribute store/read — no locks: a torn
    read can only misattribute one sample to a neighboring stage,
    which the sampling error already dominates."""

    __slots__ = ("stage",)

    def __init__(self) -> None:
        self.stage = ""


# module-global: broker/pubsub + dispatch_engine import this once and
# stamp `.stage`; the sampler thread reads it per sample
STAGE_MARK = _StageMark()


class SamplingProfiler:
    """Thread-based wall+CPU stack sampler over the event-loop thread.

    `start()` spawns one daemon thread; `stop()` joins it. While
    stopped the served path pays zero (no hooks are installed —
    ever). Aggregation: stack tuple (outermost..innermost
    "module:func:line" frames) -> [wall_samples, cpu_samples], bucketed
    under the STAGE_MARK sub-stage live at sample time ("" = outside
    the delivery path)."""

    def __init__(
        self,
        hz: float = 100.0,
        target_thread_id: Optional[int] = None,
        max_stacks: int = MAX_STACKS,
        max_depth: int = MAX_DEPTH,
    ):
        self.hz = max(1.0, min(float(hz), 1000.0))
        self.interval = 1.0 / self.hz
        # default target: the thread that constructs the profiler —
        # boot/Observability run on the event-loop thread, so the
        # sampler watches the loop unless told otherwise
        self.target_thread_id = (
            threading.get_ident()
            if target_thread_id is None
            else target_thread_id
        )
        self.max_stacks = max_stacks
        self.max_depth = max_depth
        # stage -> {stack_tuple -> [wall, cpu]}
        self.stacks: Dict[str, Dict[Tuple[str, ...], List[int]]] = {}
        self.samples_total = 0
        self.cpu_samples_total = 0
        self.overflow_total = 0
        self.missed_thread_total = 0
        self.started_at: Optional[float] = None
        self.stopped_at: Optional[float] = None
        self.arms_total = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._disarm_at: Optional[float] = None
        self._lock = threading.Lock()

    # --- lifecycle --------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> bool:
        """Idempotent; returns True when a sampler thread was spawned
        by THIS call."""
        if self.running:
            return False
        self._stop.clear()
        self._disarm_at = None
        self.started_at = time.time()
        self.stopped_at = None
        self._thread = threading.Thread(
            target=self._run, name="xla-profiler", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=2.0)
        self._thread = None
        self.stopped_at = time.time()

    def arm_for(self, seconds: float) -> None:
        """Flight-recorder auto-arm: run for `seconds` then self-stop
        (extends the window if already armed; never shortens a manual
        start)."""
        self.arms_total += 1
        until = time.monotonic() + max(0.0, seconds)
        if self.running:
            if self._disarm_at is not None and until > self._disarm_at:
                self._disarm_at = until
            return
        self.start()
        self._disarm_at = until

    def reset(self) -> None:
        with self._lock:
            self.stacks = {}
            self.samples_total = 0
            self.cpu_samples_total = 0
            self.overflow_total = 0
            self.missed_thread_total = 0

    # --- the sampler loop -------------------------------------------------

    def _run(self) -> None:
        interval = self.interval
        get_frames = sys._current_frames
        tid = self.target_thread_id
        mark = STAGE_MARK
        last_cpu = time.process_time()
        # count unique stacks across every stage bucket for the cap
        n_stacks = 0
        while not self._stop.wait(interval):
            if (
                self._disarm_at is not None
                and time.monotonic() >= self._disarm_at
            ):
                break
            frame = get_frames().get(tid)
            if frame is None:
                self.missed_thread_total += 1
                continue
            stack: List[str] = []
            depth = 0
            f: Any = frame
            while f is not None and depth < self.max_depth:
                co = f.f_code
                stack.append(
                    f"{co.co_filename.rsplit('/', 1)[-1]}:"
                    f"{co.co_name}:{f.f_lineno}"
                )
                f = f.f_back
                depth += 1
            stack.reverse()
            key = tuple(stack)
            cpu = time.process_time()
            on_cpu = (cpu - last_cpu) >= 0.5 * interval
            last_cpu = cpu
            stage = mark.stage
            with self._lock:
                bucket = self.stacks.get(stage)
                if bucket is None:
                    bucket = self.stacks[stage] = {}
                cell = bucket.get(key)
                if cell is None:
                    if n_stacks >= self.max_stacks:
                        self.overflow_total += 1
                        key = _OVERFLOW_KEY
                        cell = bucket.get(key)
                        if cell is None:
                            cell = bucket[key] = [0, 0]
                    else:
                        n_stacks += 1
                        cell = bucket[key] = [0, 0]
                cell[0] += 1
                if on_cpu:
                    cell[1] += 1
                    self.cpu_samples_total += 1
                self.samples_total += 1
        self.stopped_at = time.time()

    # --- export -----------------------------------------------------------

    def top_stacks(
        self, stage: Optional[str] = None, n: int = 10, which: str = "wall"
    ) -> List[Dict[str, Any]]:
        """Top-N stacks by sample count — per sub-stage when `stage`
        names one, over every bucket otherwise."""
        idx = 0 if which == "wall" else 1
        rows: List[Dict[str, Any]] = []
        with self._lock:
            buckets = (
                {stage: self.stacks.get(stage, {})}
                if stage is not None
                else dict(self.stacks)
            )
            for st, bucket in buckets.items():
                for key, cell in bucket.items():
                    if cell[idx]:
                        rows.append(
                            {
                                "stage": st,
                                "stack": list(key),
                                "wall_samples": cell[0],
                                "cpu_samples": cell[1],
                            }
                        )
        rows.sort(key=lambda r: -r[f"{which}_samples"])
        return rows[:n]

    def collapsed(
        self, stage: Optional[str] = None, which: str = "wall"
    ) -> str:
        """Collapsed-stack flamegraph text: `frame;frame;frame count`
        per line (flamegraph.pl / speedscope input). Stage-bucketed
        stacks are rooted under a `stage:<name>` frame so one
        flamegraph shows the sub-stage split at its base."""
        idx = 0 if which == "wall" else 1
        out: List[str] = []
        with self._lock:
            for st in sorted(self.stacks):
                if stage is not None and st != stage:
                    continue
                root = f"stage:{st or 'other'}"
                for key, cell in sorted(self.stacks[st].items()):
                    if cell[idx]:
                        out.append(
                            ";".join((root,) + key) + f" {cell[idx]}"
                        )
        return "\n".join(out)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            per_stage = {
                st or "other": sum(c[0] for c in bucket.values())
                for st, bucket in sorted(self.stacks.items())
            }
            n_stacks = sum(len(b) for b in self.stacks.values())
        other = per_stage.get("other", 0)
        total = self.samples_total
        return {
            "running": self.running,
            "hz": self.hz,
            "samples_total": self.samples_total,
            "cpu_samples_total": self.cpu_samples_total,
            # fraction of samples landing in a NAMED stage bucket: the
            # attribution contract (ISSUE 19 satellite) — `other` held
            # 1898/1910 of r17's samples before the storm-gen/launch/
            # fetch marks
            "attributed_ratio": (
                round((total - other) / total, 4) if total else 0.0
            ),
            "unique_stacks": n_stacks,
            "overflow_total": self.overflow_total,
            "missed_thread_total": self.missed_thread_total,
            "stage_samples": per_stage,
            "arms_total": self.arms_total,
            "started_at": self.started_at,
            "stopped_at": self.stopped_at,
        }

    def snapshot(self, top_n: int = 10) -> Dict[str, Any]:
        """Flight-bundle payload: status + top stacks per sub-stage
        (bounded — a bundle must stay a bundle, not a heap dump)."""
        with self._lock:
            stages = sorted(self.stacks)
        return {
            **self.status(),
            "top_stacks": {
                st or "other": self.top_stacks(stage=st, n=top_n)
                for st in stages
            },
        }

    def prometheus_lines(
        self, node_name: str = "emqx@127.0.0.1"
    ) -> List[str]:
        node = f'node="{node_name}"'
        st = self.status()
        lines = [
            "# TYPE emqx_xla_profiler_samples_total counter",
            f"emqx_xla_profiler_samples_total{{{node}}} "
            f"{st['samples_total']}",
            "# TYPE emqx_xla_profiler_cpu_samples_total counter",
            f"emqx_xla_profiler_cpu_samples_total{{{node}}} "
            f"{st['cpu_samples_total']}",
            "# TYPE emqx_xla_profiler_overflow_total counter",
            f"emqx_xla_profiler_overflow_total{{{node}}} "
            f"{st['overflow_total']}",
            "# TYPE emqx_xla_profiler_running gauge",
            f"emqx_xla_profiler_running{{{node}}} {int(st['running'])}",
            "# TYPE emqx_xla_profiler_unique_stacks gauge",
            f"emqx_xla_profiler_unique_stacks{{{node}}} "
            f"{st['unique_stacks']}",
        ]
        return lines


class LoopLagMonitor:
    """Sampled event-loop lag ticker: `asyncio.sleep(interval)` in a
    supervised task, overshoot lands in the
    `emqx_xla_loop_lag_seconds` histogram. Bounded recent-lag deque
    feeds the status/API view. Costs one timer per interval — nothing
    rides the publish path."""

    def __init__(self, interval_s: float = 0.1, max_recent: int = 64):
        self.interval_s = max(0.005, float(interval_s))
        self.hist = StreamingHistogram()
        self.recent: Deque[float] = deque(maxlen=max_recent)
        self.ticks_total = 0
        self._task: Optional[Any] = None

    @property
    def running(self) -> bool:
        t = self._task
        return t is not None and not t.done()

    def start(self) -> bool:
        """Idempotent; needs a running event loop (returns False when
        none is — callers retry from an async context)."""
        import asyncio

        if self.running:
            return False
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False
        self._task = loop.create_task(self._tick())
        self._task.add_done_callback(_swallow_cancel)
        return True

    def stop(self) -> None:
        t = self._task
        if t is not None and not t.done():
            t.cancel()
        self._task = None

    async def _tick(self) -> None:
        import asyncio

        interval = self.interval_s
        clock = time.perf_counter
        while True:
            t0 = clock()
            await asyncio.sleep(interval)
            lag = max(0.0, clock() - t0 - interval)
            self.hist.observe(lag)
            self.recent.append(lag)
            self.ticks_total += 1

    def status(self) -> Dict[str, Any]:
        return {
            "running": self.running,
            "interval_s": self.interval_s,
            "ticks_total": self.ticks_total,
            "lag": self.hist.snapshot(),
            "recent_ms": [round(v * 1e3, 4) for v in self.recent],
        }

    def prometheus_lines(
        self, node_name: str = "emqx@127.0.0.1"
    ) -> List[str]:
        from .kernel_telemetry import render_histogram_lines

        lines: List[str] = []
        render_histogram_lines(
            lines, "emqx_xla_loop_lag_seconds", f'node="{node_name}"',
            self.hist,
        )
        return lines


def _swallow_cancel(task) -> None:
    """Done-callback for the supervised ticker task: a cancel at stop
    is the expected teardown; anything else is re-raised to the loop's
    exception handler by retrieving it."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        raise exc
