"""Per-topic message counters — emqx_topic_metrics analog.

Reference: apps/emqx_modules/src/emqx_topic_metrics.erl — an explicit
registry of EXACT topic names (max 512; wildcards rejected) counting
messages.{in,out,dropped} and the per-QoS in/out splits through the
message.publish / message.delivered / message.dropped hooks. Rates are
the caller's derivative; the reference samples them the same way.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..ops import topic as topic_mod

MAX_TOPICS = 512

_COUNTERS = (
    "messages.in", "messages.out", "messages.dropped",
    "messages.qos0.in", "messages.qos0.out",
    "messages.qos1.in", "messages.qos1.out",
    "messages.qos2.in", "messages.qos2.out",
)


class TopicMetrics:
    def __init__(self, broker) -> None:
        self.broker = broker
        self._topics: Dict[str, Dict[str, int]] = {}
        self._created: Dict[str, float] = {}
        self._installed = False

    # --- registry --------------------------------------------------------

    def register(self, topic: str) -> None:
        if topic_mod.is_wildcard(topic):
            raise ValueError("topic metrics take exact topics, not filters")
        topic_mod.validate_name(topic)
        if topic in self._topics:
            raise ValueError(f"topic {topic!r} already registered")
        if len(self._topics) >= MAX_TOPICS:
            raise OverflowError(f"topic metrics limit {MAX_TOPICS} reached")
        self._topics[topic] = {c: 0 for c in _COUNTERS}
        self._created[topic] = time.time()
        self.install()

    def deregister(self, topic: str) -> bool:
        self._created.pop(topic, None)
        return self._topics.pop(topic, None) is not None

    def deregister_all(self) -> None:
        self._topics.clear()
        self._created.clear()

    def metrics(self, topic: str) -> Optional[dict]:
        c = self._topics.get(topic)
        if c is None:
            return None
        return {
            "topic": topic,
            "create_time": self._created[topic],
            "metrics": dict(c),
        }

    def list(self) -> List[dict]:
        return [self.metrics(t) for t in sorted(self._topics)]

    def reset(self, topic: Optional[str] = None) -> None:
        for t, c in self._topics.items():
            if topic is None or t == topic:
                for k in c:
                    c[k] = 0

    # --- hooks -----------------------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        self.broker.hooks.add("message.publish", self._on_publish, priority=5)
        self.broker.hooks.add("message.delivered", self._on_delivered)
        self.broker.hooks.add("message.dropped", self._on_dropped)
        self._installed = True

    def _on_publish(self, msg, acc=None):
        m = acc if acc is not None else msg
        c = self._topics.get(getattr(m, "topic", None))
        if c is not None:
            c["messages.in"] += 1
            c[f"messages.qos{min(m.qos, 2)}.in"] += 1
        return None  # fold passthrough

    def _on_delivered(self, client_id, msg):
        c = self._topics.get(msg.topic)
        if c is not None:
            c["messages.out"] += 1
            c[f"messages.qos{min(msg.qos, 2)}.out"] += 1

    def _on_dropped(self, msg, reason):
        c = self._topics.get(msg.topic)
        if c is not None:
            c["messages.dropped"] += 1
