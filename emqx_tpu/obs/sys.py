"""$SYS heartbeat topics (apps/emqx/src/emqx_sys.erl:1-421).

The reference runs two timers: a heartbeat (uptime + datetime) and an
interval tick publishing version/brokers/stats/metrics under
`$SYS/brokers/<node>/...`. Here the publisher is tickable — tests call
`tick()` directly; `start()` drives it from asyncio.

$SYS messages are retained-ish in the reference (flag sys=true); we
publish them as plain QoS0 retained=False messages from the node, and
subscribers use normal `$SYS/#` filters (which the topic algebra
already keeps out of root `+`/`#` matches).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from ..broker.message import Message

VERSION = "0.2.0"


class SysHeartbeat:
    def __init__(self, broker, node_name: str = "emqx@127.0.0.1"):
        self.broker = broker
        self.node_name = node_name
        self.started_at = time.time()
        self._task: Optional[asyncio.Task] = None
        self.heartbeat_interval = 30.0

    # --- publishing -----------------------------------------------------

    def _pub(self, suffix: str, payload) -> None:
        if isinstance(payload, (dict, list)):
            body = json.dumps(payload).encode()
        elif isinstance(payload, bytes):
            body = payload
        else:
            body = str(payload).encode()
        topic = f"$SYS/brokers/{self.node_name}/{suffix}"
        self.broker.publish(Message(topic=topic, payload=body, qos=0))

    def uptime(self) -> float:
        return time.time() - self.started_at

    def heartbeat(self) -> None:
        """The fast timer (emqx_sys.erl heartbeat: uptime + datetime)."""
        self._pub("uptime", int(self.uptime() * 1000))
        self._pub(
            "datetime", time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())
        )

    def tick(self) -> None:
        """The slow timer (emqx_sys.erl sys_interval: version, brokers,
        stats/*, metrics/*)."""
        b = self.broker
        self._pub("version", VERSION)
        self.broker.publish(
            Message(topic="$SYS/brokers", payload=self.node_name.encode())
        )
        self._pub("sysdescr", "emqx_tpu broker")
        for name, val in b.stats.all().items():
            self._pub(f"stats/{name}", val)
        for name, val in b.metrics.all().items():
            self._pub(f"metrics/{name}", val)
        self.heartbeat()

    # --- asyncio driver -------------------------------------------------

    def start(self, interval: float = 30.0) -> None:
        self.heartbeat_interval = interval
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_interval)
            self.tick()

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
