"""Kernel telemetry — first-class observability for the TPU match path.

The paper's target is a p99 batch-match latency under 1ms at 10M
filters, but until now that number only existed inside offline
bench.py runs; the obs/ layer mirrored the reference's broker-level
surfaces (emqx_prometheus, emqx_opentelemetry) and was blind to the
device hot path this reproduction exists for. PERF_NOTES.md records
two full rounds lost to exactly that blindness: the r3→r4
"regression" that bisected to relay RTT jitter, and p25 estimates
silently sitting on the epsilon clamp.

This module is the always-on collector the Router/DeviceTable hot
path reports into:

  * per-dispatch latency in fixed-bucket streaming histograms
    (p50/p99/p999 queryable at runtime), one series per leg — the
    hash-index kernel, the residual dense kernel, the host-trie
    fallback, plus the encode/unpack host stages and device sync;
  * a recompile tracker keyed on the jit-relevant static shapes of
    each kernel (batch size, max_hits, packed class count, slot-table
    size): distinct keys ARE distinct XLA cache entries, so the
    counter stays flat under steady shapes and increments exactly when
    a new shape bucket forces a retrace — batch-shape churn being the
    classic silent TPU perf killer;
  * DeviceTable gauges: HBM bytes resident, pow2 capacity vs active
    rows, cuckoo slot load factor, pending-delta queue depth, last
    sync batch size;
  * escalation/fallback counters: `_escalating_pairs` retries,
    hash-kernel overflow re-dispatches, ambiguity host fallbacks, and
    rows the pattern-class index couldn't class (residual).

Export surfaces: `prometheus_lines()` renders `emqx_xla_*` families
(histograms with `_bucket`/`_sum`/`_count` + `le` labels) appended to
the broker scrape; `snapshot()` is the JSON body of
GET /api/v5/xla/telemetry; an optional `tracer` (obs/otel.py Tracer)
receives encode→dispatch→unpack spans per batch.

`NullKernelTelemetry` keeps the hot path branch-free when disabled:
every record method is a bound no-op and `clock` returns 0.0 without a
syscall, so instrumented code never tests a flag.

bench.py feeds the SAME collector: its per-dispatch samples land in
these histograms, and floor-saturation (the round-5 p25-on-the-clamp
bug) is a bucket-zero query — `CLAMP_BOUND`, the first bucket's upper
bound, equals the bench epsilon clamp ceiling by construction.
"""

from __future__ import annotations

import logging
from bisect import bisect_left
from time import perf_counter
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

log = logging.getLogger("emqx_tpu.obs.kernel_telemetry")

# First bucket upper bound == bench.py's epsilon clamp ceiling
# (EPS=1e-5 per batch, saturation test at EPS*1.2): a latency sample in
# bucket zero IS a floor-saturated measurement, so "the estimate sits
# on the clamp" becomes a histogram query instead of bespoke bracketing
# logic that can drift from the exporter.
CLAMP_BOUND = 1.2e-5

# √2-spaced bounds from the clamp ceiling up to ~10s: 40 finite buckets
# + one +Inf overflow. Fixed at import so every histogram (router,
# bench, tests) shares one bucket layout and merges are index-aligned.
_N_BOUNDS = 40
BOUNDS: Tuple[float, ...] = tuple(
    CLAMP_BOUND * (2.0 ** (i / 2.0)) for i in range(_N_BOUNDS)
)

# dispatch legs with dedicated series (callers may add ad-hoc legs,
# e.g. bench labels its configs)
LEG_HASH = "hash"  # pattern-class cuckoo kernel (the production leg)
LEG_DENSE = "dense"  # residual dense kernel / no-index path
LEG_FALLBACK = "fallback"  # host-trie re-match (ambiguity contract)
LEG_ENCODE = "encode"  # host: topic dictionary-encode
LEG_UNPACK = "unpack"  # host: candidate verify + dest expansion
LEG_SYNC = "sync"  # DeviceTable delta scatter / full upload

# The device-resolved fanout leg (ops/fanout.py) reports through the
# same surfaces rather than a dedicated series here: resolve latency as
# the standalone family `emqx_xla_fanout_resolve_seconds`
# (observe_family), plan-cache traffic as the
# `fanout_plan_{hits,misses,stale}` / `fanout_device_plans_total` /
# `fanout_host_fallback_total` counters, and the last resolve's
# fan-to-plan compression as the `fanout_dedup_ratio` gauge.
#
# The mesh serve path (parallel/sharded_match.py) likewise: residual
# wait + host filter of the device-side cross-shard reduction as
# `emqx_xla_mesh_combine_seconds` (observe_family), the last fused
# churn dispatch's row+slot batch as the `mesh_sync_batch_rows` gauge,
# admission-knob flips to single-device serving as the
# `mesh_degraded_single_device_total` counter (+ a 0/1 gauge), and
# per-shard host->device upload skew as the labeled counter family
# `mesh_shard_transfer_rows_total{shard=...}`.


class StreamingHistogram:
    """Fixed-bucket streaming latency histogram (seconds).

    O(1) observe via bisect on the shared √2 bound ladder; percentile
    answers by linear interpolation inside the located bucket. Buckets
    are cumulative only at render time (Prometheus `le` semantics)."""

    __slots__ = ("bounds", "counts", "total", "sum")

    unit = "seconds"

    def __init__(self, bounds: Sequence[float] = BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # [+Inf] overflow last
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.total += 1
        self.sum += v

    def merge(self, other: "StreamingHistogram") -> None:
        assert self.bounds == other.bounds
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.total += other.total
        self.sum += other.sum

    def percentile(self, p: float) -> float:
        """p in [0, 100] -> seconds (0.0 when empty). Interpolates
        linearly within the located bucket; the +Inf bucket reports the
        last finite bound (a floor, honestly labeled by the caller)."""
        if self.total == 0:
            return 0.0
        rank = (p / 100.0) * self.total
        cum = 0.0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self.bounds):  # +Inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
        return self.bounds[-1]

    def clamp_saturated(self) -> bool:
        """True when at least half the samples sit in bucket zero —
        i.e. the median is at or below the epsilon clamp ceiling, so
        the series measures the floor, not a throughput."""
        return self.total > 0 and 2 * self.counts[0] >= self.total

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.total,
            "sum_seconds": round(self.sum, 9),
            "p50_ms": round(self.percentile(50) * 1e3, 6),
            "p99_ms": round(self.percentile(99) * 1e3, 6),
            "p999_ms": round(self.percentile(99.9) * 1e3, 6),
            "clamp_saturated": self.clamp_saturated(),
        }


class CountHistogram(StreamingHistogram):
    """Unitless twin for SIZE distributions (fanout width, batch
    occupancy): same streaming ladder machinery, but the snapshot
    reports raw quantiles — `p50`, not `p50_ms` — so a subscriber
    count can never render as six seconds of latency (the r17
    `emqx_xla_delivery_fan` abuse, ISSUE 19 satellite), and the
    exposition `_sum` drops the nanosecond padding."""

    __slots__ = ()

    unit = "count"

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.total,
            "sum": round(self.sum, 3),
            "p50": round(self.percentile(50), 3),
            "p99": round(self.percentile(99), 3),
            "p999": round(self.percentile(99.9), 3),
            "clamp_saturated": self.clamp_saturated(),
        }


def _fmt_le(v: float) -> str:
    return format(v, "g")


def render_histogram_lines(
    lines: List[str],
    fam: str,
    label_str: str,
    h: StreamingHistogram,
    emit_type: bool = True,
) -> None:
    """Append one labeled histogram series in Prometheus text
    exposition (cumulative `le` buckets, terminal +Inf, `_sum`/`_count`).
    Shared by every histogram exporter in obs/ — kernel telemetry,
    flight-recorder hook durations, sentinel publish stages — so the
    structural invariants the exposition lint enforces live in one
    place. `emit_type=False` for the 2nd..nth series of one family."""
    if emit_type:
        lines.append(f"# TYPE {fam} histogram")
    cum = 0
    for le, c in zip(h.bounds, h.counts):
        cum += c
        lines.append(f'{fam}_bucket{{{label_str},le="{_fmt_le(le)}"}} {cum}')
    lines.append(f'{fam}_bucket{{{label_str},le="+Inf"}} {h.total}')
    # seconds histograms keep nanosecond precision; unitless (count)
    # histograms render their sum as a plain number
    if h.unit == "seconds":
        lines.append(f"{fam}_sum{{{label_str}}} {h.sum:.9f}")
    else:
        lines.append(f"{fam}_sum{{{label_str}}} {_fmt_le(h.sum)}")
    lines.append(f"{fam}_count{{{label_str}}} {h.total}")


class KernelTelemetry:
    """The live collector. One instance per Router (always-on by
    default); every method is cheap host work — dict probes, a bisect,
    integer adds — so the <2% overhead budget holds even on the
    microsecond-scale host legs."""

    enabled = True
    clock = staticmethod(perf_counter)

    def __init__(self, tracer=None, retrace_warn_after: int = 16):
        # spans flow through the obs/otel.py Tracer seam when attached
        # (None costs one attribute read per batch, same contract as
        # broker.tracer)
        self.tracer = tracer
        # flight-recorder seam (obs/flight_recorder.FlightRecorder):
        # when attached, every dispatch-leg sample also lands in the
        # ring as an `xla.<leg>` event — the same stage names as the
        # histograms/spans — so the black box can answer "what were
        # the device legs doing right before the breach". None costs
        # one attribute read per record.
        self.flight = None
        self.retrace_warn_after = retrace_warn_after
        self.hist: Dict[str, StreamingHistogram] = {}
        # standalone histogram FAMILIES (one exposition family each,
        # `emqx_xla_<name>`), as opposed to `hist` whose legs are label
        # values of the shared dispatch-duration family. The dispatch
        # engine's queue-wait series lives here: it measures host-side
        # batching discipline, not a device dispatch leg.
        self.family_hist: Dict[str, StreamingHistogram] = {}
        self.counters: Dict[str, int] = {}
        # labeled counter families: name -> {((k, v), ...) -> count}.
        # Disjoint from `counters` by construction (callers pick one
        # surface per name) so the one-family-per-name exposition
        # invariant holds; rendered like the jit_cache_entries gauge —
        # one TYPE line, one sample per label set.
        self.labeled_counters: Dict[
            str, Dict[Tuple[Tuple[str, str], ...], int]
        ] = {}
        self.gauges: Dict[str, float] = {}
        self._shape_keys: Dict[str, Set[tuple]] = {}
        self._trace_seq = 0
        # serve-time retrace accounting: False during AOT warmup (the
        # engine pre-traces every shape bucket at attach), True once
        # mark_serving() flips it — a fresh shape key after that is a
        # compile stall a production publisher PAID for, the exact
        # outlier class the e2e p99 gate bans (counted as
        # `recompiles_at_serve_total`, gated at 0 over the bench run)
        self.serving = False

    # --- dispatch histograms ---------------------------------------------

    def histogram(self, leg: str) -> StreamingHistogram:
        h = self.hist.get(leg)
        if h is None:
            h = self.hist[leg] = StreamingHistogram()
        return h

    def record_dispatch(self, leg: str, seconds: float) -> None:
        self.histogram(leg).observe(seconds)
        fr = self.flight
        if fr is not None:
            fr.record("xla." + leg, "", {"s": seconds})

    def record_samples(
        self, leg: str, values: Sequence[float]
    ) -> StreamingHistogram:
        """Fold a batch of already-measured samples (bench dispatch
        timings) into `leg`, returning a histogram of JUST this batch
        so the caller can query saturation per-measurement while the
        collector accumulates the run-wide series."""
        batch = StreamingHistogram()
        for v in values:
            batch.observe(float(v))
        self.histogram(leg).merge(batch)
        return batch

    def observe_family(self, name: str, seconds: float) -> None:
        """Record one sample into the standalone histogram family
        `emqx_xla_<name>` (created on first observe)."""
        h = self.family_hist.get(name)
        if h is None:
            h = self.family_hist[name] = StreamingHistogram()
        h.observe(seconds)

    def dispatch_percentile(
        self,
        p: float,
        legs: Sequence[str] = (LEG_HASH, LEG_DENSE, LEG_FALLBACK),
    ) -> float:
        """Percentile over the merged device-dispatch legs (seconds) —
        the dashboard's one-number 'match p99'."""
        merged = StreamingHistogram()
        for leg in legs:
            h = self.hist.get(leg)
            if h is not None:
                merged.merge(h)
        return merged.percentile(p)

    # --- counters / gauges ------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def count_labeled(
        self, name: str, labels: Dict[str, str], n: int = 1
    ) -> None:
        """Increment one series of the labeled counter family
        `emqx_xla_<name>` (e.g. fault_injected_total{leg,shard}). Two
        dict probes + a tuple build — hot-path safe for the chaos-only
        call sites that use it."""
        fam = self.labeled_counters.get(name)
        if fam is None:
            fam = self.labeled_counters[name] = {}
        key = tuple(sorted(labels.items()))
        fam[key] = fam.get(key, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def add_gauge(self, name: str, delta: float) -> None:
        """Relative gauge move (e.g. transfer_inflight up at launch,
        down at collect) — one dict probe + add, hot-path safe."""
        self.gauges[name] = self.gauges.get(name, 0) + delta

    # --- recompile / shape-bucket tracking --------------------------------

    def record_shape(self, kernel: str, key: tuple) -> bool:
        """Note a dispatch of `kernel` under jit-relevant static shapes
        `key`. A fresh key is a new XLA cache entry (a compile); the
        counter therefore stays flat across repeated same-shape batches.
        Crossing `retrace_warn_after` distinct keys flags runaway
        batch-shape churn. Returns True when the key was new."""
        seen = self._shape_keys.get(kernel)
        if seen is None:
            seen = self._shape_keys[kernel] = set()
        if key in seen:
            return False
        seen.add(key)
        self.count("recompiles_total")
        if self.serving:
            self.count("recompiles_at_serve_total")
        fr = self.flight
        if fr is not None:
            fr.record(
                "xla.recompile", "",
                {"kernel": kernel, "shape": str(key), "buckets": len(seen)},
            )
        if len(seen) == self.retrace_warn_after:
            self.count("retrace_warnings_total")
            log.warning(
                "kernel %s reached %d distinct shape buckets — "
                "batch-shape churn is retracing XLA; pad batches to "
                "pow2 sizes", kernel, len(seen),
            )
        return True

    def shape_buckets(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._shape_keys.items()}

    def mark_serving(self) -> None:
        """Close the AOT-warmup window: every shape bucket traced from
        here on is a serve-time compile stall. The counter is seeded
        at 0 so the family renders on the scrape (and the bench gate
        can assert it) even over a perfectly clean run."""
        self.serving = True
        self.counters.setdefault("recompiles_at_serve_total", 0)

    # --- device-table state ----------------------------------------------

    def record_sync(
        self, rows: int, seconds: float, pending: int, full: bool
    ) -> None:
        self.record_dispatch(LEG_SYNC, seconds)
        self.count("sync_rows_total", rows)
        if full:
            self.count("full_uploads_total")
        self.set_gauge("sync_batch_size", rows)
        self.set_gauge("pending_deltas", pending)

    def observe_device_table(self, dtable) -> None:
        """Sample DeviceTable/ShardedDeviceTable-resident state into
        gauges. Called after sync when device state changed; all O(1)
        attribute reads plus a handful of nbytes sums."""
        table = dtable.table
        hbm = 0
        for arrs in (
            dtable._dev, dtable._dev_meta, dtable._dev_slots,
        ):
            if arrs is not None:
                hbm += sum(int(a.nbytes) for a in arrs)
        if dtable._dev_residual is not None:
            hbm += int(dtable._dev_residual.nbytes)
        self.set_gauge("device_table_bytes", hbm)
        self.set_gauge("device_table_capacity", table.capacity)
        self.set_gauge("device_table_rows", len(table))
        self.set_gauge("pending_deltas", len(table.dirty))
        ix = getattr(dtable, "index", None)
        if ix is not None:
            self.set_gauge("classes_active", ix.active_hi())
            self.set_gauge("residual_rows", len(ix.residual_rows))
            self.set_gauge(
                "slot_load_factor",
                round(len(ix) / ix.n_slots, 6) if ix.n_slots else 0.0,
            )

    # --- spans (encode -> dispatch -> unpack) -----------------------------

    def span(self, name: str, parent=None):
        """Start a child span under `parent` (or a new trace) through
        the attached Tracer; returns None when no tracer is wired so
        hot-path callers pay one attribute read."""
        tr = self.tracer
        if tr is None:
            return None
        if parent is not None:
            trace_id = parent.trace_id
        else:
            self._trace_seq += 1
            trace_id = f"{self._trace_seq:032x}"
        return tr.start_span(name, trace_id, parent)

    def end_span(self, span) -> None:
        if span is not None:
            self.tracer.finish(span)

    # --- export -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able runtime view (GET /api/v5/xla/telemetry)."""
        return {
            "enabled": True,
            "counters": dict(sorted(self.counters.items())),
            "labeled_counters": {
                name: {
                    ",".join(f"{k}={v}" for k, v in key): n
                    for key, n in sorted(series.items())
                }
                for name, series in sorted(self.labeled_counters.items())
            },
            "gauges": dict(sorted(self.gauges.items())),
            "dispatch": {
                leg: h.snapshot() for leg, h in sorted(self.hist.items())
            },
            "families": {
                name: h.snapshot()
                for name, h in sorted(self.family_hist.items())
            },
            "recompiles": {
                "total": self.counters.get("recompiles_total", 0),
                "shape_buckets": dict(sorted(self.shape_buckets().items())),
            },
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        """`emqx_xla_*` families in Prometheus text exposition. The
        namespace is disjoint from the broker's `emqx_` families (none
        of which start with `xla_`), so appending to the broker scrape
        preserves the one-family-per-name invariant."""
        node = f'node="{node_name}"'
        lines: List[str] = []
        if self.hist:
            fam = "emqx_xla_dispatch_duration_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for leg in sorted(self.hist):
                render_histogram_lines(
                    lines, fam, f'{node},leg="{leg}"', self.hist[leg],
                    emit_type=False,
                )
        for name in sorted(self.family_hist):
            render_histogram_lines(
                lines, f"emqx_xla_{name}", node, self.family_hist[name]
            )
        for name in sorted(self.counters):
            fam = f"emqx_xla_{name}"
            lines.append(f"# TYPE {fam} counter")
            lines.append(f"{fam}{{{node}}} {self.counters[name]}")
        for name in sorted(self.labeled_counters):
            fam = f"emqx_xla_{name}"
            lines.append(f"# TYPE {fam} counter")
            series = self.labeled_counters[name]
            for key in sorted(series):
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                lines.append(f"{fam}{{{node},{lbl}}} {series[key]}")
        for name in sorted(self.gauges):
            fam = f"emqx_xla_{name}"
            lines.append(f"# TYPE {fam} gauge")
            lines.append(f"{fam}{{{node}}} {self.gauges[name]}")
        buckets = self.shape_buckets()
        if buckets:
            fam = "emqx_xla_jit_cache_entries"
            lines.append(f"# TYPE {fam} gauge")
            for kernel in sorted(buckets):
                lines.append(
                    f'{fam}{{{node},kernel="{kernel}"}} {buckets[kernel]}'
                )
        return lines


class NullKernelTelemetry:
    """Branch-free disabled collector: instrumented code calls the same
    methods and multiplies out to nothing — no flag tests on the hot
    path, no syscalls (clock returns 0.0), no state."""

    enabled = False
    tracer = None
    flight = None

    @staticmethod
    def clock() -> float:
        return 0.0

    def histogram(self, leg):  # tests/bench introspection only
        return StreamingHistogram()

    def record_dispatch(self, leg, seconds) -> None:
        pass

    def record_samples(self, leg, values) -> StreamingHistogram:
        batch = StreamingHistogram()
        for v in values:
            batch.observe(float(v))
        return batch

    def observe_family(self, name, seconds) -> None:
        pass

    def dispatch_percentile(self, p, legs=()) -> float:
        return 0.0

    def count(self, name, n=1) -> None:
        pass

    def count_labeled(self, name, labels, n=1) -> None:
        pass

    def set_gauge(self, name, value) -> None:
        pass

    def record_shape(self, kernel, key) -> bool:
        return False

    def shape_buckets(self) -> Dict[str, int]:
        return {}

    def record_sync(self, rows, seconds, pending, full) -> None:
        pass

    def observe_device_table(self, dtable) -> None:
        pass

    def span(self, name, parent=None):
        return None

    def end_span(self, span) -> None:
        pass

    def snapshot(self) -> Dict[str, Any]:
        return {"enabled": False}

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        return []


NULL = NullKernelTelemetry()
