"""Client/topic/ip traces to files (apps/emqx/src/emqx_trace/).

The reference's emqx_trace gen_server manages trace records and
installs per-trace logger handlers writing rotating files; broker
publish/subscribe call taps (emqx_trace.erl:82-102). Here each Trace
filters events against its type (clientid | topic | ip_address) and
appends formatted lines (text or json) to its own file; the manager
installs broker hooks once and fans events to all running traces.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..ops import topic as topic_mod


@dataclass
class Trace:
    name: str
    type: str  # clientid | topic | ip_address
    filter: str
    formatter: str = "text"  # text | json
    start_at: float = field(default_factory=time.time)
    end_at: Optional[float] = None
    enabled: bool = True
    path: str = ""

    def expired(self) -> bool:
        return self.end_at is not None and time.time() > self.end_at

    def matches(self, clientid: str, topic: Optional[str], ip: str) -> bool:
        if not self.enabled or self.expired():
            return False
        if self.type == "clientid":
            return clientid == self.filter
        if self.type == "topic":
            return topic is not None and topic_mod.match(
                topic_mod.words(topic), topic_mod.words(self.filter)
            )
        if self.type == "ip_address":
            return ip == self.filter
        return False


class TraceManager:
    # expired traces are reaped at most this often from the event path
    SWEEP_INTERVAL = 5.0

    def __init__(self, trace_dir: str = "/tmp/emqx_tpu_trace"):
        self.trace_dir = trace_dir
        self._traces: Dict[str, Trace] = {}
        self._files: Dict[str, object] = {}
        # only RUNNING traces are consulted per event: stopped/expired
        # records stay in _traces for list()/read_log but must not be
        # filtered against on every publish
        self._running: Dict[str, Trace] = {}
        self._next_sweep = 0.0

    # --- lifecycle ------------------------------------------------------

    _TAPS = (
        ("message.publish", "_on_publish"),
        ("session.subscribed", "_on_subscribed"),
        ("client.connected", "_on_connected"),
        ("client.disconnected", "_on_disconnected"),
    )

    def install(self, hooks) -> None:
        """Tap the broker events the reference traces (publish,
        subscribe, connect/disconnect)."""
        self._hooks = hooks
        for point, meth in self._TAPS:
            hooks.add(point, getattr(self, meth), priority=1000)

    def uninstall(self) -> None:
        hooks = getattr(self, "_hooks", None)
        if hooks is None:
            return
        for point, meth in self._TAPS:
            hooks.delete(point, getattr(self, meth))
        self._hooks = None

    def create(
        self,
        name: str,
        type: str,
        filter: str,
        formatter: str = "text",
        end_at: Optional[float] = None,
    ) -> Trace:
        if not name or not all(c.isalnum() or c in "-_" for c in name):
            raise ValueError(f"bad trace name: {name!r}")
        if name in self._traces:
            raise ValueError(f"trace exists: {name}")
        if type not in ("clientid", "topic", "ip_address"):
            raise ValueError(f"bad trace type: {type}")
        if end_at is not None and not isinstance(end_at, (int, float)):
            raise ValueError(f"end_at must be a unix timestamp: {end_at!r}")
        os.makedirs(self.trace_dir, exist_ok=True)
        path = os.path.join(self.trace_dir, f"{name}.log")
        t = Trace(
            name=name, type=type, filter=filter, formatter=formatter,
            end_at=end_at, path=path,
        )
        self._traces[name] = t
        self._running[name] = t
        self._files[name] = open(path, "a", encoding="utf-8")
        return t

    def delete(self, name: str) -> None:
        if name not in self._traces:
            raise KeyError(name)
        self._traces.pop(name)
        self._running.pop(name, None)
        f = self._files.pop(name, None)
        if f is not None:
            f.close()

    def stop_trace(self, name: str) -> None:
        if name not in self._traces:
            raise KeyError(name)
        self._traces[name].enabled = False
        self._running.pop(name, None)
        f = self._files.pop(name, None)
        if f is not None:
            f.close()

    def list(self) -> List[Dict]:
        self._reap_expired()
        return [
            {
                "name": t.name,
                "type": t.type,
                t.type: t.filter,
                "status": "running" if t.enabled else "stopped",
                "start_at": t.start_at,
                "end_at": t.end_at,
            }
            for t in self._traces.values()
        ]

    def _reap_expired(self) -> None:
        """Transition past-end_at traces to stopped, release their file
        handles, and drop them from the per-event filter set (the
        reference stops traces at end_at). Without this an expired
        trace kept its file open and kept being matched against on
        every publish until someone happened to call list()."""
        for t in self._traces.values():
            if t.enabled and t.expired():
                t.enabled = False
                self._running.pop(t.name, None)
                f = self._files.pop(t.name, None)
                if f is not None:
                    f.close()

    def sweep(self, now: Optional[float] = None) -> None:
        """Rate-limited expiry sweep, driven from the event path so
        expiry needs no timer task; cost between sweeps is one float
        compare per emitted event."""
        if now is None:
            now = time.time()
        if now < self._next_sweep:
            return
        self._next_sweep = now + self.SWEEP_INTERVAL
        self._reap_expired()

    def read_log(self, name: str) -> str:
        t = self._traces.get(name)
        if t is None:
            raise KeyError(name)
        f = self._files.get(name)
        if f is not None:
            f.flush()
        with open(t.path, "r", encoding="utf-8") as fh:
            return fh.read()

    def close(self) -> None:
        for name in list(self._traces):
            self.delete(name)

    # --- event taps -----------------------------------------------------

    def _emit(self, clientid: str, topic: Optional[str], ip: str, event: str, detail: Dict) -> None:
        if not self._running:
            return
        self.sweep()
        for t in list(self._running.values()):
            if not t.matches(clientid, topic, ip):
                continue
            f = self._files.get(t.name)
            if f is None:
                continue
            ts = time.strftime("%Y-%m-%dT%H:%M:%S")
            if t.formatter == "json":
                rec = {"time": ts, "event": event, "clientid": clientid, **detail}
                f.write(json.dumps(rec) + "\n")
            else:
                kv = " ".join(f"{k}: {v}" for k, v in detail.items())
                f.write(f"{ts} [{event}] clientid: {clientid} {kv}\n")
            f.flush()

    def _on_publish(self, msg, *_acc):
        peer = str((msg.headers or {}).get("peerhost", ""))
        self._emit(
            msg.from_client, msg.topic, peer.rsplit(":", 1)[0],
            "PUBLISH",
            {"topic": msg.topic, "qos": msg.qos, "payload": msg.payload[:128].hex()},
        )

    def _on_subscribed(self, client_id: str, flt: str, opts, *_):
        self._emit(client_id, flt, "", "SUBSCRIBE", {"topic": flt})

    def _on_connected(self, client_id: str, *info):
        # hook args: (client_id, proto_ver, peer) — peer is "ip:port"
        peer = str(info[1]) if len(info) > 1 else ""
        ip = peer.rsplit(":", 1)[0]
        self._emit(client_id, None, ip, "CONNECTED", {"peer": peer})

    def _on_disconnected(self, client_id: str, *info):
        reason = info[0] if info else ""
        self._emit(client_id, None, "", "DISCONNECTED", {"reason": reason})
