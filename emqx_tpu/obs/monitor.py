"""Dashboard monitor — time-series samples of broker load.

Reference: apps/emqx_dashboard/src/emqx_dashboard_monitor.erl —
periodic sampling of connection/subscription/message counters into a
bounded table, served to the dashboard as both instantaneous gauges
(`/monitor_current`) and a window of rate samples (`/monitor`).
Rates derive from counter deltas between consecutive samples."""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, Dict, List, Optional

DEFAULT_INTERVAL = 10.0
RETENTION = 1000  # samples kept (~2.7h at 10s)

# counter -> rate field name (deltas / interval)
_RATES = {
    "messages.received": "received_msg_rate",
    "messages.sent": "sent_msg_rate",
    "messages.dropped": "dropped_msg_rate",
}


class Monitor:
    def __init__(self, broker, interval: float = DEFAULT_INTERVAL):
        self.broker = broker
        self.interval = interval
        self.samples: Deque[Dict] = deque(maxlen=RETENTION)
        self._task: Optional[asyncio.Task] = None
        self._last_counters: Dict[str, int] = {}
        self._last_ts: Optional[float] = None

    # --- sampling ---------------------------------------------------------

    def current(self) -> Dict:
        """Instantaneous gauges (monitor_current)."""
        stats = self.broker.stats.all()
        m = self.broker.metrics
        out = {
            "connections": stats.get("connections.count", 0),
            "sessions": stats.get("sessions.count", 0),
            "subscriptions": stats.get("subscriptions.count", 0),
            "topics": self.broker.router.topic_count(),
            "retained": stats.get("retained.count", 0),
            "received_msg": m.val("messages.received"),
            "sent_msg": m.val("messages.sent"),
            "dropped_msg": m.val("messages.dropped"),
            # device hot-path gauges ride the same sampling loop, so
            # the dashboard time-series carries dispatch p99 and HBM
            # occupancy alongside connection/message rates
            "xla_dispatch_p99_ms": 0.0,
            "xla_hbm_bytes": 0,
            "xla_recompiles": 0,
        }
        tel = getattr(self.broker.router, "telemetry", None)
        if tel is not None and tel.enabled:
            out["xla_dispatch_p99_ms"] = round(
                tel.dispatch_percentile(99) * 1e3, 4
            )
            out["xla_hbm_bytes"] = int(tel.gauges.get("device_table_bytes", 0))
            out["xla_recompiles"] = tel.counters.get("recompiles_total", 0)
        # sentinel series: per-stage publish p99s, audit divergences,
        # SLO burn rates — the dashboard view of the served-path
        # watchdog (obs/sentinel.py)
        st = getattr(self.broker, "sentinel", None)
        if st is not None:
            out.update(st.monitor_sample())
        return out

    def sample(self) -> Dict:
        """Take one sample; rates are deltas since the previous one."""
        now = time.time()
        cur = self.current()
        out = dict(cur)
        out["time_stamp"] = int(now * 1000)
        dt = (now - self._last_ts) if self._last_ts else None
        for counter, rate_field in _RATES.items():
            v = self.broker.metrics.val(counter)
            prev = self._last_counters.get(counter)
            if dt and prev is not None and dt > 0:
                out[rate_field] = round(max(0, v - prev) / dt, 2)
            else:
                out[rate_field] = 0.0
            self._last_counters[counter] = v
        self._last_ts = now
        self.samples.append(out)
        return out

    def window(self, latest: Optional[int] = None) -> List[Dict]:
        out = list(self.samples)
        if latest is not None and latest > 0:
            out = out[-latest:]
        return out

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self.sample()  # seed the delta base
            self._task = asyncio.ensure_future(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.interval)
                self.sample()
            except asyncio.CancelledError:
                return
            except Exception:  # pragma: no cover - keep sampling
                pass
