"""Slow-subscriber top-k latency tracker (apps/emqx_slow_subs).

The reference hooks 'message.delivered'/'delivery.completed', computes
per-(clientid, topic) delivery latency, and keeps a bounded top-k
table with expiry. Here `install()` hooks the broker's
'message.delivered' point; latency = deliver time − msg.timestamp
(the reference's `whole` stats_type).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple


class SlowSubs:
    def __init__(
        self,
        threshold_ms: float = 500.0,
        top_k: int = 10,
        expire_interval: float = 300.0,
    ):
        self.threshold_ms = threshold_ms
        self.top_k = top_k
        self.expire_interval = expire_interval
        # (clientid, topic) -> {timespan, last_update_time}
        self._tab: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def install(self, hooks) -> None:
        self._hooks = hooks
        hooks.add("message.delivered", self._on_delivered, priority=-100)

    def uninstall(self) -> None:
        hooks = getattr(self, "_hooks", None)
        if hooks is not None:
            hooks.delete("message.delivered", self._on_delivered)
            self._hooks = None

    def _on_delivered(self, client_id: str, msg, *_acc) -> None:
        lat_ms = (time.time() - msg.timestamp) * 1000.0
        self.track(client_id, msg.topic, lat_ms)

    def track(self, client_id: str, topic: str, latency_ms: float) -> None:
        if latency_ms < self.threshold_ms:
            return
        key = (client_id, topic)
        rec = self._tab.get(key)
        now = time.time()
        if rec is None or latency_ms > rec["timespan"]:
            self._tab[key] = {"timespan": latency_ms, "last_update_time": now}
        else:
            rec["last_update_time"] = now
        self._shrink()

    def _shrink(self) -> None:
        self.expire()
        if len(self._tab) > self.top_k:
            # evict the smallest timespans, keeping k (top-k semantics)
            ranked = sorted(
                self._tab.items(), key=lambda kv: -kv[1]["timespan"]
            )
            self._tab = dict(ranked[: self.top_k])

    def expire(self) -> None:
        cutoff = time.time() - self.expire_interval
        self._tab = {
            k: v for k, v in self._tab.items() if v["last_update_time"] >= cutoff
        }

    def topk(self) -> List[Dict[str, Any]]:
        self.expire()
        out = []
        for (cid, topic), rec in sorted(
            self._tab.items(), key=lambda kv: -kv[1]["timespan"]
        ):
            out.append(
                {
                    "clientid": cid,
                    "topic": topic,
                    "timespan": rec["timespan"],
                    "last_update_time": rec["last_update_time"],
                }
            )
        return out

    def clear(self) -> None:
        self._tab.clear()
