"""Prometheus text exposition (apps/emqx_prometheus/src/emqx_prometheus.erl).

Renders the broker's counters and gauges into the Prometheus text
format the reference serves at /api/v5/prometheus/stats. Counter
names are mapped `messages.received` → `emqx_messages_received`,
matching the reference's emqx_* metric families; stats `.max`
watermarks map to `emqx_*_max` gauge families.

Kernel-telemetry families (`emqx_xla_*` — dispatch-latency histograms
with `_bucket`/`_sum`/`_count` + `le` labels, recompile counters,
DeviceTable gauges; see obs/kernel_telemetry.py) append to the same
scrape when the broker's Router carries a live collector, so the
device hot path and the broker surface share one exposition endpoint.

When the Observability bundle is passed, the scrape also carries:

  * `emqx_slow_subs_*` — tracked slow-subscription count + worst
    delivery timespan (apps/emqx_slow_subs, previously API-only);
  * `emqx_topic_messages_*` — per-registered-topic counters with a
    `topic` label (emqx_topic_metrics, previously API-only);
  * `emqx_otel_spans_exported`/`emqx_otel_spans_dropped` — exporter
    throughput/backpressure when an OtelTracer is the broker tracer;
  * `emqx_flight_*` + `emqx_hook_duration_seconds` — flight-recorder
    ring/trigger counters and per-hookpoint latency histograms
    (obs/flight_recorder.py).
"""

from __future__ import annotations

from typing import List


def _norm(name: str) -> str:
    return "emqx_" + name.replace(".", "_").replace("-", "_")


def _lab(value: str) -> str:
    """Escape a label value per the exposition format."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def prometheus_text(broker, node_name: str = "emqx@127.0.0.1", obs=None) -> str:
    lines: List[str] = []
    label = f'{{node="{node_name}"}}'
    seen = set()

    def emit(name: str, kind: str, value) -> None:
        if name in seen:  # one family per name or the scrape fails
            return
        seen.add(name)
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{label} {value}")

    for name, val in sorted(broker.metrics.all().items()):
        emit(_norm(name), "counter", val)
    # broker-level families the reference always exposes (win over the
    # stats-loop variants below, which only appear once traffic starts)
    emit("emqx_sessions_count", "gauge", len(broker.sessions))
    emit("emqx_subscriptions_count", "gauge", len(broker.suboptions))
    for name, val in sorted(broker.stats.all().items()):
        # `.max` watermarks normalize to their own `emqx_*_max` family
        # (distinct names, so the one-family invariant holds)
        emit(_norm(name), "gauge", val)
    rstats = broker.router.stats()
    emit(
        "emqx_topics_count",
        "gauge",
        rstats["exact_topics"] + rstats["wildcard_routes"] + rstats["deep_routes"],
    )
    # kernel telemetry: the emqx_xla_* namespace is disjoint from every
    # broker-derived family, so a plain append preserves uniqueness
    tel = getattr(broker.router, "telemetry", None)
    if tel is not None and tel.enabled:
        lines.extend(tel.prometheus_lines(node_name))
    # publish sentinel: stage-attribution histograms + SLO burn gauges
    # (audit counters already rode the collector's emqx_xla_* render)
    sentinel = getattr(broker, "sentinel", None)
    if sentinel is not None:
        lines.extend(sentinel.prometheus_lines(node_name))
    # mesh microscope: per-dispatch stage decomposition + collective
    # ledger (emqx_xla_mesh_* scope families; labeled histograms render
    # in the scope, like the sentinel's stage exposition)
    scope = getattr(
        getattr(broker.router, "device_table", None), "scope", None
    )
    if scope is not None:
        lines.extend(scope.prometheus_lines(node_name))
    # otel exporter throughput/backpressure (previously only process-
    # internal attributes: a collector outage dropped spans invisibly)
    tracer = getattr(broker, "tracer", None)
    if tracer is not None and hasattr(tracer, "exported"):
        emit("emqx_otel_spans_exported", "counter", tracer.exported)
        emit("emqx_otel_spans_dropped", "counter", tracer.dropped)
    if obs is not None:
        _emit_obs(lines, obs, node_name)
    # durable-tier crash-consistency ledger (emqx_ds_* namespace —
    # process-global: WAL replay runs at open(), often before any
    # broker or obs object exists, so it renders on EVERY scrape)
    from ..ds.metrics import DS_METRICS

    lines.extend(DS_METRICS.prometheus_lines(node_name))
    # cluster-plane failure-domain ledger (emqx_cluster_* namespace —
    # process-global for the same reason: partition/heal transitions
    # ride membership timers that outlive any one broker object)
    from ..cluster.metrics import CLUSTER_METRICS

    lines.extend(CLUSTER_METRICS.prometheus_lines(node_name))
    # JSON codec seam ledger (emqx_json_* namespace — process-global:
    # bridges/REST decode payloads before any broker object exists)
    from ..jsonc import JSON_METRICS

    lines.extend(JSON_METRICS.prometheus_lines(node_name))
    # wire-frame codec seam ledger (emqx_frame_* namespace — process-
    # global like jsonc's: the counted fallback IS the parity story,
    # so it must render even before a broker object exists)
    from ..framec import FRAME_METRICS

    lines.extend(FRAME_METRICS.prometheus_lines(node_name))
    # native delivery-ledger seam (emqx_delivery_* namespace): the
    # native/twin split and per-op fallbacks on every scrape
    from ..broker.delivery import DELIVERY_METRICS

    lines.extend(DELIVERY_METRICS.prometheus_lines(node_name))
    # retainer surface (emqx_retainer_* namespace — the max_retained
    # drop and expiry sweep were previously invisible)
    retainer = getattr(broker, "retainer", None)
    if retainer is not None and hasattr(retainer, "prometheus_lines"):
        lines.extend(retainer.prometheus_lines(node_name))
    return "\n".join(lines) + "\n"


def _emit_obs(lines: List[str], obs, node_name: str) -> None:
    node = f'node="{node_name}"'
    slow = getattr(obs, "slow_subs", None)
    if slow is not None:
        top = slow.topk()
        lines.append("# TYPE emqx_slow_subs_tracked gauge")
        lines.append(f"emqx_slow_subs_tracked{{{node}}} {len(top)}")
        lines.append("# TYPE emqx_slow_subs_max_timespan_ms gauge")
        worst = top[0]["timespan"] if top else 0.0
        lines.append(
            f"emqx_slow_subs_max_timespan_ms{{{node}}} {round(worst, 3)}"
        )
    tm = getattr(obs, "topic_metrics", None)
    if tm is not None:
        rows = tm.list()
        if rows:
            # one family per counter, one labeled sample per topic
            counters = sorted(rows[0]["metrics"])
            for counter in counters:
                fam = "emqx_topic_" + counter.replace(".", "_") + "_total"
                lines.append(f"# TYPE {fam} counter")
                for row in rows:
                    lines.append(
                        f'{fam}{{{node},topic="{_lab(row["topic"])}"}} '
                        f"{row['metrics'][counter]}"
                    )
    flight = getattr(obs, "flight", None)
    if flight is not None:
        lines.extend(flight.prometheus_lines(node_name))
    # delivery-path microscope: sampling-profiler counters/gauges and
    # the event-loop lag histogram (obs/profiler.py) ride the bundle's
    # scrape — both are per-Observability objects, not process-global
    profiler = getattr(obs, "profiler", None)
    if profiler is not None:
        lines.extend(profiler.prometheus_lines(node_name))
    loop_lag = getattr(obs, "loop_lag", None)
    if loop_lag is not None:
        lines.extend(loop_lag.prometheus_lines(node_name))
