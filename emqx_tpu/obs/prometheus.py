"""Prometheus text exposition (apps/emqx_prometheus/src/emqx_prometheus.erl).

Renders the broker's counters and gauges into the Prometheus text
format the reference serves at /api/v5/prometheus/stats. Counter
names are mapped `messages.received` → `emqx_messages_received`,
matching the reference's emqx_* metric families; stats `.max`
watermarks map to `emqx_*_max` gauge families.

Kernel-telemetry families (`emqx_xla_*` — dispatch-latency histograms
with `_bucket`/`_sum`/`_count` + `le` labels, recompile counters,
DeviceTable gauges; see obs/kernel_telemetry.py) append to the same
scrape when the broker's Router carries a live collector, so the
device hot path and the broker surface share one exposition endpoint.
"""

from __future__ import annotations

from typing import List


def _norm(name: str) -> str:
    return "emqx_" + name.replace(".", "_").replace("-", "_")


def prometheus_text(broker, node_name: str = "emqx@127.0.0.1") -> str:
    lines: List[str] = []
    label = f'{{node="{node_name}"}}'
    seen = set()

    def emit(name: str, kind: str, value) -> None:
        if name in seen:  # one family per name or the scrape fails
            return
        seen.add(name)
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{label} {value}")

    for name, val in sorted(broker.metrics.all().items()):
        emit(_norm(name), "counter", val)
    # broker-level families the reference always exposes (win over the
    # stats-loop variants below, which only appear once traffic starts)
    emit("emqx_sessions_count", "gauge", len(broker.sessions))
    emit("emqx_subscriptions_count", "gauge", len(broker.suboptions))
    for name, val in sorted(broker.stats.all().items()):
        # `.max` watermarks normalize to their own `emqx_*_max` family
        # (distinct names, so the one-family invariant holds)
        emit(_norm(name), "gauge", val)
    rstats = broker.router.stats()
    emit(
        "emqx_topics_count",
        "gauge",
        rstats["exact_topics"] + rstats["wildcard_routes"] + rstats["deep_routes"],
    )
    # kernel telemetry: the emqx_xla_* namespace is disjoint from every
    # broker-derived family, so a plain append preserves uniqueness
    tel = getattr(broker.router, "telemetry", None)
    if tel is not None and tel.enabled:
        lines.extend(tel.prometheus_lines(node_name))
    return "\n".join(lines) + "\n"
