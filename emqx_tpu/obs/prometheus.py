"""Prometheus text exposition (apps/emqx_prometheus/src/emqx_prometheus.erl).

Renders the broker's counters and gauges into the Prometheus text
format the reference serves at /api/v5/prometheus/stats. Counter
names are mapped `messages.received` → `emqx_messages_received`,
matching the reference's emqx_* metric families.
"""

from __future__ import annotations

from typing import List


def _norm(name: str) -> str:
    return "emqx_" + name.replace(".", "_").replace("-", "_")


def prometheus_text(broker, node_name: str = "emqx@127.0.0.1") -> str:
    lines: List[str] = []
    label = f'{{node="{node_name}"}}'
    seen = set()

    def emit(name: str, kind: str, value) -> None:
        if name in seen:  # one family per name or the scrape fails
            return
        seen.add(name)
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{label} {value}")

    for name, val in sorted(broker.metrics.all().items()):
        emit(_norm(name), "counter", val)
    # broker-level families the reference always exposes (win over the
    # stats-loop variants below, which only appear once traffic starts)
    emit("emqx_sessions_count", "gauge", len(broker.sessions))
    emit("emqx_subscriptions_count", "gauge", len(broker.suboptions))
    for name, val in sorted(broker.stats.all().items()):
        if name.endswith(".max"):
            continue
        emit(_norm(name), "gauge", val)
    rstats = broker.router.stats()
    emit(
        "emqx_topics_count",
        "gauge",
        rstats["exact_topics"] + rstats["wildcard_routes"] + rstats["deep_routes"],
    )
    return "\n".join(lines) + "\n"
