"""Publish-path sentinel — continuous correctness + latency watchdog.

The north star is the publish fanout path run as one XLA dispatch,
bit-for-bit equal to the host oracle — but through PR 4 that equality
was only asserted in tests and bench stages, never in the served path,
and real blind spots slipped through whole review rounds (a 29% fanout
regression, a silently halved native baseline). This module is the
production-serving answer: a sentinel that rides the live publish path
and keeps three continuous checks running against it.

  * **Shadow-oracle audit** — for 1/N served publishes (the
    `broker.perf.tpu_audit_sample_n` knob) the dispatch engine captures
    the device match result and the fanout plan that actually served,
    and the sentinel re-runs the host oracle (`Router.match_filters` +
    `Broker._build_fanout_plan`) on a deferred event-loop turn. A
    mismatch is a divergence: it bumps
    `emqx_xla_audit_divergence_total`, freezes a flight-recorder
    bundle through the `audit_divergence` trigger rule, raises the
    `xla_audit_divergence` alarm, and — behind `tpu_audit_quarantine`
    — quarantines the diverging filters to the host-walk fallback
    (Router.quarantine_filters) until the next clean table sync
    rewrites their device rows (auto-unquarantine, counted). Audits of
    state that mutated since serve are skipped (counted), never
    reported as divergence.

  * **Per-publish stage attribution** — a sampled publish carries a
    StageSpan through the pipeline: queue (engine wait), encode (topic
    dictionary-encode), kernel (launch), fetch (device->host +
    verify/unpack), resolve (fanout-plan install), deliver (dispatch
    fan-out). Stages land in `emqx_xla_publish_stage_seconds{stage=..}`
    streaming histograms (the kernel-telemetry bucket ladder, so p99s
    are runtime-queryable) plus a bounded exemplar ring of
    (topic, trace id, per-stage ms) served by GET /api/v5/xla/telemetry
    — a p99 breach now names its stage. Unsampled publishes pay one
    attribute read + one counter increment, the same probe-free
    discipline as `run_unobserved`.

  * **SLO tracker** — publish-latency and audit-cleanliness objectives
    with fast/slow burn-rate windows (the multiwindow multi-burn-rate
    alerting shape): error budget = 1 - target, burn = observed error
    rate / budget, and the alarm raises only when BOTH windows burn
    above threshold (fast reacts, slow confirms), clearing when either
    recovers. Burn rates surface on the Prometheus scrape
    (`emqx_xla_slo_*`), the monitor dashboard series,
    GET /api/v5/xla/sentinel, and the `sentinel` ctl command; a
    cluster rollup leg over the RPC plane (ClusterNode.sentinel_rollup)
    lets one node report cluster-wide audit/SLO state.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from .kernel_telemetry import (
    CountHistogram,
    StreamingHistogram,
    render_histogram_lines,
)
from .profiler import DELIVERY_STAGES

log = logging.getLogger("emqx_tpu.obs.sentinel")

# pipeline stages in pipeline order — the label values of
# emqx_xla_publish_stage_seconds. `transfer` is the residual
# device->host wait the finish half actually blocked for (the eager
# copy_to_host_async overlap makes it ~zero on a healthy ring);
# `fetch` is the rest of what finish forces (escalation, verify/
# unpack, deep-trie fold).
STAGES = (
    "queue", "encode", "kernel", "transfer", "fetch", "resolve", "deliver"
)

# fan-size histogram bounds: powers of two up to 1M subscribers — the
# kernel-telemetry seconds ladder tops out at ~8.9 so counts need
# their own scale
FAN_BOUNDS = tuple(2.0 ** i for i in range(21))

# the decomposition contract: per sampled span, sum(sub-stages) must
# land within this fraction of the measured queue+deliver wall, or the
# span counts as out-of-band (the self-check that keeps the
# sub-decomposition from silently lying)
DECOMP_TOLERANCE = 0.10

ALARM_DIVERGENCE = "xla_audit_divergence"

# consecutive clean audits (with no active quarantine) that clear the
# divergence alarm — long enough that a flapping corruption can't
# silence itself between samples
CLEAN_STREAK_TO_CLEAR = 16

# SLO evaluation cadence in samples: a breach evaluation scans both
# burn windows, so successes amortize it; a FAILED sample always
# evaluates immediately (a storm must not wait out the cadence)
SLO_EVAL_EVERY = 8


class StageSpan:
    """Per-sampled-publish stage accumulator. `add` is the only hot
    call: one dict write. Batch-level stages (encode/kernel/fetch,
    shared by every publish coalesced into one dispatch) merge in at
    collect time — standard exemplar semantics: the sampled publish
    carries its batch's device legs."""

    __slots__ = ("topic", "trace_id", "stages", "subs", "fan")

    def __init__(self, topic: str = "", trace_id: str = ""):
        self.topic = topic
        self.trace_id = trace_id
        self.stages: Dict[str, float] = {}
        # delivery sub-stages (DELIVERY_STAGES) — the decomposition of
        # the queue+deliver wall, kept separate so span.total() stays
        # the wall total and never double-counts
        self.subs: Dict[str, float] = {}
        # per-publish fanout plan size, stamped by Broker._fanout
        self.fan = 0

    def add(self, stage: str, seconds: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def add_sub(self, stage: str, seconds: float) -> None:
        self.subs[stage] = self.subs.get(stage, 0.0) + seconds

    def merge(self, other: "StageSpan") -> None:
        for k, v in other.stages.items():
            self.add(k, v)
        for k, v in other.subs.items():
            self.add_sub(k, v)
        if other.fan:
            self.fan += other.fan

    def total(self) -> float:
        return sum(self.stages.values())

    def sub_total(self) -> float:
        return sum(self.subs.values())


class SloObjective:
    """One objective: a target success ratio and two burn-rate
    windows. Events are (monotonic ts, ok) in a bounded deque — the
    feed is sampled publishes/audits, not raw traffic, so the scan
    cost at record/evaluate time is bounded and off the hot path."""

    def __init__(
        self,
        name: str,
        target: float = 0.999,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        burn_threshold: float = 10.0,
        min_events: int = 8,
        max_events: int = 4096,
    ):
        self.name = name
        self.target = min(max(target, 0.0), 1.0 - 1e-9)
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.burn_threshold = burn_threshold
        self.min_events = min_events
        self.events: Deque[Tuple[float, bool]] = deque(maxlen=max_events)
        self.ok_total = 0
        self.bad_total = 0
        self.breached = False

    def record(self, ok: bool, now: Optional[float] = None) -> None:
        self.events.append(
            (time.monotonic() if now is None else now, bool(ok))
        )
        if ok:
            self.ok_total += 1
        else:
            self.bad_total += 1

    def burn_rate(
        self, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Error-budget burn over the window: error_rate / (1-target).
        1.0 = exactly consuming budget; None below `min_events` (too
        little signal to alert on)."""
        now = time.monotonic() if now is None else now
        cutoff = now - window_s
        total = bad = 0
        for ts, ok in reversed(self.events):
            if ts < cutoff:
                break
            total += 1
            if not ok:
                bad += 1
        if total < self.min_events:
            return None
        return (bad / total) / (1.0 - self.target)

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Multiwindow rule: breach requires BOTH windows over the
        threshold (fast reacts to a new storm, slow keeps a brief blip
        from paging); recovery on either window dropping back."""
        fast = self.burn_rate(self.fast_window_s, now)
        slow = self.burn_rate(self.slow_window_s, now)
        if fast is not None and slow is not None:
            if fast > self.burn_threshold and slow > self.burn_threshold:
                self.breached = True
            elif (
                fast <= self.burn_threshold or slow <= self.burn_threshold
            ):
                self.breached = False
        return {
            "target": self.target,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "fast_burn": None if fast is None else round(fast, 4),
            "slow_burn": None if slow is None else round(slow, 4),
            "ok_total": self.ok_total,
            "bad_total": self.bad_total,
            "breached": self.breached,
        }


class _AuditRecord:
    __slots__ = ("topic", "filters", "pairs", "gen", "trace_id")

    def __init__(self, topic, filters, pairs, gen, trace_id):
        self.topic = topic
        self.filters = filters
        self.pairs = pairs
        self.gen = gen
        self.trace_id = trace_id


class PublishSentinel:
    """Attached at boot alongside KernelTelemetry (broker.sentinel is
    the None-seam the dispatch engine probes). All counters land in
    the router's KernelTelemetry collector so `emqx_xla_audit_*`
    families ride the existing scrape; the stage histograms and SLO
    gauges render from prometheus_lines here."""

    def __init__(
        self,
        broker,
        sample_n: int = 1024,
        quarantine: bool = True,
        alarms=None,
        flight=None,
        slo_publish_ms: float = 50.0,
        slo_publish_target: float = 0.999,
        slo_audit_target: float = 0.999,
        slo_fast_window_s: float = 300.0,
        slo_slow_window_s: float = 3600.0,
        slo_burn_threshold: float = 10.0,
        max_pending_audits: int = 64,
        max_exemplars: int = 32,
        warmup_spans: int = 0,
    ):
        self.broker = broker
        self.router = broker.router
        self.telemetry = self.router.telemetry
        self.sample_n = max(0, int(sample_n))
        self.quarantine_enabled = bool(quarantine)
        self.alarms = alarms
        self.flight = flight
        self.slo_publish_ms = slo_publish_ms
        self.stage_hist: Dict[str, StreamingHistogram] = {}
        self.total_hist = StreamingHistogram()
        # delivery sub-stage decomposition (ISSUE 17): the queue+deliver
        # wall split into DELIVERY_STAGES, plus the fan-size histogram
        # and the sum-to-wall self-check counters
        self.delivery_hist: Dict[str, StreamingHistogram] = {}
        # fan width is a COUNT: the unitless histogram keeps it from
        # ever rendering as milliseconds (p50_ms 6000.0 for fan 6, r17)
        self.fan_hist = CountHistogram(bounds=FAN_BOUNDS)
        # broker.perf.tpu_delivery_stages gate: False parks the
        # sub-stage histograms (spans still carry publish stages)
        self.delivery_stages_enabled = True
        self.decomp_in_band = 0
        self.decomp_out_of_band = 0
        self.decomp_last_ratio = 0.0
        self.forwarded_spans_total = 0
        self.exemplars: Deque[Dict[str, Any]] = deque(maxlen=max_exemplars)
        self.slo = {
            "publish_latency": SloObjective(
                "publish_latency",
                target=slo_publish_target,
                fast_window_s=slo_fast_window_s,
                slow_window_s=slo_slow_window_s,
                burn_threshold=slo_burn_threshold,
            ),
            "audit_clean": SloObjective(
                "audit_clean",
                target=slo_audit_target,
                fast_window_s=slo_fast_window_s,
                slow_window_s=slo_slow_window_s,
                burn_threshold=slo_burn_threshold,
            ),
        }
        # warmup exclusion (ISSUE 19 satellite): the first sampled
        # spans ride XLA compile/cache-donation warmup — r17's 723ms
        # kernel p999 was one jit compile, not a serve-path stall.
        # The first `warmup_spans` finished spans are counted and
        # exemplar'd but kept OUT of the serve-stage histograms/SLO.
        # 0 (the bare-broker default) disables the exclusion.
        self.warmup_left = max(0, int(warmup_spans))
        self.warmup_skipped = 0
        self._tick = 0
        self._ack_tick = 0
        self._slo_tick = 0
        self._pending: Deque[_AuditRecord] = deque(maxlen=max_pending_audits)
        self._drain_scheduled = False
        self._clean_streak = 0
        self.spans_total = 0
        self.divergences: Deque[Dict[str, Any]] = deque(maxlen=16)
        # divergence listeners (chaos engine / tests): called with the
        # divergence summary the moment an audit confirms one — lets a
        # harness timestamp detection latency without polling counters
        self.on_divergence: List[Any] = []

    # --- sampling (the only per-publish cost) ----------------------------

    def maybe_span(self, msg) -> Optional[StageSpan]:
        """One increment + one modulo per publish; a hit builds the
        span (and pays the trace-id hash) for this publish only."""
        n = self.sample_n
        if n == 0:
            return None
        self._tick += 1
        if self._tick % n:
            return None
        from .otel import trace_id_of

        self.spans_total += 1
        return StageSpan(msg.topic, trace_id_of(msg))

    def batch_span(self) -> StageSpan:
        """Accumulator for batch-level stages (encode/kernel/fetch/
        resolve), merged into each sampled publish's span at collect."""
        return StageSpan()

    def maybe_ack_clock(self):
        """1/sample_n ack sweeps get wall-timed into the `ack_sweep`
        delivery histogram (channel._handle_ack wraps its body with
        the returned clock) — same probe-free discipline as
        maybe_span: one increment + one modulo per ack packet."""
        n = self.sample_n
        if n == 0:
            return None
        self._ack_tick += 1
        if self._ack_tick % n:
            return None
        return self.telemetry.clock

    def forwarded_span(self, msg) -> Optional[StageSpan]:
        """Remote-side span for a cluster-forwarded publish. The
        origin node stamps its sampled span's trace id into the wire
        payload (`sentinel_trace`); here the receiving node forces a
        span carrying that SAME id, so remote-side delivery sub-stage
        samples join the originating trace — the Dapper propagation
        shape over the broker RPC plane. Forwards without the header
        (origin didn't sample them) stay probe-free."""
        if self.sample_n == 0:
            return None
        trace = msg.headers.get("sentinel_trace") if msg.headers else None
        if not trace:
            return None
        self.spans_total += 1
        self.forwarded_spans_total += 1
        return StageSpan(msg.topic, str(trace))

    # --- stage attribution -----------------------------------------------

    def finish_span(self, span: StageSpan) -> None:
        if self.warmup_left > 0:
            # compile-warmup span: visible as an exemplar (honestly
            # flagged), excluded from the serve-stage stats
            self.warmup_left -= 1
            self.warmup_skipped += 1
            total = span.total()
            self.exemplars.append(
                {
                    "topic": span.topic,
                    "trace_id": span.trace_id,
                    "total_ms": round(total * 1e3, 4),
                    "stages_ms": {
                        k: round(v * 1e3, 4)
                        for k, v in span.stages.items()
                    },
                    "subs_ms": {
                        k: round(v * 1e3, 4) for k, v in span.subs.items()
                    },
                    "fan": span.fan,
                    "warmup": True,
                }
            )
            return
        for stage, s in span.stages.items():
            h = self.stage_hist.get(stage)
            if h is None:
                h = self.stage_hist[stage] = StreamingHistogram()
            h.observe(s)
        if self.delivery_stages_enabled:
            for stage, s in span.subs.items():
                self.observe_delivery(stage, s)
            if span.fan:
                self.fan_hist.observe(float(span.fan))
        # decomposition self-check: the sub-stages must sum to within
        # DECOMP_TOLERANCE of the queue+deliver wall they decompose —
        # a drifting ratio means a sub-stage lost its recording site
        if span.subs:
            wall = span.stages.get("queue", 0.0) + span.stages.get(
                "deliver", 0.0
            )
            sub_total = span.sub_total()
            if wall > 1e-9:
                self.decomp_last_ratio = sub_total / wall
                if abs(sub_total - wall) <= DECOMP_TOLERANCE * wall:
                    self.decomp_in_band += 1
                else:
                    self.decomp_out_of_band += 1
        total = span.total()
        self.total_hist.observe(total)
        self.exemplars.append(
            {
                "topic": span.topic,
                "trace_id": span.trace_id,
                "total_ms": round(total * 1e3, 4),
                "stages_ms": {
                    k: round(v * 1e3, 4) for k, v in span.stages.items()
                },
                "subs_ms": {
                    k: round(v * 1e3, 4) for k, v in span.subs.items()
                },
                "fan": span.fan,
            }
        )
        slo = self.slo["publish_latency"]
        slo.record(total * 1e3 <= self.slo_publish_ms)
        # evaluating burns scans both windows; amortize it — the alarm
        # can lag by a few samples, the deque can't lose any
        self._slo_tick += 1
        if self._slo_tick % SLO_EVAL_EVERY == 0 or not slo.events[-1][1]:
            self._slo_alarm("publish_latency", slo.evaluate())

    def observe_delivery(self, stage: str, seconds: float) -> None:
        """Direct sub-stage observation — spans fold through here, and
        ack/retry sweeps that run outside any publish span (the QoS1/2
        timer path) record their `ack_sweep` time here so ack traffic
        stays visible in the decomposition."""
        h = self.delivery_hist.get(stage)
        if h is None:
            h = self.delivery_hist[stage] = StreamingHistogram()
        h.observe(seconds)

    # --- shadow-oracle audit ---------------------------------------------

    def capture_audit(
        self,
        topic: str,
        filters: Tuple[str, ...],
        pairs: list,
        gen: int,
        trace_id: str = "",
    ) -> None:
        """Record one served publish for deferred re-verification. The
        hot path cost is one deque append; the oracle walk runs on a
        later event-loop turn (or inline when no loop is running —
        bench/offline use)."""
        self._pending.append(
            _AuditRecord(topic, filters, pairs, gen, trace_id)
        )
        if self._drain_scheduled:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self.run_audits()
            return
        self._drain_scheduled = True
        loop.call_soon(self._drain_audits)

    def _drain_audits(self) -> None:
        self._drain_scheduled = False
        self.run_audits()

    def run_audits(self) -> int:
        """Drain and verify every pending capture; returns divergences
        found in this drain."""
        found = 0
        while self._pending:
            if self._audit_one(self._pending.popleft()):
                found += 1
        return found

    def _audit_one(self, rec: _AuditRecord) -> bool:
        tel = self.telemetry
        router = self.router
        if router.generation != rec.gen:
            # routes mutated since serve: the served answer was correct
            # for ITS generation but the oracle would answer for NOW —
            # comparing the two reports churn as corruption
            tel.count("audit_skipped_stale_total")
            return False
        tel.count("audit_total")
        served = sorted(rec.filters)
        oracle = sorted(router.match_filters(rec.topic))
        if served != oracle:
            self._divergence(
                rec,
                kind="match",
                detail={
                    "served": served,
                    "oracle": oracle,
                },
                filters=sorted(set(served).symmetric_difference(oracle)),
            )
            return True
        # fanout-plan leg: audit the plan that is still installed for
        # this filter set (the one the dispatch used), if it is still
        # fresh — a stale entry already rebuilds on next use
        broker = self.broker
        entry = broker._fanout_cache.get(rec.filters)
        if entry is not None and broker._plan_entry_fresh(
            entry, rec.filters
        ):
            oracle_plan = broker._build_fanout_plan(rec.pairs)
            if not _plans_equal(entry[1], oracle_plan):
                self._divergence(
                    rec,
                    kind="fanout",
                    detail={
                        "served_plan": _plan_sig(entry[1]),
                        "oracle_plan": _plan_sig(oracle_plan),
                    },
                    filters=list(rec.filters),
                )
                return True
        tel.count("audit_clean_total")
        slo = self.slo["audit_clean"]
        slo.record(True)
        self._clean_streak += 1
        if (
            self._clean_streak >= CLEAN_STREAK_TO_CLEAR
            and not router.quarantined_filters()
            and self.alarms is not None
        ):
            self.alarms.ensure_deactivated(ALARM_DIVERGENCE)
        return False

    def _divergence(
        self, rec: _AuditRecord, kind: str, detail: Dict, filters: List[str]
    ) -> None:
        tel = self.telemetry
        tel.count("audit_divergence_total")
        self._clean_streak = 0
        slo = self.slo["audit_clean"]
        slo.record(False)
        self._slo_alarm("audit_clean", slo.evaluate())
        summary = {
            "kind": kind,
            "topic": rec.topic,
            "filters": filters,
            "generation": rec.gen,
            **detail,
        }
        self.divergences.append(summary)
        for cb in self.on_divergence:
            try:
                cb(summary)
            except Exception:
                log.exception("divergence listener failed")
        log.error(
            "shadow-oracle divergence (%s) on topic %r: device served a "
            "result the host oracle rejects — %s", kind, rec.topic, detail,
        )
        fl = self.flight
        if fl is not None:
            fl.recorder.record(
                "audit.divergence", rec.trace_id,
                {"kind": kind, "topic": rec.topic},
            )
            fl.maybe_trigger("audit_divergence", summary)
        if self.alarms is not None:
            try:
                self.alarms.ensure(
                    ALARM_DIVERGENCE,
                    details=summary,
                    message=f"XLA publish path diverged from host oracle "
                            f"({kind}) on {rec.topic}",
                )
            except Exception:
                log.exception("divergence alarm failed")
        if self.quarantine_enabled and filters:
            n = self.router.quarantine_filters(filters)
            if n:
                # plans embedding the quarantined filters must rebuild
                # host-side immediately, not on their next stale probe
                for f in filters:
                    self.broker._mark_fanout(f)

    def _slo_alarm(self, name: str, state: Dict[str, Any]) -> None:
        if self.alarms is None:
            return
        alarm = f"xla_slo_{name}_burn"
        try:
            if state["breached"]:
                self.alarms.ensure(
                    alarm,
                    details=state,
                    message=f"SLO {name} burning error budget "
                            f"{state['fast_burn']}x (fast) / "
                            f"{state['slow_burn']}x (slow)",
                )
            else:
                self.alarms.ensure_deactivated(alarm)
        except Exception:
            log.exception("slo alarm transition failed")

    # --- export -----------------------------------------------------------

    def stage_snapshot(self) -> Dict[str, Any]:
        return {
            "sampled_publishes": self.spans_total,
            "sample_n": self.sample_n,
            "warmup_skipped": self.warmup_skipped,
            "total": self.total_hist.snapshot(),
            "stages": {
                s: self.stage_hist[s].snapshot()
                for s in STAGES
                if s in self.stage_hist
            },
            "delivery": {
                s: self.delivery_hist[s].snapshot()
                for s in DELIVERY_STAGES
                if s in self.delivery_hist
            },
            "fan": self.fan_hist.snapshot(),
            "decomposition": self.decomposition_snapshot(),
            "forwarded_spans": self.forwarded_spans_total,
            "exemplars": list(self.exemplars),
        }

    def decomposition_snapshot(self) -> Dict[str, Any]:
        """The sum-to-wall self-check state: how many sampled spans
        decomposed within DECOMP_TOLERANCE of their queue+deliver
        wall, and the latest sub-sum/wall ratio."""
        checked = self.decomp_in_band + self.decomp_out_of_band
        return {
            "tolerance": DECOMP_TOLERANCE,
            "in_band": self.decomp_in_band,
            "out_of_band": self.decomp_out_of_band,
            "in_band_ratio": (
                round(self.decomp_in_band / checked, 4) if checked else None
            ),
            "last_ratio": round(self.decomp_last_ratio, 4),
        }

    def status(self) -> Dict[str, Any]:
        tel = self.telemetry
        counters = getattr(tel, "counters", {})
        return {
            "enabled": self.sample_n > 0,
            "sample_n": self.sample_n,
            "quarantine_enabled": self.quarantine_enabled,
            "quarantined_filters": self.router.quarantined_filters(),
            "audit": {
                "total": counters.get("audit_total", 0),
                "clean": counters.get("audit_clean_total", 0),
                "divergence": counters.get("audit_divergence_total", 0),
                "skipped_stale": counters.get("audit_skipped_stale_total", 0),
                "quarantined": counters.get("audit_quarantine_total", 0),
                "unquarantined": counters.get(
                    "audit_unquarantine_total", 0
                ),
                "pending": len(self._pending),
                "recent_divergences": list(self.divergences),
            },
            "stages": self.stage_snapshot(),
            "slo": {
                "publish_latency_ms": self.slo_publish_ms,
                **{name: obj.evaluate() for name, obj in self.slo.items()},
            },
        }

    def summary(self) -> Dict[str, Any]:
        """Wire-encodable rollup leaf (ClusterNode.sentinel_rollup):
        the cluster view needs verdicts and burn rates, not exemplar
        payloads."""
        tel = self.telemetry
        counters = getattr(tel, "counters", {})
        slo = {name: obj.evaluate() for name, obj in self.slo.items()}
        return {
            "enabled": self.sample_n > 0,
            "audit_total": counters.get("audit_total", 0),
            "audit_divergence": counters.get("audit_divergence_total", 0),
            "quarantined_filters": len(self.router.quarantined_filters()),
            "publish_p99_ms": round(self.total_hist.percentile(99) * 1e3, 4),
            "slo": {
                name: {
                    "fast_burn": s["fast_burn"],
                    "slow_burn": s["slow_burn"],
                    "breached": s["breached"],
                }
                for name, s in slo.items()
            },
        }

    def monitor_sample(self) -> Dict[str, Any]:
        """Flat-ish fields for the dashboard monitor series."""
        counters = getattr(self.telemetry, "counters", {})
        pub = self.slo["publish_latency"].burn_rate(
            self.slo["publish_latency"].fast_window_s
        )
        aud = self.slo["audit_clean"].burn_rate(
            self.slo["audit_clean"].fast_window_s
        )
        return {
            "xla_publish_p99_ms": round(
                self.total_hist.percentile(99) * 1e3, 4
            ),
            "xla_publish_stage_p99_ms": {
                s: round(h.percentile(99) * 1e3, 4)
                for s, h in sorted(self.stage_hist.items())
            },
            "xla_delivery_stage_p99_ms": {
                s: round(h.percentile(99) * 1e3, 4)
                for s, h in sorted(self.delivery_hist.items())
            },
            "xla_audit_divergence": counters.get(
                "audit_divergence_total", 0
            ),
            "xla_slo_publish_burn": 0.0 if pub is None else round(pub, 4),
            "xla_slo_audit_burn": 0.0 if aud is None else round(aud, 4),
        }

    def prometheus_lines(self, node_name: str = "emqx@127.0.0.1") -> List[str]:
        """`emqx_xla_publish_stage_seconds{stage=..}` histograms +
        `emqx_xla_slo_*` gauges. Audit counters already render from the
        kernel-telemetry collector (emqx_xla_audit_*), so only the
        labeled families live here."""
        node = f'node="{node_name}"'
        lines: List[str] = []
        if self.stage_hist:
            fam = "emqx_xla_publish_stage_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for stage in sorted(self.stage_hist):
                render_histogram_lines(
                    lines, fam, f'{node},stage="{stage}"',
                    self.stage_hist[stage], emit_type=False,
                )
        if self.delivery_hist:
            fam = "emqx_xla_delivery_stage_seconds"
            lines.append(f"# TYPE {fam} histogram")
            for stage in sorted(self.delivery_hist):
                render_histogram_lines(
                    lines, fam, f'{node},stage="{stage}"',
                    self.delivery_hist[stage], emit_type=False,
                )
            render_histogram_lines(
                lines, "emqx_xla_delivery_fan", node, self.fan_hist
            )
            decomp = self.decomposition_snapshot()
            lines.append(
                "# TYPE emqx_xla_delivery_decomp_in_band_total counter"
            )
            lines.append(
                f"emqx_xla_delivery_decomp_in_band_total{{{node}}} "
                f"{decomp['in_band']}"
            )
            lines.append(
                "# TYPE emqx_xla_delivery_decomp_out_of_band_total counter"
            )
            lines.append(
                f"emqx_xla_delivery_decomp_out_of_band_total{{{node}}} "
                f"{decomp['out_of_band']}"
            )
            lines.append(
                "# TYPE emqx_xla_delivery_decomp_last_ratio gauge"
            )
            lines.append(
                f"emqx_xla_delivery_decomp_last_ratio{{{node}}} "
                f"{decomp['last_ratio']}"
            )
        evals = {name: obj.evaluate() for name, obj in self.slo.items()}
        lines.append("# TYPE emqx_xla_slo_burn_rate gauge")
        for name, s in sorted(evals.items()):
            for window in ("fast", "slow"):
                v = s[f"{window}_burn"]
                lines.append(
                    f'emqx_xla_slo_burn_rate{{{node},objective="{name}",'
                    f'window="{window}"}} {0.0 if v is None else v}'
                )
        lines.append("# TYPE emqx_xla_slo_breached gauge")
        for name, s in sorted(evals.items()):
            lines.append(
                f'emqx_xla_slo_breached{{{node},objective="{name}"}} '
                f"{int(s['breached'])}"
            )
        return lines


def _plan_sig(plan: tuple) -> Dict[str, list]:
    mem, other = plan
    return {
        "mem": [(c, o.qos) for c, _s, o in mem],
        "other": [(c, f, o.qos) for c, f, o in other],
    }


def _plans_equal(served: tuple, oracle: tuple) -> bool:
    """Plans are bit-identical by contract: same clients, same winning
    QoS, same order (first-seen dict order). Compare the delivery-
    relevant projection in place (no signature materialization — this
    runs per audit over the full fan, so a 100k-fan audit must not
    build four throwaway lists); session objects are skipped because
    the registry note can lag a resubscribe without changing delivery."""
    smem, sother = served
    omem, oother = oracle
    if len(smem) != len(omem) or len(sother) != len(oother):
        return False
    for (c1, _s1, o1), (c2, _s2, o2) in zip(smem, omem):
        if c1 != c2 or o1.qos != o2.qos:
            return False
    for (c1, f1, o1), (c2, f2, o2) in zip(sother, oother):
        if c1 != c2 or f1 != f2 or o1.qos != o2.qos:
            return False
    return True
