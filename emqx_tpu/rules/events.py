"""Hookpoint → rule-event bridge — emqx_rule_events analog.

The reference turns broker hookpoints into `$events/...` topics that
rules can select FROM (apps/emqx_rule_engine/src/emqx_rule_events.erl:
80,118); a plain topic in FROM means the 'message.publish' stream.
Event field sets mirror the reference's event payloads.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from ..broker.message import Message

EVENT_TOPICS = {
    "$events/message_publish": "message.publish",
    "$events/message_delivered": "message.delivered",
    "$events/message_acked": "message.acked",
    "$events/message_dropped": "message.dropped",
    "$events/client_connected": "client.connected",
    "$events/client_disconnected": "client.disconnected",
    "$events/client_connack": "client.connack",
    "$events/client_check_authz_complete": "client.check_authz_complete",
    "$events/session_subscribed": "session.subscribed",
    "$events/session_unsubscribed": "session.unsubscribed",
    "$events/delivery_dropped": "delivery.dropped",
}


def is_event_topic(t: str) -> bool:
    return t.startswith("$events/")


def message_event(msg: Message, event: str = "$events/message_publish") -> Dict[str, Any]:
    """Build the rule-eval environment for a message event; field names
    follow the reference's columns(message.publish)."""
    ts_ms = int(msg.timestamp * 1000)
    return {
        "event": event.removeprefix("$events/"),
        "id": msg.id,
        "clientid": msg.from_client,
        "username": (msg.headers or {}).get("username", ""),
        "topic": msg.topic,
        "qos": msg.qos,
        "flags": {"retain": msg.retain},
        "retain": msg.retain,
        "payload": msg.payload,
        "peerhost": (msg.headers or {}).get("peerhost", ""),
        "pub_props": dict(msg.props or {}),
        "timestamp": ts_ms,
        "publish_received_at": ts_ms,
        "node": "local",
    }


def client_event(event: str, client_id: str, **extra: Any) -> Dict[str, Any]:
    env = {
        "event": event.removeprefix("$events/"),
        "clientid": client_id,
        "username": extra.pop("username", ""),
        "timestamp": int(time.time() * 1000),
        "node": "local",
    }
    env.update(extra)
    return env
