"""Rule engine runtime — emqx_rule_engine / emqx_rule_runtime analog.

Rules are indexed by their FROM topic filters in the SAME matcher
structure the router uses (the reference shares emqx_topic_index
between router and ?RULE_TOPIC_INDEX, apps/emqx_rule_engine/src/
emqx_rule_engine.erl:230-231,537,545 — BASELINE config #5). On
'message.publish' the engine matches the message topic against the
rule index (host trie for singles; the engine also exposes
`match_rules_batch` so the broker's TPU batch path can fold rule
matching into the same device dispatch), evaluates WHERE, binds the
SELECT fields, and feeds the result to the rule's actions.

Actions: console (debug log), republish (back into the broker with
placeholder-templated topic/payload/qos), function (any callable —
the bridge/action hookup point).
"""

from __future__ import annotations

import logging
import re
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..broker.message import Message
from ..jsonc import dumps as _json_dumps, loads as _json_loads
from ..ops import topic as topic_mod
from ..ops.host_index import TopicTrie
from . import events as ev
from .funcs import FUNCS, _str
from .sql import Select, SqlError, parse

log = logging.getLogger("emqx_tpu.rules")

_UNDEF = object()


# --- expression evaluation ---------------------------------------------


def _get_path(env: Dict[str, Any], path: List[str]) -> Any:
    cur: Any = env
    for i, seg in enumerate(path):
        if seg == "*":
            return cur
        if isinstance(cur, (bytes, str)) and i >= 1:
            # payload.* auto-decodes JSON payloads (reference behavior)
            try:
                cur = _json_loads(cur if isinstance(cur, str) else cur.decode())
            except Exception:
                return None
        if isinstance(cur, dict):
            cur = cur.get(seg, _UNDEF)
            if cur is _UNDEF:
                return None
        elif isinstance(cur, list):
            try:
                cur = cur[int(seg) - 1]
            except (ValueError, IndexError):
                return None
        else:
            return None
    if isinstance(cur, bytes):
        # strict-else-bytes keeps binary payloads LOSSLESS end to end:
        # a valid-utf8 payload round-trips through str (decode/encode
        # are inverse), an invalid one stays bytes for the binary
        # consumers (schema_decode of avro/protobuf wire payloads) —
        # 'replace' corrupted them irreversibly
        try:
            cur = cur.decode("utf-8")
        except UnicodeDecodeError:
            pass
    return cur


def _like(s: Any, pat: str) -> bool:
    rx = re.escape(pat).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, _str(s)) is not None


def _eq(a: Any, b: Any) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if isinstance(a, (int, float)) or isinstance(b, (int, float)):
        try:
            return float(a) == float(b)
        except (TypeError, ValueError):
            return False
    return a == b


def eval_expr(e: Any, env: Dict[str, Any]) -> Any:
    op = e[0]
    if op == "lit":
        return e[1]
    if op == "path":
        return _get_path(env, e[1])
    if op == "index":
        obj = eval_expr(e[1], env)
        idx = eval_expr(e[2], env)
        if isinstance(obj, dict):
            return obj.get(_str(idx))
        if isinstance(obj, list):
            try:
                return obj[int(idx) - 1]
            except (ValueError, IndexError):
                return None
        return None
    if op == "and":
        return bool(eval_expr(e[1], env)) and bool(eval_expr(e[2], env))
    if op == "or":
        return bool(eval_expr(e[1], env)) or bool(eval_expr(e[2], env))
    if op == "not":
        return not bool(eval_expr(e[1], env))
    if op == "neg":
        return -eval_expr(e[1], env)
    if op in ("=", "!=", ">", "<", ">=", "<="):
        a, b = eval_expr(e[1], env), eval_expr(e[2], env)
        if op == "=":
            return _eq(a, b)
        if op == "!=":
            return not _eq(a, b)
        try:
            if op == ">":
                return a > b
            if op == "<":
                return a < b
            if op == ">=":
                return a >= b
            return a <= b
        except TypeError:
            return False
    if op in ("+", "-", "*", "/", "div", "mod"):
        a, b = eval_expr(e[1], env), eval_expr(e[2], env)
        if op == "+" and (isinstance(a, str) or isinstance(b, str)):
            return _str(a) + _str(b)
        try:
            if op == "+":
                return a + b
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b
            if op == "div":
                return int(a) // int(b)
            return int(a) % int(b)
        except (TypeError, ZeroDivisionError):
            return None
    if op == "in":
        v = eval_expr(e[1], env)
        return any(_eq(v, eval_expr(x, env)) for x in e[2])
    if op == "like":
        return _like(eval_expr(e[1], env), e[2])
    if op == "isnull":
        return eval_expr(e[1], env) is None
    if op == "case":
        for c, v in e[1]:
            if bool(eval_expr(c, env)):
                return eval_expr(v, env)
        return eval_expr(e[2], env)
    if op == "call":
        fn = FUNCS.get(e[1])
        if fn is None:
            raise SqlError(f"unknown function {e[1]!r}")
        if getattr(fn, "_wants_env", False):
            # message-context accessors (clientid(), payload(path), …)
            # read the event env directly, like the reference's
            # closure-over-message builtins (emqx_rule_funcs.erl:317-396)
            return fn(env, *(eval_expr(a, env) for a in e[2]))
        return fn(*(eval_expr(a, env) for a in e[2]))
    raise SqlError(f"bad expr node {op!r}")


def _public_env(env: Dict[str, Any]) -> Dict[str, Any]:
    # engine-internal keys (_proc_dict, _kv_store, _republish_depth)
    # must never appear in rows: a bare `SELECT *` republish would
    # otherwise serialize the whole engine-wide kv store into the
    # published payload
    return {k: v for k, v in env.items() if not k.startswith("_")}


def select_fields(sel: Select, env: Dict[str, Any]) -> Dict[str, Any]:
    """Bind the SELECT list; '*' keeps the (public) env."""
    if not sel.fields:
        return _public_env(env)
    out: Dict[str, Any] = {}
    for expr, alias in sel.fields:
        if expr == ("path", ["*"]):
            out.update(_public_env(env))
            continue
        val = eval_expr(expr, env)
        name = alias or (expr[1][-1] if expr[0] == "path" else "value")
        out[name] = val
    return out


_PLACEHOLDER = re.compile(r"\$\{([^}]+)\}")


def render_template(tpl: str, env: Dict[str, Any]) -> str:
    """${path.to.field} placeholder substitution (emqx_placeholder)."""
    return _PLACEHOLDER.sub(
        lambda m: _str(_get_path(env, m.group(1).split("."))), tpl
    )


# --- rules --------------------------------------------------------------


@dataclass
class RuleMetrics:
    matched: int = 0
    passed: int = 0
    failed: int = 0
    no_result: int = 0
    actions_success: int = 0
    actions_failed: int = 0


@dataclass
class Rule:
    id: str
    sql: str
    select: Select
    actions: List[Dict[str, Any]] = field(default_factory=list)
    enable: bool = True
    description: str = ""
    metrics: RuleMetrics = field(default_factory=RuleMetrics)
    created_at: float = field(default_factory=time.time)


class RuleEngine:
    def __init__(self, broker=None, ignore_sys: bool = True):
        self.broker = broker
        self.ignore_sys = ignore_sys
        self.rules: Dict[str, Rule] = {}
        # FROM-filter index, shared matcher shape with the router
        # (?RULE_TOPIC_INDEX analog)
        self._index = TopicTrie()
        self._event_rules: Dict[str, Set[str]] = {}  # event topic -> rule ids
        self._installed = False
        # named action providers: kind -> fn(args, row, env)
        self.action_providers: Dict[str, Any] = {}
        # per-rule proc dicts + engine-wide kv store (see apply_rule)
        self._proc_dicts: Dict[str, Dict[str, Any]] = {}
        self._kv_store: Dict[str, Any] = {}
        # batched WHERE leg (rules/batch_where.py): inside an open
        # batch_window(), vectorizable WHERE predicates defer into one
        # columnar mask evaluation at window close; everything else
        # (foreach, uncompilable predicates, fallback rows) re-runs
        # through eval_expr — the oracle — counted, never silently
        # wrong
        self.batch_where_enabled = False
        self.telemetry = None  # KernelTelemetry handle (emqx_xla_rule_*)
        self._win_envs: Optional[List[Dict[str, Any]]] = None
        self._win_groups: Optional[Dict[str, Tuple[Rule, List[int]]]] = None
        self.where_stats = {
            "windows": 0,
            "batch_rows": 0,
            "fallback_rows": 0,
            "uncompiled_rows": 0,
        }

    # --- CRUD -----------------------------------------------------------

    def create_rule(
        self,
        rule_id: str,
        sql: str,
        actions: Optional[List[Dict[str, Any]]] = None,
        enable: bool = True,
        description: str = "",
    ) -> Rule:
        if rule_id in self.rules:
            raise ValueError(f"rule {rule_id!r} exists")
        sel = parse(sql)
        rule = Rule(rule_id, sql, sel, actions or [], enable, description)
        self.rules[rule_id] = rule
        for f in sel.froms:
            if ev.is_event_topic(f):
                self._event_rules.setdefault(f, set()).add(rule_id)
            else:
                self._index.insert(topic_mod.words(f), (rule_id, f))
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        for f in rule.select.froms:
            if ev.is_event_topic(f):
                self._event_rules.get(f, set()).discard(rule_id)
            else:
                self._index.remove(topic_mod.words(f), (rule_id, f))
        # the proc dict dies with the rule (the reference's erlang
        # proc dict dies with the rule's process); a later rule that
        # reuses the id must start clean
        self._proc_dicts.pop(rule_id, None)
        return True

    def update_rule(self, rule_id: str, **kw) -> Rule:
        old = self.rules.get(rule_id)
        if old is None:
            raise KeyError(rule_id)
        sql = kw.get("sql", old.sql)
        parse(sql)  # validate BEFORE touching the live rule
        actions = kw.get("actions", old.actions)
        enable = kw.get("enable", old.enable)
        desc = kw.get("description", old.description)
        self.delete_rule(rule_id)
        return self.create_rule(rule_id, sql, actions, enable, desc)

    # --- matching -------------------------------------------------------

    def match_rules(self, topic: str) -> List[Rule]:
        # a rule with several FROM filters matching the same topic
        # still fires once (the reference dedups by rule id)
        seen = set()
        out = []
        for rid, _f in self._index.match(topic_mod.words(topic)):
            if rid in seen or rid not in self.rules:
                continue
            seen.add(rid)
            rule = self.rules[rid]
            if rule.enable:
                out.append(rule)
        return out

    def match_rules_batch(self, topics: Sequence[str]) -> List[List[Rule]]:
        """Batch-shaped API so the broker's device dispatch can carry
        rule matching in the same batch (config #5)."""
        return [self.match_rules(t) for t in topics]

    # --- evaluation -----------------------------------------------------

    MAX_REPUBLISH_DEPTH = 8

    def on_message_publish(self, msg: Message, acc=None):
        """'message.publish' hook body (emqx_rule_events.erl:80,118)."""
        if self.ignore_sys and msg.topic.startswith("$SYS/"):
            return None
        depth = int(msg.headers.get("republish_depth", 0))
        if depth >= self.MAX_REPUBLISH_DEPTH:
            log.warning("republish loop cut at depth %d on %s", depth, msg.topic)
            return None
        env = ev.message_event(msg)
        env["_republish_depth"] = depth
        by = msg.headers.get("republish_by")
        if self._win_envs is not None:
            # open batch window: defer WHERE-bearing single-row rules
            # into the columnar drain; foreach and WHERE-less rules
            # apply immediately (nothing to vectorize)
            ei = None
            for rule in self.match_rules(msg.topic):
                if by is not None and rule.id == by:
                    continue
                sel = rule.select
                if sel.foreach is not None or sel.where is None:
                    self.apply_rule(rule, env)
                    continue
                rule.metrics.matched += 1
                if ei is None:
                    ei = len(self._win_envs)
                    self._win_envs.append(env)
                self._win_groups.setdefault(rule.id, (rule, []))[1].append(ei)
            return None
        for rule in self.match_rules(msg.topic):
            if by is not None and rule.id == by:
                continue  # a rule never re-triggers itself
            self.apply_rule(rule, env)
        return None

    def on_event(self, event_topic: str, env: Dict[str, Any]) -> None:
        for rid in self._event_rules.get(event_topic, ()):
            rule = self.rules.get(rid)
            if rule is not None and rule.enable:
                self.apply_rule(rule, env)

    def _bind_env(self, rule: Rule, env: Dict[str, Any]) -> Dict[str, Any]:
        # proc_dict is scoped PER RULE (the reference's erlang proc
        # dict belongs to the evaluating process — rules must not see
        # each other's values); kv_store is engine-wide like the
        # reference's node-global ets (ADVICE r4)
        # a COPY per rule: the caller reuses one env across matching
        # rules, and injecting per-rule state into the shared dict
        # would hand every later rule the first rule's proc dict
        env = dict(env)
        env["_proc_dict"] = self._proc_dicts.setdefault(rule.id, {})
        env["_kv_store"] = self._kv_store
        return env

    def apply_rule(self, rule: Rule, env: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
        rule.metrics.matched += 1
        env = self._bind_env(rule, env)
        try:
            sel = rule.select
            rows: List[Dict[str, Any]]
            if sel.foreach is not None:
                coll = eval_expr(sel.foreach[0], env)
                if not isinstance(coll, list):
                    rule.metrics.no_result += 1
                    return None
                alias = sel.foreach[1] or "item"
                rows = []
                for item in coll:
                    ienv = {**env, alias: item, "item": item}
                    if sel.incase is not None and not bool(eval_expr(sel.incase, ienv)):
                        continue
                    if sel.where is not None and not bool(eval_expr(sel.where, ienv)):
                        continue
                    rows.append(select_fields(sel, ienv))
                if not rows:
                    rule.metrics.no_result += 1
                    return None
            else:
                if sel.where is not None and not bool(eval_expr(sel.where, env)):
                    rule.metrics.no_result += 1
                    return None
                rows = [select_fields(sel, env)]
            rule.metrics.passed += 1
        except Exception:
            rule.metrics.failed += 1
            log.exception("rule %s evaluation failed", rule.id)
            return None
        for row in rows:
            self._run_actions(rule, row, env)
        return rows

    def _finish_rule(self, rule: Rule, env: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
        """Post-WHERE half of apply_rule for a single-row rule whose
        predicate already passed; env must be _bind_env-bound."""
        try:
            rows = [select_fields(rule.select, env)]
            rule.metrics.passed += 1
        except Exception:
            rule.metrics.failed += 1
            log.exception("rule %s evaluation failed", rule.id)
            return None
        for row in rows:
            self._run_actions(rule, row, env)
        return rows

    def _apply_where_row(self, rule: Rule, env: Dict[str, Any]) -> None:
        """Per-row escalation for a batch-windowed rule: evaluate the
        WHERE through eval_expr (the oracle) and finish. `matched` was
        counted at enqueue time."""
        env = self._bind_env(rule, env)
        try:
            if not bool(eval_expr(rule.select.where, env)):
                rule.metrics.no_result += 1
                return
        except Exception:
            rule.metrics.failed += 1
            log.exception("rule %s evaluation failed", rule.id)
            return
        self._finish_rule(rule, env)

    # --- batched WHERE window (rules/batch_where.py) --------------------

    @contextmanager
    def batch_window(self):
        """Defer WHERE evaluation for every 'message.publish' rule hit
        inside the window into one columnar mask drain at close. The
        broker's coalesced publish paths (publish_batch, the dispatch
        engine's _flush) open this around their _pre_publish fold.
        Nested windows are no-ops (the outermost drains)."""
        if not self.batch_where_enabled or self._win_envs is not None:
            yield
            return
        self._win_envs = []
        self._win_groups = {}
        try:
            yield
        finally:
            self._drain_window()

    def _drain_window(self) -> None:
        envs, self._win_envs = self._win_envs, None
        groups, self._win_groups = self._win_groups, None
        if not groups:
            return
        import numpy as np

        from .batch_where import ColumnBatch, compile_where

        tel = self.telemetry
        if tel is None and self.broker is not None:
            tel = getattr(self.broker.router, "telemetry", None)
        if tel is not None and not getattr(tel, "enabled", False):
            tel = None
        t0 = time.perf_counter()
        batch = ColumnBatch(envs)
        st = self.where_stats
        st["windows"] += 1
        n_vec = n_fb = n_unc = 0
        for rule, idxs in groups.values():
            comp = getattr(rule, "_where_compiled", _UNDEF)
            if comp is _UNDEF:
                comp = compile_where(rule.select.where)
                rule._where_compiled = comp
            if comp is None:
                n_unc += len(idxs)
                for i in idxs:
                    self._apply_where_row(rule, envs[i])
                continue
            ix = np.asarray(idxs, dtype=np.int64)
            try:
                mask, fb = comp.eval(batch, ix)
            except Exception:
                log.exception(
                    "rule %s batched WHERE failed; per-row fallback", rule.id
                )
                n_fb += len(idxs)
                for i in idxs:
                    self._apply_where_row(rule, envs[i])
                continue
            n_vec += len(idxs)
            for j, i in enumerate(idxs):
                if fb[j]:
                    n_fb += 1
                    self._apply_where_row(rule, envs[i])
                elif mask[j]:
                    self._finish_rule(rule, self._bind_env(rule, envs[i]))
                else:
                    rule.metrics.no_result += 1
        st["batch_rows"] += n_vec
        st["fallback_rows"] += n_fb
        st["uncompiled_rows"] += n_unc
        if tel is not None:
            tel.count("rule_where_batch_rows_total", n_vec)
            tel.count("rule_where_fallback_rows_total", n_fb)
            tel.count("rule_where_uncompiled_rows_total", n_unc)
            tel.observe_family(
                "rule_where_batch_seconds", time.perf_counter() - t0
            )

    def _run_actions(self, rule: Rule, row: Dict[str, Any], env: Dict[str, Any]) -> None:
        for action in rule.actions:
            try:
                self._run_action({**action, "_rule_id": rule.id}, row, env)
                rule.metrics.actions_success += 1
            except Exception:
                rule.metrics.actions_failed += 1
                log.exception("rule %s action %s failed", rule.id, action)

    def _run_action(self, action: Dict[str, Any], row: Dict[str, Any], env: Dict[str, Any]) -> None:
        kind = action.get("function", action.get("type", "console"))
        if kind == "console":
            log.info("[rule console] %s", _json_dumps(row, default=_str))
        elif kind == "republish":
            args = action.get("args", {})
            tpl_env = {**env, **row}
            topic = render_template(args.get("topic", "republish/${topic}"), tpl_env)
            payload_tpl = args.get("payload", "${payload}")
            payload = render_template(payload_tpl, tpl_env) if payload_tpl else _json_dumps(row, default=_str)
            qos_raw = args.get("qos", 0)
            qos = int(render_template(str(qos_raw), tpl_env)) if isinstance(qos_raw, str) else qos_raw
            if self.broker is None:
                raise RuntimeError("republish without a broker")
            out = Message(
                topic=topic,
                payload=payload.encode() if isinstance(payload, str) else payload,
                qos=qos,
                retain=bool(args.get("retain", False)),
                from_client=f"rule:{action.get('_rule_id', '')}",
            )
            # loop guards: a rule never re-triggers itself, and chains
            # across rules are depth-capped (the reference marks
            # republished messages and warns on loops)
            out.headers["republish_by"] = action.get("_rule_id")
            out.headers["republish_depth"] = int(env.get("_republish_depth", 0)) + 1
            self.broker.publish(out)
        elif callable(kind):
            kind(row, env)
        elif kind in self.action_providers:
            # registered providers (bridges register "bridge" here —
            # the actions-v2 seam of emqx_bridge_v2:send_message)
            self.action_providers[kind](action.get("args", {}), row, env)
        else:
            raise ValueError(f"unknown action {kind!r}")

    # --- wiring + dry run ----------------------------------------------

    def install(self, hooks) -> None:
        if self._installed:
            return
        hooks.add("message.publish", self._hook_cb, priority=50)
        if self.broker is not None:
            # coalesced publish paths probe this handle to open the
            # batched-WHERE window around their _pre_publish fold
            self.broker.rule_batcher = self
        self._installed = True

    def _hook_cb(self, msg, acc=None):
        # run_fold('message.publish', (), msg): single arg is the acc
        m = msg if isinstance(msg, Message) else acc
        if isinstance(m, Message):
            self.on_message_publish(m)
        return None

    def test_sql(self, sql: str, env: Dict[str, Any]) -> Optional[Any]:
        """Dry-run (emqx_rule_sqltester analog): returns the bound
        SELECT result or None if WHERE filtered it out."""
        sel = parse(sql)
        tmp = Rule("$test", sql, sel)
        rows = self.apply_rule(tmp, env)
        if rows is None:
            return None
        return rows[0] if sel.foreach is None else rows
