"""Rule engine runtime — emqx_rule_engine / emqx_rule_runtime analog.

Rules are indexed by their FROM topic filters in the SAME matcher
structure the router uses (the reference shares emqx_topic_index
between router and ?RULE_TOPIC_INDEX, apps/emqx_rule_engine/src/
emqx_rule_engine.erl:230-231,537,545 — BASELINE config #5). On
'message.publish' the engine matches the message topic against the
rule index (host trie for singles; the engine also exposes
`match_rules_batch` so the broker's TPU batch path can fold rule
matching into the same device dispatch), evaluates WHERE, binds the
SELECT fields, and feeds the result to the rule's actions.

Actions: console (debug log), republish (back into the broker with
placeholder-templated topic/payload/qos), function (any callable —
the bridge/action hookup point).
"""

from __future__ import annotations

import json
import logging
import re
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..broker.message import Message
from ..ops import topic as topic_mod
from ..ops.host_index import TopicTrie
from . import events as ev
from .funcs import FUNCS, _str
from .sql import Select, SqlError, parse

log = logging.getLogger("emqx_tpu.rules")

_UNDEF = object()


# --- expression evaluation ---------------------------------------------


def _get_path(env: Dict[str, Any], path: List[str]) -> Any:
    cur: Any = env
    for i, seg in enumerate(path):
        if seg == "*":
            return cur
        if isinstance(cur, (bytes, str)) and i >= 1:
            # payload.* auto-decodes JSON payloads (reference behavior)
            try:
                cur = json.loads(cur if isinstance(cur, str) else cur.decode())
            except Exception:
                return None
        if isinstance(cur, dict):
            cur = cur.get(seg, _UNDEF)
            if cur is _UNDEF:
                return None
        elif isinstance(cur, list):
            try:
                cur = cur[int(seg) - 1]
            except (ValueError, IndexError):
                return None
        else:
            return None
    if isinstance(cur, bytes):
        # strict-else-bytes keeps binary payloads LOSSLESS end to end:
        # a valid-utf8 payload round-trips through str (decode/encode
        # are inverse), an invalid one stays bytes for the binary
        # consumers (schema_decode of avro/protobuf wire payloads) —
        # 'replace' corrupted them irreversibly
        try:
            cur = cur.decode("utf-8")
        except UnicodeDecodeError:
            pass
    return cur


def _like(s: Any, pat: str) -> bool:
    rx = re.escape(pat).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, _str(s)) is not None


def _eq(a: Any, b: Any) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if isinstance(a, (int, float)) or isinstance(b, (int, float)):
        try:
            return float(a) == float(b)
        except (TypeError, ValueError):
            return False
    return a == b


def eval_expr(e: Any, env: Dict[str, Any]) -> Any:
    op = e[0]
    if op == "lit":
        return e[1]
    if op == "path":
        return _get_path(env, e[1])
    if op == "index":
        obj = eval_expr(e[1], env)
        idx = eval_expr(e[2], env)
        if isinstance(obj, dict):
            return obj.get(_str(idx))
        if isinstance(obj, list):
            try:
                return obj[int(idx) - 1]
            except (ValueError, IndexError):
                return None
        return None
    if op == "and":
        return bool(eval_expr(e[1], env)) and bool(eval_expr(e[2], env))
    if op == "or":
        return bool(eval_expr(e[1], env)) or bool(eval_expr(e[2], env))
    if op == "not":
        return not bool(eval_expr(e[1], env))
    if op == "neg":
        return -eval_expr(e[1], env)
    if op in ("=", "!=", ">", "<", ">=", "<="):
        a, b = eval_expr(e[1], env), eval_expr(e[2], env)
        if op == "=":
            return _eq(a, b)
        if op == "!=":
            return not _eq(a, b)
        try:
            if op == ">":
                return a > b
            if op == "<":
                return a < b
            if op == ">=":
                return a >= b
            return a <= b
        except TypeError:
            return False
    if op in ("+", "-", "*", "/", "div", "mod"):
        a, b = eval_expr(e[1], env), eval_expr(e[2], env)
        if op == "+" and (isinstance(a, str) or isinstance(b, str)):
            return _str(a) + _str(b)
        try:
            if op == "+":
                return a + b
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b
            if op == "div":
                return int(a) // int(b)
            return int(a) % int(b)
        except (TypeError, ZeroDivisionError):
            return None
    if op == "in":
        v = eval_expr(e[1], env)
        return any(_eq(v, eval_expr(x, env)) for x in e[2])
    if op == "like":
        return _like(eval_expr(e[1], env), e[2])
    if op == "isnull":
        return eval_expr(e[1], env) is None
    if op == "case":
        for c, v in e[1]:
            if bool(eval_expr(c, env)):
                return eval_expr(v, env)
        return eval_expr(e[2], env)
    if op == "call":
        fn = FUNCS.get(e[1])
        if fn is None:
            raise SqlError(f"unknown function {e[1]!r}")
        if getattr(fn, "_wants_env", False):
            # message-context accessors (clientid(), payload(path), …)
            # read the event env directly, like the reference's
            # closure-over-message builtins (emqx_rule_funcs.erl:317-396)
            return fn(env, *(eval_expr(a, env) for a in e[2]))
        return fn(*(eval_expr(a, env) for a in e[2]))
    raise SqlError(f"bad expr node {op!r}")


def _public_env(env: Dict[str, Any]) -> Dict[str, Any]:
    # engine-internal keys (_proc_dict, _kv_store, _republish_depth)
    # must never appear in rows: a bare `SELECT *` republish would
    # otherwise serialize the whole engine-wide kv store into the
    # published payload
    return {k: v for k, v in env.items() if not k.startswith("_")}


def select_fields(sel: Select, env: Dict[str, Any]) -> Dict[str, Any]:
    """Bind the SELECT list; '*' keeps the (public) env."""
    if not sel.fields:
        return _public_env(env)
    out: Dict[str, Any] = {}
    for expr, alias in sel.fields:
        if expr == ("path", ["*"]):
            out.update(_public_env(env))
            continue
        val = eval_expr(expr, env)
        name = alias or (expr[1][-1] if expr[0] == "path" else "value")
        out[name] = val
    return out


_PLACEHOLDER = re.compile(r"\$\{([^}]+)\}")


def render_template(tpl: str, env: Dict[str, Any]) -> str:
    """${path.to.field} placeholder substitution (emqx_placeholder)."""
    return _PLACEHOLDER.sub(
        lambda m: _str(_get_path(env, m.group(1).split("."))), tpl
    )


# --- rules --------------------------------------------------------------


@dataclass
class RuleMetrics:
    matched: int = 0
    passed: int = 0
    failed: int = 0
    no_result: int = 0
    actions_success: int = 0
    actions_failed: int = 0


@dataclass
class Rule:
    id: str
    sql: str
    select: Select
    actions: List[Dict[str, Any]] = field(default_factory=list)
    enable: bool = True
    description: str = ""
    metrics: RuleMetrics = field(default_factory=RuleMetrics)
    created_at: float = field(default_factory=time.time)


class RuleEngine:
    def __init__(self, broker=None, ignore_sys: bool = True):
        self.broker = broker
        self.ignore_sys = ignore_sys
        self.rules: Dict[str, Rule] = {}
        # FROM-filter index, shared matcher shape with the router
        # (?RULE_TOPIC_INDEX analog)
        self._index = TopicTrie()
        self._event_rules: Dict[str, Set[str]] = {}  # event topic -> rule ids
        self._installed = False
        # named action providers: kind -> fn(args, row, env)
        self.action_providers: Dict[str, Any] = {}
        # per-rule proc dicts + engine-wide kv store (see apply_rule)
        self._proc_dicts: Dict[str, Dict[str, Any]] = {}
        self._kv_store: Dict[str, Any] = {}

    # --- CRUD -----------------------------------------------------------

    def create_rule(
        self,
        rule_id: str,
        sql: str,
        actions: Optional[List[Dict[str, Any]]] = None,
        enable: bool = True,
        description: str = "",
    ) -> Rule:
        if rule_id in self.rules:
            raise ValueError(f"rule {rule_id!r} exists")
        sel = parse(sql)
        rule = Rule(rule_id, sql, sel, actions or [], enable, description)
        self.rules[rule_id] = rule
        for f in sel.froms:
            if ev.is_event_topic(f):
                self._event_rules.setdefault(f, set()).add(rule_id)
            else:
                self._index.insert(topic_mod.words(f), (rule_id, f))
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        rule = self.rules.pop(rule_id, None)
        if rule is None:
            return False
        for f in rule.select.froms:
            if ev.is_event_topic(f):
                self._event_rules.get(f, set()).discard(rule_id)
            else:
                self._index.remove(topic_mod.words(f), (rule_id, f))
        # the proc dict dies with the rule (the reference's erlang
        # proc dict dies with the rule's process); a later rule that
        # reuses the id must start clean
        self._proc_dicts.pop(rule_id, None)
        return True

    def update_rule(self, rule_id: str, **kw) -> Rule:
        old = self.rules.get(rule_id)
        if old is None:
            raise KeyError(rule_id)
        sql = kw.get("sql", old.sql)
        parse(sql)  # validate BEFORE touching the live rule
        actions = kw.get("actions", old.actions)
        enable = kw.get("enable", old.enable)
        desc = kw.get("description", old.description)
        self.delete_rule(rule_id)
        return self.create_rule(rule_id, sql, actions, enable, desc)

    # --- matching -------------------------------------------------------

    def match_rules(self, topic: str) -> List[Rule]:
        # a rule with several FROM filters matching the same topic
        # still fires once (the reference dedups by rule id)
        seen = set()
        out = []
        for rid, _f in self._index.match(topic_mod.words(topic)):
            if rid in seen or rid not in self.rules:
                continue
            seen.add(rid)
            rule = self.rules[rid]
            if rule.enable:
                out.append(rule)
        return out

    def match_rules_batch(self, topics: Sequence[str]) -> List[List[Rule]]:
        """Batch-shaped API so the broker's device dispatch can carry
        rule matching in the same batch (config #5)."""
        return [self.match_rules(t) for t in topics]

    # --- evaluation -----------------------------------------------------

    MAX_REPUBLISH_DEPTH = 8

    def on_message_publish(self, msg: Message, acc=None):
        """'message.publish' hook body (emqx_rule_events.erl:80,118)."""
        if self.ignore_sys and msg.topic.startswith("$SYS/"):
            return None
        depth = int(msg.headers.get("republish_depth", 0))
        if depth >= self.MAX_REPUBLISH_DEPTH:
            log.warning("republish loop cut at depth %d on %s", depth, msg.topic)
            return None
        env = ev.message_event(msg)
        env["_republish_depth"] = depth
        by = msg.headers.get("republish_by")
        for rule in self.match_rules(msg.topic):
            if by is not None and rule.id == by:
                continue  # a rule never re-triggers itself
            self.apply_rule(rule, env)
        return None

    def on_event(self, event_topic: str, env: Dict[str, Any]) -> None:
        for rid in self._event_rules.get(event_topic, ()):
            rule = self.rules.get(rid)
            if rule is not None and rule.enable:
                self.apply_rule(rule, env)

    def apply_rule(self, rule: Rule, env: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
        rule.metrics.matched += 1
        # proc_dict is scoped PER RULE (the reference's erlang proc
        # dict belongs to the evaluating process — rules must not see
        # each other's values); kv_store is engine-wide like the
        # reference's node-global ets (ADVICE r4)
        # a COPY per rule: the caller reuses one env across matching
        # rules, and injecting per-rule state into the shared dict
        # would hand every later rule the first rule's proc dict
        env = dict(env)
        env["_proc_dict"] = self._proc_dicts.setdefault(rule.id, {})
        env["_kv_store"] = self._kv_store
        try:
            sel = rule.select
            rows: List[Dict[str, Any]]
            if sel.foreach is not None:
                coll = eval_expr(sel.foreach[0], env)
                if not isinstance(coll, list):
                    rule.metrics.no_result += 1
                    return None
                alias = sel.foreach[1] or "item"
                rows = []
                for item in coll:
                    ienv = {**env, alias: item, "item": item}
                    if sel.incase is not None and not bool(eval_expr(sel.incase, ienv)):
                        continue
                    if sel.where is not None and not bool(eval_expr(sel.where, ienv)):
                        continue
                    rows.append(select_fields(sel, ienv))
                if not rows:
                    rule.metrics.no_result += 1
                    return None
            else:
                if sel.where is not None and not bool(eval_expr(sel.where, env)):
                    rule.metrics.no_result += 1
                    return None
                rows = [select_fields(sel, env)]
            rule.metrics.passed += 1
        except Exception:
            rule.metrics.failed += 1
            log.exception("rule %s evaluation failed", rule.id)
            return None
        for row in rows:
            self._run_actions(rule, row, env)
        return rows

    def _run_actions(self, rule: Rule, row: Dict[str, Any], env: Dict[str, Any]) -> None:
        for action in rule.actions:
            try:
                self._run_action({**action, "_rule_id": rule.id}, row, env)
                rule.metrics.actions_success += 1
            except Exception:
                rule.metrics.actions_failed += 1
                log.exception("rule %s action %s failed", rule.id, action)

    def _run_action(self, action: Dict[str, Any], row: Dict[str, Any], env: Dict[str, Any]) -> None:
        kind = action.get("function", action.get("type", "console"))
        if kind == "console":
            log.info("[rule console] %s", json.dumps(row, default=_str))
        elif kind == "republish":
            args = action.get("args", {})
            tpl_env = {**env, **row}
            topic = render_template(args.get("topic", "republish/${topic}"), tpl_env)
            payload_tpl = args.get("payload", "${payload}")
            payload = render_template(payload_tpl, tpl_env) if payload_tpl else json.dumps(row, default=_str)
            qos_raw = args.get("qos", 0)
            qos = int(render_template(str(qos_raw), tpl_env)) if isinstance(qos_raw, str) else qos_raw
            if self.broker is None:
                raise RuntimeError("republish without a broker")
            out = Message(
                topic=topic,
                payload=payload.encode() if isinstance(payload, str) else payload,
                qos=qos,
                retain=bool(args.get("retain", False)),
                from_client=f"rule:{action.get('_rule_id', '')}",
            )
            # loop guards: a rule never re-triggers itself, and chains
            # across rules are depth-capped (the reference marks
            # republished messages and warns on loops)
            out.headers["republish_by"] = action.get("_rule_id")
            out.headers["republish_depth"] = int(env.get("_republish_depth", 0)) + 1
            self.broker.publish(out)
        elif callable(kind):
            kind(row, env)
        elif kind in self.action_providers:
            # registered providers (bridges register "bridge" here —
            # the actions-v2 seam of emqx_bridge_v2:send_message)
            self.action_providers[kind](action.get("args", {}), row, env)
        else:
            raise ValueError(f"unknown action {kind!r}")

    # --- wiring + dry run ----------------------------------------------

    def install(self, hooks) -> None:
        if self._installed:
            return
        hooks.add("message.publish", self._hook_cb, priority=50)
        self._installed = True

    def _hook_cb(self, msg, acc=None):
        # run_fold('message.publish', (), msg): single arg is the acc
        m = msg if isinstance(msg, Message) else acc
        if isinstance(m, Message):
            self.on_message_publish(m)
        return None

    def test_sql(self, sql: str, env: Dict[str, Any]) -> Optional[Any]:
        """Dry-run (emqx_rule_sqltester analog): returns the bound
        SELECT result or None if WHERE filtered it out."""
        sel = parse(sql)
        tmp = Rule("$test", sql, sel)
        rows = self.apply_rule(tmp, env)
        if rows is None:
            return None
        return rows[0] if sel.foreach is None else rows
