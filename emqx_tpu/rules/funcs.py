"""Builtin rule functions — emqx_rule_funcs analog.

Full-parity table of the reference's exported builtins
(apps/emqx_rule_engine/src/emqx_rule_funcs.erl:25-283 exports;
string/bit helpers delegate to apps/emqx_utils/src/emqx_variform_bif.erl,
date helpers to emqx_utils_calendar.erl): type conversion, string,
arithmetic/trig, bitwise + subbits, map/array, JSON + Erlang external
term format, time/tz formatting, compression, hashing/encoding/UUID,
topic, conditional, redis/sql arg shaping, proc-dict + kv-store state,
message-context accessors, and a practical jq subset.
"""

from __future__ import annotations

import base64
import hashlib
from .. import jsonc as json  # codec seam: native with stdlib fallback
import math
import os
import re
import struct
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional

from ..ops import topic as topic_mod


def _num(x: Any) -> float:
    if isinstance(x, bool):
        return 1.0 if x else 0.0
    if isinstance(x, (int, float)):
        return x
    return float(x)


def _str(x: Any) -> str:
    if isinstance(x, bytes):
        return x.decode("utf-8", "replace")
    if isinstance(x, bool):
        return "true" if x else "false"
    if x is None:
        return ""
    if isinstance(x, (dict, list)):
        return json.dumps(x)
    return str(x)


FUNCS: Dict[str, Callable[..., Any]] = {}


def func(name: str):
    def deco(f):
        FUNCS[name] = f
        return f

    return deco


# --- type conversion / checks ------------------------------------------

FUNCS["str"] = _str
FUNCS["str_utf8"] = _str
FUNCS["int"] = lambda x: int(_num(x))
FUNCS["float"] = _num
FUNCS["bool"] = lambda x: x in (True, "true", 1)
FUNCS["num"] = _num
FUNCS["is_null"] = lambda x: x is None
FUNCS["is_not_null"] = lambda x: x is not None
FUNCS["is_str"] = lambda x: isinstance(x, str)
FUNCS["is_num"] = lambda x: isinstance(x, (int, float)) and not isinstance(x, bool)
FUNCS["is_int"] = lambda x: isinstance(x, int) and not isinstance(x, bool)
FUNCS["is_float"] = lambda x: isinstance(x, float)
FUNCS["is_bool"] = lambda x: isinstance(x, bool)
FUNCS["is_map"] = lambda x: isinstance(x, dict)
FUNCS["is_array"] = lambda x: isinstance(x, list)

# --- arithmetic ---------------------------------------------------------

FUNCS["abs"] = lambda x: abs(_num(x))
FUNCS["ceil"] = lambda x: math.ceil(_num(x))
FUNCS["floor"] = lambda x: math.floor(_num(x))
FUNCS["round"] = lambda x: round(_num(x))
FUNCS["sqrt"] = lambda x: math.sqrt(_num(x))
FUNCS["exp"] = lambda x: math.exp(_num(x))
FUNCS["power"] = lambda x, y: _num(x) ** _num(y)
FUNCS["log"] = lambda x: math.log(_num(x))
FUNCS["log10"] = lambda x: math.log10(_num(x))
FUNCS["log2"] = lambda x: math.log2(_num(x))
FUNCS["mod"] = lambda x, y: int(_num(x)) % int(_num(y))
FUNCS["range"] = lambda a, b: list(range(int(_num(a)), int(_num(b)) + 1))
FUNCS["random"] = lambda: __import__("random").random()

# --- strings ------------------------------------------------------------

FUNCS["lower"] = lambda s: _str(s).lower()
FUNCS["upper"] = lambda s: _str(s).upper()
FUNCS["trim"] = lambda s: _str(s).strip()
FUNCS["ltrim"] = lambda s: _str(s).lstrip()
FUNCS["rtrim"] = lambda s: _str(s).rstrip()
FUNCS["reverse"] = lambda s: _str(s)[::-1]
FUNCS["strlen"] = lambda s: len(_str(s))
FUNCS["substr"] = lambda s, start, *n: (
    _str(s)[int(start) :] if not n else _str(s)[int(start) : int(start) + int(n[0])]
)
FUNCS["concat"] = lambda *xs: "".join(_str(x) for x in xs)
FUNCS["regex_match"] = lambda s, p: re.search(p, _str(s)) is not None
FUNCS["regex_replace"] = lambda s, p, r: re.sub(p, r, _str(s))
FUNCS["regex_extract"] = lambda s, p: (
    (m := re.search(p, _str(s))) and (m.group(1) if m.groups() else m.group(0)) or ""
)
FUNCS["ascii"] = lambda s: ord(_str(s)[0])
FUNCS["join_to_string"] = lambda sep, xs: _str(sep).join(_str(x) for x in xs)
FUNCS["tokens"] = lambda s, sep: [p for p in _str(s).split(_str(sep)) if p]

# --- maps / arrays ------------------------------------------------------

FUNCS["map_get"] = lambda key, m, *d: (m or {}).get(_str(key), d[0] if d else None)
FUNCS["map_put"] = lambda key, val, m: {**(m or {}), _str(key): val}
FUNCS["map_keys"] = lambda m: list((m or {}).keys())
FUNCS["map_values"] = lambda m: list((m or {}).values())
FUNCS["map_to_entries"] = lambda m: [
    {"key": k, "value": v} for k, v in (m or {}).items()
]
FUNCS["mget"] = FUNCS["map_get"]
FUNCS["mput"] = FUNCS["map_put"]
FUNCS["nth"] = lambda n, xs: xs[int(n) - 1] if 0 < int(n) <= len(xs) else None
FUNCS["length"] = lambda xs: len(xs)
FUNCS["first"] = lambda xs: xs[0] if xs else None
FUNCS["last"] = lambda xs: xs[-1] if xs else None
FUNCS["contains"] = lambda x, xs: x in xs


# --- JSON ---------------------------------------------------------------


@func("json_decode")
def _json_decode(s):
    if isinstance(s, (dict, list)):
        return s
    if isinstance(s, bytes):
        s = s.decode("utf-8", "replace")
    return json.loads(s)


FUNCS["json_encode"] = lambda x: json.dumps(x, separators=(",", ":"))

# --- time ---------------------------------------------------------------


# --- hashing / encoding -------------------------------------------------

FUNCS["md5"] = lambda s: hashlib.md5(_b(s)).hexdigest()
FUNCS["sha"] = lambda s: hashlib.sha1(_b(s)).hexdigest()
FUNCS["sha256"] = lambda s: hashlib.sha256(_b(s)).hexdigest()
FUNCS["base64_encode"] = lambda s: base64.b64encode(_b(s)).decode()
FUNCS["base64_decode"] = lambda s: base64.b64decode(_str(s)).decode("utf-8", "replace")
FUNCS["hexstr"] = lambda s: _b(s).hex()
FUNCS["bitsize"] = lambda s: len(_b(s)) * 8
FUNCS["bytesize"] = lambda s: len(_b(s))
FUNCS["byteszie"] = FUNCS["bytesize"]  # reference's typo'd alias
FUNCS["uuid_v4"] = lambda: str(uuid.uuid4())
FUNCS["crc32"] = lambda s: __import__("zlib").crc32(_b(s))


def _b(x: Any) -> bytes:
    if isinstance(x, bytes):
        return x
    return _str(x).encode()


# --- topic helpers ------------------------------------------------------

FUNCS["topic_match"] = lambda t, f: topic_mod.match(
    topic_mod.words(_str(t)), topic_mod.words(_str(f))
)


@func("nth_topic_level")
def _nth_level(n, t):
    ws = topic_mod.words(_str(t))
    n = int(n)
    return ws[n - 1] if 0 < n <= len(ws) else None


FUNCS["topic_levels"] = lambda t: topic_mod.words(_str(t))

# --- conditional --------------------------------------------------------

FUNCS["iif"] = lambda c, a, b: a if c in (True, "true") else b

# --- schema registry (emqx_schema_registry_serde rule functions) --------


def _schema_registry():
    from ..transform.registry import default_registry

    return default_registry()


@func("schema_decode")
def _schema_decode(name, payload):
    data = payload.encode() if isinstance(payload, str) else bytes(payload)
    return _schema_registry().check_payload(_str(name), data)


@func("schema_encode")
def _schema_encode(name, value):
    return _schema_registry().encode_payload(_str(name), value)


@func("schema_check")
def _schema_check(name, payload):
    try:
        data = payload.encode() if isinstance(payload, str) else bytes(payload)
        _schema_registry().check_payload(_str(name), data)
        return True
    except Exception:
        return False


# ======================================================================
# Full-parity additions (VERDICT r3 item 7): the remaining reference
# exports, table-driven-tested in tests/test_rule_funcs_parity.py.
# ======================================================================

# --- trig / math (emqx_rule_funcs.erl math section) ---------------------

for _name in ("acos", "acosh", "asin", "asinh", "atan", "atanh", "cos",
              "cosh", "sin", "sinh", "tan", "tanh"):
    FUNCS[_name] = (lambda f: lambda x: f(_num(x)))(getattr(math, _name))
FUNCS["fmod"] = lambda x, y: math.fmod(_num(x), _num(y))
def _erl_div(x, y):
    # Erlang div truncates toward ZERO (Python // floors)
    a, b = int(_num(x)), int(_num(y))
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


FUNCS["div"] = _erl_div
FUNCS["eq"] = lambda x, y: x == y
FUNCS["null"] = lambda: None

# --- bitwise + subbits --------------------------------------------------

FUNCS["bitand"] = lambda x, y: int(_num(x)) & int(_num(y))
FUNCS["bitor"] = lambda x, y: int(_num(x)) | int(_num(y))
FUNCS["bitxor"] = lambda x, y: int(_num(x)) ^ int(_num(y))
FUNCS["bitnot"] = lambda x: ~int(_num(x))
FUNCS["bitsl"] = lambda x, n: int(_num(x)) << int(_num(n))
FUNCS["bitsr"] = lambda x, n: int(_num(x)) >> int(_num(n))


@func("subbits")
def _subbits(data, *args):
    """subbits(Bits, Len) / (Bits, Start, Len[, Type[, Signedness[,
    Endianness]]]) — 1-based bit offsets, like the reference
    (emqx_rule_funcs.erl:596-707). Type: integer|float|bits."""
    raw = _b(data)
    if len(args) == 1:
        start, length = 1, int(args[0])
        typ, signed, endian = "integer", "unsigned", "big"
    else:
        start, length = int(args[0]), int(args[1])
        typ = _str(args[2]) if len(args) > 2 else "integer"
        signed = _str(args[3]) if len(args) > 3 else "unsigned"
        endian = _str(args[4]) if len(args) > 4 else "big"
    nbits = len(raw) * 8
    if start < 1 or length < 0 or start - 1 + length > nbits:
        return None
    whole = int.from_bytes(raw, "big")
    chunk = (whole >> (nbits - (start - 1) - length)) & ((1 << length) - 1)
    if typ == "bits":
        # bit-exact slice, returned as bytes (pad to byte boundary)
        nbytes = (length + 7) // 8
        return (chunk << (nbytes * 8 - length)).to_bytes(nbytes, "big")
    if endian == "little":
        # Erlang bit-syntax little-endian: the FIRST 8 bits of the
        # stream are the least-significant byte; a trailing partial
        # byte is most significant (<<16#12, 16#3:4>> :12/little ->
        # 16#312). Byte-padding then swapping diverges for lengths
        # that aren't a multiple of 8.
        nfull, rbits = divmod(length, 8)
        val = 0
        stream = chunk
        if rbits:
            partial = stream & ((1 << rbits) - 1)
            stream >>= rbits
            val = partial << (8 * nfull)
        for i in range(nfull):
            byte = (stream >> (8 * (nfull - 1 - i))) & 0xFF
            val |= byte << (8 * i)
        chunk = val
    if typ == "float":
        if length == 32:
            return struct.unpack(">f", chunk.to_bytes(4, "big"))[0]
        if length == 64:
            return struct.unpack(">d", chunk.to_bytes(8, "big"))[0]
        return None
    if length and signed == "signed" and chunk >= 1 << (length - 1):
        chunk -= 1 << length
    return chunk


# --- strings ------------------------------------------------------------


@func("float2str")
def _float2str(x, precision):
    # float_to_binary(F, [{decimals, P}, compact]) trims trailing zeros
    # but keeps at least one decimal
    s = f"{_num(x):.{int(precision)}f}"
    if "." in s:
        s = s.rstrip("0")
        if s.endswith("."):
            s += "0"
    return s


def _pad(s, n, position="trailing", char=" "):
    s, n, char = _str(s), int(n), _str(char) or " "
    fill = n - len(s)
    if fill <= 0:
        return s
    pad = (char * fill)[:fill]
    if position == "leading":
        return pad + s
    if position == "both":
        left = fill // 2
        return (char * left)[:left] + s + (char * (fill - left))[: fill - left]
    return s + pad


FUNCS["pad"] = _pad


@func("replace")
def _replace(s, pat, rep, where="all"):
    s, pat, rep = _str(s), _str(pat), _str(rep)
    if where == "leading":
        return s.replace(pat, rep, 1)
    if where == "trailing":
        i = s.rfind(pat)
        return s if i < 0 else s[:i] + rep + s[i + len(pat):]
    return s.replace(pat, rep)


@func("find")
def _find(s, sub, direction="leading"):
    s, sub = _str(s), _str(sub)
    i = s.rfind(sub) if _str(direction) == "trailing" else s.find(sub)
    return s[i:] if i >= 0 else ""


@func("split")
def _split(s, sep=" ", mode=None):
    s, sep = _str(s), _str(sep)
    mode = _str(mode) if mode is not None else None
    if mode is None:
        return [p for p in s.split(sep) if p != ""]
    if mode == "notrim":
        return s.split(sep)
    if mode == "leading_notrim":
        return s.split(sep, 1)
    if mode == "leading":
        return [p for p in s.split(sep, 1) if p != ""]
    if mode == "trailing_notrim":
        return s.rsplit(sep, 1)
    if mode == "trailing":
        return [p for p in s.rsplit(sep, 1) if p != ""]
    return [p for p in s.split(sep) if p != ""]


@func("rm_prefix")
def _rm_prefix(s, prefix):
    s, prefix = _str(s), _str(prefix)
    return s[len(prefix):] if s.startswith(prefix) else s


@func("sprintf_s")
def _sprintf_s(fmt, args=None):
    """Erlang io_lib:format subset: ~s ~ts ~p ~w ~b ~n ~~."""
    out, i, ai = [], 0, 0
    fmt = _str(fmt)
    args = list(args or [])
    while i < len(fmt):
        c = fmt[i]
        if c != "~":
            out.append(c)
            i += 1
            continue
        i += 1
        spec = fmt[i] if i < len(fmt) else ""
        if spec == "t" and i + 1 < len(fmt):
            i += 1
            spec = fmt[i]
        i += 1
        if spec == "~":
            out.append("~")
        elif spec == "n":
            out.append("\n")
        elif spec == "s":
            out.append(_str(args[ai])); ai += 1
        elif spec in ("p", "w"):
            a = args[ai]; ai += 1
            out.append(json.dumps(a) if isinstance(a, (dict, list)) else _str(a))
        elif spec in ("b", "B"):
            out.append(str(int(_num(args[ai])))); ai += 1
        else:
            out.append(spec)
    return "".join(out)


FUNCS["sprintf"] = lambda fmt, *xs: _sprintf_s(fmt, list(xs))


@func("unescape")
def _unescape(s):
    """C-style escapes (emqx_variform_bif.erl:291-345): \\n \\t \\r
    \\b \\f \\v \\' \\" \\? \\a \\\\ and \\xHH hex."""
    src = _str(s)
    out, i = [], 0
    simple = {"\\": "\\", "n": "\n", "t": "\t", "r": "\r", "b": "\b",
              "f": "\f", "v": "\v", "'": "'", '"': '"', "?": "?",
              "a": "\a"}
    while i < len(src):
        c = src[i]
        if c != "\\":
            out.append(c)
            i += 1
            continue
        if i + 1 >= len(src):
            raise ValueError("dangling backslash")
        n = src[i + 1]
        if n in simple:
            out.append(simple[n])
            i += 2
        elif n == "x":
            j = i + 2
            while j < len(src) and src[j] in "0123456789abcdefABCDEF":
                j += 1
            if j == i + 2:
                raise ValueError("invalid hex escape")
            out.append(chr(int(src[i + 2 : j], 16)))
            i = j
        else:
            raise ValueError(f"unrecognized escape \\{n}")
    return "".join(out)


@func("str_utf16_le")
def _str_utf16_le(s):
    return _str(s).encode("utf-16-le")


# --- hex ----------------------------------------------------------------

FUNCS["bin2hexstr"] = lambda b, prefix=None: (
    (_str(prefix) if prefix is not None else "") + _b(b).hex().upper()
)


@func("hexstr2bin")
def _hexstr2bin(s, prefix=None):
    s = _str(s)
    if prefix is not None and s.startswith(_str(prefix)):
        s = s[len(_str(prefix)):]
    return bytes.fromhex(s)


FUNCS["sqlserver_bin2hexstr"] = lambda b: "0x" + _b(b).hex().upper()

# --- compression --------------------------------------------------------

def _zcompress(data, wbits):
    # zlib.compress() grew its wbits kwarg in 3.11; compressobj works on 3.10
    co = zlib.compressobj(wbits=wbits)
    return co.compress(data) + co.flush()


FUNCS["gzip"] = lambda s: _zcompress(_b(s), wbits=31)
FUNCS["gunzip"] = lambda s: zlib.decompress(_b(s), wbits=31)
FUNCS["zip"] = lambda s: _zcompress(_b(s), wbits=-15)  # raw deflate
FUNCS["unzip"] = lambda s: zlib.decompress(_b(s), wbits=-15)
FUNCS["zip_compress"] = lambda s: zlib.compress(_b(s))  # zlib-wrapped
FUNCS["zip_uncompress"] = lambda s: zlib.decompress(_b(s))

# --- maps / arrays ------------------------------------------------------

FUNCS["map_new"] = lambda: {}
FUNCS["map_size"] = lambda m: len(m or {})


@func("map")
def _map(x):
    if isinstance(x, dict):
        return x
    if isinstance(x, (bytes, str)):
        v = json.loads(_str(x))
        if not isinstance(v, dict):
            raise ValueError("map(): JSON is not an object")
        return v
    if isinstance(x, list):
        return {
            _str(k): v
            for k, v in (
                (e[0], e[1]) if isinstance(e, (list, tuple))
                else (e.get("key"), e.get("value"))
                for e in x
            )
        }
    raise ValueError("map(): bad argument")


@func("sublist")
def _sublist(*args):
    if len(args) == 2:
        n, xs = args
        return list(xs)[: int(n)]
    start, n, xs = args  # 1-based start like lists:sublist/3
    return list(xs)[int(start) - 1 : int(start) - 1 + int(n)]


FUNCS["is_empty"] = lambda x: (
    x is None or x == "" or x == b"" or x == [] or x == {}
)
FUNCS["is_null_var"] = lambda x: x is None or x == "undefined"
FUNCS["is_not_null_var"] = lambda x: not FUNCS["is_null_var"](x)


@func("coalesce_ne")
def _coalesce_ne(*xs):
    vals = xs[0] if len(xs) == 1 and isinstance(xs[0], list) else xs
    for v in vals:
        if v is not None and v != "" and v != b"":
            return v
    return None


@func("coalesce")
def _coalesce(*xs):
    vals = xs[0] if len(xs) == 1 and isinstance(xs[0], list) else xs
    for v in vals:
        if v is not None:
            return v
    return None


# --- redis / sql arg shaping --------------------------------------------


@func("map_to_redis_hset_args")
def _map_to_redis_hset_args(payload):
    """Flatten a map to [field, value, ...] for HSET (floats get
    6-decimal compact formatting, emqx_rule_funcs.erl:901-938)."""
    if isinstance(payload, (str, bytes)):
        try:
            payload = json.loads(_str(payload))
        except Exception:
            return []
    if not isinstance(payload, dict):
        return []
    out = []
    for k, v in payload.items():
        if isinstance(v, bool):
            out += [_str(k), "true" if v else "false"]
        elif isinstance(v, float):
            out += [_str(k), _float2str(v, 6)]
        elif isinstance(v, (int, str, bytes)):
            out += [_str(k), _str(v)]
    return out


def _quote_sql(v):
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return _str(v)
    if isinstance(v, (list, dict)):
        v = json.dumps(v)
    return "'" + _str(v).replace("'", "''") + "'"


FUNCS["join_to_sql_values_string"] = lambda xs: ", ".join(
    _quote_sql(x) for x in xs
)

# --- Erlang external term format (term_to_binary parity) ----------------


def _etf_encode(x) -> bytes:
    def enc(v):
        if v is None:
            return b"\x77\x09undefined"  # SMALL_ATOM_UTF8
        if v is True:
            return b"\x77\x04true"
        if v is False:
            return b"\x77\x05false"
        if isinstance(v, int):
            if 0 <= v <= 255:
                return b"\x61" + bytes([v])
            if -(1 << 31) <= v < (1 << 31):
                return b"\x62" + struct.pack(">i", v)
            # SMALL_BIG_EXT
            sign = 1 if v < 0 else 0
            mag = abs(v)
            nb = (mag.bit_length() + 7) // 8
            return b"\x6e" + bytes([nb, sign]) + mag.to_bytes(nb, "little")
        if isinstance(v, float):
            return b"\x46" + struct.pack(">d", v)
        if isinstance(v, str):
            v = v.encode()
        if isinstance(v, bytes):
            return b"\x6d" + struct.pack(">I", len(v)) + v
        if isinstance(v, (list, tuple)):
            if not v:
                return b"\x6a"  # NIL
            return (
                b"\x6c" + struct.pack(">I", len(v))
                + b"".join(enc(e) for e in v) + b"\x6a"
            )
        if isinstance(v, dict):
            return b"\x74" + struct.pack(">I", len(v)) + b"".join(
                enc(k) + enc(val) for k, val in v.items()
            )
        raise ValueError(f"term_encode: unsupported {type(v).__name__}")

    return b"\x83" + enc(x)


def _etf_decode(data: bytes):
    buf = memoryview(_b(data))
    if not buf or buf[0] != 0x83:
        raise ValueError("not an external term")

    def dec(pos):
        tag = buf[pos]
        pos += 1
        if tag == 0x61:
            return buf[pos], pos + 1
        if tag == 0x62:
            return struct.unpack_from(">i", buf, pos)[0], pos + 4
        if tag == 0x6E:
            nb, sign = buf[pos], buf[pos + 1]
            mag = int.from_bytes(bytes(buf[pos + 2 : pos + 2 + nb]), "little")
            return (-mag if sign else mag), pos + 2 + nb
        if tag == 0x46:
            return struct.unpack_from(">d", buf, pos)[0], pos + 8
        if tag in (0x77, 0x73):  # SMALL_ATOM_UTF8 / SMALL_ATOM
            n = buf[pos]
            name = bytes(buf[pos + 1 : pos + 1 + n]).decode()
            v = {"true": True, "false": False, "undefined": None}.get(
                name, name
            )
            return v, pos + 1 + n
        if tag in (0x76, 0x64):  # ATOM_UTF8 / ATOM_EXT (2-byte len)
            n = struct.unpack_from(">H", buf, pos)[0]
            name = bytes(buf[pos + 2 : pos + 2 + n]).decode()
            v = {"true": True, "false": False, "undefined": None}.get(
                name, name
            )
            return v, pos + 2 + n
        if tag == 0x6D:
            n = struct.unpack_from(">I", buf, pos)[0]
            return bytes(buf[pos + 4 : pos + 4 + n]), pos + 4 + n
        if tag == 0x6A:
            return [], pos
        if tag == 0x6C:
            n = struct.unpack_from(">I", buf, pos)[0]
            pos += 4
            out = []
            for _ in range(n):
                v, pos = dec(pos)
                out.append(v)
            tail, pos = dec(pos)
            if tail != []:
                out.append(tail)  # improper list: keep the tail
            return out, pos
        if tag == 0x6B:  # STRING_EXT: list of small ints
            n = struct.unpack_from(">H", buf, pos)[0]
            return list(bytes(buf[pos + 2 : pos + 2 + n])), pos + 2 + n
        if tag == 0x74:
            n = struct.unpack_from(">I", buf, pos)[0]
            pos += 4
            out = {}
            for _ in range(n):
                k, pos = dec(pos)
                v, pos = dec(pos)
                if isinstance(k, bytes):
                    k = k.decode("utf-8", "replace")
                out[k] = v
            return out, pos
        raise ValueError(f"term_decode: unsupported tag {tag}")

    v, _pos = dec(1)
    return v


FUNCS["term_encode"] = _etf_encode
FUNCS["term_decode"] = _etf_decode

# --- time / timezone ----------------------------------------------------

_UNIT_S = {"second": 1, "millisecond": 10**3, "microsecond": 10**6,
           "nanosecond": 10**9}


def _unit_mult(unit) -> int:
    u = _str(unit) if unit is not None else "second"
    if u not in _UNIT_S:
        raise ValueError(f"bad time unit {u!r}")
    return _UNIT_S[u]


@func("timezone_to_offset_seconds")
def _tz_offset(tz):
    tz = _str(tz)
    if tz in ("Z", "z", "utc", "UTC", ""):
        return 0
    if tz == "local":
        # altzone is the DST-adjusted offset; hardcoding +3600 breaks
        # half-hour-DST zones (Lord Howe)
        if time.daylight and time.localtime().tm_isdst:
            return -time.altzone
        return -time.timezone
    m = re.fullmatch(r"([+-])(\d{2}):?(\d{2})(?::?(\d{2}))?", tz)
    if not m:
        raise ValueError(f"bad timezone {tz!r}")
    sign = -1 if m.group(1) == "-" else 1
    return sign * (
        int(m.group(2)) * 3600 + int(m.group(3)) * 60 + int(m.group(4) or 0)
    )


FUNCS["timezone_to_second"] = _tz_offset


def _fmt_epoch(epoch, unit_mult: int, offset_s: int, fmt: str) -> str:
    """emqx_utils_calendar format tokens: %Y %m %d %H %M %S %N(ns)
    %3N(ms) %6N(us) %z(+0800) %:z(+08:00). Integer arithmetic
    throughout — nanosecond epochs (~1e18) lose digits past float53."""
    whole, rem = divmod(int(epoch), unit_mult)
    frac_ns = rem * (10**9 // unit_mult)
    t = time.gmtime(whole + offset_s)
    sign = "+" if offset_s >= 0 else "-"
    oh, om = divmod(abs(offset_s) // 60, 60)
    reps = {
        "%Y": f"{t.tm_year:04d}", "%m": f"{t.tm_mon:02d}",
        "%d": f"{t.tm_mday:02d}", "%H": f"{t.tm_hour:02d}",
        "%M": f"{t.tm_min:02d}", "%S": f"{t.tm_sec:02d}",
        "%6N": f"{frac_ns // 1000:06d}", "%3N": f"{frac_ns // 1000000:03d}",
        "%N": f"{frac_ns:09d}",
        "%:z": f"{sign}{oh:02d}:{om:02d}", "%z": f"{sign}{oh:02d}{om:02d}",
    }
    out = fmt
    for k in ("%6N", "%3N", "%N", "%:z", "%z", "%Y", "%m", "%d", "%H",
              "%M", "%S"):
        out = out.replace(k, reps[k])
    return out


@func("format_date")
def _format_date(unit, offset, fmt, epoch=None):
    mult = _unit_mult(unit)
    if epoch is None:
        epoch = int(time.time() * mult)
    off = offset if isinstance(offset, int) else _tz_offset(offset)
    return _fmt_epoch(int(_num(epoch)), mult, off, _str(fmt))


@func("date_to_unix_ts")
def _date_to_unix_ts(unit, *args):
    """(unit, fmt, input) or (unit, offset, fmt, input)."""
    mult = _unit_mult(unit)
    if len(args) == 2:
        fmt, inp = args
        offset = None
    else:
        offset, fmt, inp = args
    fmt, inp = _str(fmt), _str(inp)
    # translate the calendar tokens to a regex, capture parts
    token_re = {
        "%Y": r"(?P<Y>\d{4})", "%m": r"(?P<m>\d{1,2})",
        "%d": r"(?P<d>\d{1,2})", "%H": r"(?P<H>\d{1,2})",
        "%M": r"(?P<M>\d{1,2})", "%S": r"(?P<S>\d{1,2})",
        "%6N": r"(?P<us>\d{1,6})", "%3N": r"(?P<ms>\d{1,3})",
        "%N": r"(?P<ns>\d{1,9})",
        "%:z": r"(?P<tz>Z|[+-]\d{2}:\d{2})",
        "%z": r"(?P<tz>Z|[+-]\d{4})",
    }
    pat = ""
    i = 0
    while i < len(fmt):
        for tok in ("%6N", "%3N", "%:z", "%N", "%z", "%Y", "%m", "%d",
                    "%H", "%M", "%S"):
            if fmt.startswith(tok, i):
                pat += token_re[tok]
                i += len(tok)
                break
        else:
            pat += re.escape(fmt[i])
            i += 1
    m = re.fullmatch(pat, inp)
    if not m:
        raise ValueError(f"date {inp!r} does not match format {fmt!r}")
    g = m.groupdict()
    import calendar as _cal

    base = _cal.timegm((
        int(g.get("Y") or 1970), int(g.get("m") or 1), int(g.get("d") or 1),
        int(g.get("H") or 0), int(g.get("M") or 0), int(g.get("S") or 0),
        0, 0, 0,
    ))
    # integer nanoseconds: float arithmetic loses digits past 2^53
    # (nanosecond epochs are ~1e18)
    ns = 0
    if g.get("ns"):
        ns = int(g["ns"])
    elif g.get("us"):
        ns = int(g["us"]) * 1000
    elif g.get("ms"):
        ns = int(g["ms"]) * 1_000_000
    tz = g.get("tz")
    if tz:
        base -= _tz_offset(tz)
    out = base * mult + ns * mult // 10**9
    if offset is not None and not tz:
        off_s = offset if isinstance(offset, int) else _tz_offset(offset)
        out -= int(off_s) * mult
    return out


@func("rfc3339_to_unix_ts")
def _rfc3339_to_unix_ts(s, unit=None):
    import calendar as _cal

    mult = _unit_mult(unit)
    m = re.fullmatch(
        r"(\d{4})-(\d{2})-(\d{2})[Tt ]"
        r"(\d{2}):(\d{2}):(\d{2})(?:[.,](\d{1,9}))?"
        r"(Z|z|[+-]\d{2}:?\d{2})?",
        _str(s),
    )
    if not m:
        raise ValueError(f"bad RFC3339 datetime {s!r}")
    y, mo, d, h, mi, sec, frac, tz = m.groups()
    base = _cal.timegm(
        (int(y), int(mo), int(d), int(h), int(mi), int(sec), 0, 0, 0)
    )
    if tz and tz not in ("Z", "z"):
        base -= _tz_offset(tz)
    # exact integer nanoseconds (float timestamp() loses sub-us digits)
    ns = int(frac.ljust(9, "0")) if frac else 0
    return base * mult + ns * mult // 10**9


@func("unix_ts_to_rfc3339")
def _unix_ts_to_rfc3339(epoch, unit=None):
    mult = _unit_mult(unit)
    fmt = {1: "%Y-%m-%dT%H:%M:%S",
           10**3: "%Y-%m-%dT%H:%M:%S.%3N",
           10**6: "%Y-%m-%dT%H:%M:%S.%6N",
           10**9: "%Y-%m-%dT%H:%M:%S.%N"}[mult]
    off = _tz_offset("local")
    return _fmt_epoch(int(_num(epoch)), mult, off, fmt) + _fmt_epoch(
        0, 1, off, "%:z"
    )


@func("now_rfc3339")
def _now_rfc3339(unit=None):
    mult = _unit_mult(unit)
    return _unix_ts_to_rfc3339(int(time.time() * mult), unit)


FUNCS["now_timestamp"] = lambda unit=None: int(
    time.time() * _unit_mult(unit)
)


@func("mongo_date")
def _mongo_date(ts=None, unit=None):
    if ts is None:
        ms = int(time.time() * 1000)
    elif unit is not None:
        ms = int(_num(ts)) * 1000 // _unit_mult(unit)
    else:
        ms = int(_num(ts))  # bare timestamp is milliseconds
    iso = _fmt_epoch(ms, 1000, 0, "%Y-%m-%dT%H:%M:%S.%3N+00:00")
    return f"ISODate({iso})"


# --- UUID / hashing -----------------------------------------------------

FUNCS["uuid_v4_no_hyphen"] = lambda: uuid.uuid4().hex


@func("hash")
def _hash(alg, data):
    alg = _str(alg).lower()
    alg = {"sha1": "sha1", "sha": "sha1"}.get(alg, alg)
    return hashlib.new(alg, _b(data)).hexdigest()


# --- topic --------------------------------------------------------------


@func("contains_topic")
def _contains_topic(filters, topic):
    # exact-name membership; wildcard semantics live in
    # contains_topic_match (emqx_rule_funcs.erl contains_topic/2)
    want = _str(topic)
    for f in filters or []:
        name = f.get("topic") if isinstance(f, dict) else f
        if _str(name) == want:
            return True
    return False


@func("contains_topic_match")
def _contains_topic_match(filters, topic):
    t = topic_mod.words(_str(topic))
    for f in filters or []:
        name = f.get("topic") if isinstance(f, dict) else f
        if topic_mod.match(t, topic_mod.words(_str(name))):
            return True
    return False


# --- state: proc dict + kv store ---------------------------------------
# The reference scopes the proc dict to the evaluating rule's process
# (emqx_rule_funcs proc_dict over erlang:put/get) — rules must not
# observe each other's values — while kv_store is node-global ets.
# Both therefore resolve through the ENV the engine passes (ADVICE
# r4): apply_rule injects "_proc_dict" (per rule id) and "_kv_store"
# (per engine). The module-level fallbacks only serve direct FUNCS
# calls outside an engine (tests/tools).

_PROC_DICT: Dict[str, Any] = {}
_KV_STORE: Dict[str, Any] = {}


def _env_state(env, key, fallback):
    d = env.get(key)
    return d if d is not None else fallback


def env_func(name: str):
    """Register an env-aware func (the engine prepends the event env;
    also used by the message-context accessors below)."""

    def deco(f):
        f._wants_env = True
        FUNCS[name] = f
        return f

    return deco


@env_func("proc_dict_get")
def _proc_dict_get(env, k):
    return _env_state(env, "_proc_dict", _PROC_DICT).get(_str(k))


@env_func("proc_dict_put")
def _proc_dict_put(env, k, v):
    _env_state(env, "_proc_dict", _PROC_DICT)[_str(k)] = v


@env_func("proc_dict_del")
def _proc_dict_del(env, k):
    _env_state(env, "_proc_dict", _PROC_DICT).pop(_str(k), None)


@env_func("kv_store_get")
def _kv_store_get(env, k, *d):
    return _env_state(env, "_kv_store", _KV_STORE).get(
        _str(k), d[0] if d else None
    )


@env_func("kv_store_put")
def _kv_store_put(env, k, v):
    _env_state(env, "_kv_store", _KV_STORE)[_str(k)] = v


@env_func("kv_store_del")
def _kv_store_del(env, k):
    _env_state(env, "_kv_store", _KV_STORE).pop(_str(k), None)

# --- system -------------------------------------------------------------

FUNCS["getenv"] = lambda name: os.environ.get("EMQXVAR_" + _str(name))

# --- message-context accessors (engine passes env via _wants_env) -------


@env_func("msgid")
def _msgid(env):
    return env.get("id")


@env_func("qos")
def _qos(env):
    return env.get("qos")


@env_func("topic")
def _topic(env, n=None):
    t = env.get("topic")
    if n is None or t is None:
        return t
    ws = topic_mod.words(_str(t))
    n = int(n)
    return ws[n - 1] if 0 < n <= len(ws) else None


@env_func("flags")
def _flags(env):
    return env.get("flags") or {}


@env_func("flag")
def _flag(env, name):
    return (env.get("flags") or {}).get(_str(name))


@env_func("clientid")
def _clientid(env):
    return env.get("clientid") or env.get("from")


@env_func("username")
def _username(env):
    return env.get("username")


@env_func("peerhost")
def _peerhost(env):
    return env.get("peerhost")


FUNCS["clientip"] = FUNCS["peerhost"]


@env_func("payload")
def _payload(env, path=None):
    p = env.get("payload")
    if path is None:
        return p
    if isinstance(p, (str, bytes)):
        try:
            p = json.loads(_str(p))
        except Exception:
            return None
    for key in _str(path).split("."):
        if not isinstance(p, dict):
            return None
        p = p.get(key)
    return p


# --- jq (practical subset of the optional jq port) ----------------------


@func("jq")
def _jq(prog, data, _timeout_ms=None):
    """Subset: identity, field paths, array iteration/index, pipes,
    select(.path OP literal). Anything else raises (like the reference
    throws jq_exception on errors)."""
    if isinstance(data, (str, bytes)):
        data = json.loads(_str(data))

    def apply(term, inputs):
        term = term.strip()
        if term in (".", ""):
            return inputs
        m = re.fullmatch(
            r"select\(\s*\.([\w.]*)\s*(==|!=|>|<|>=|<=)\s*(.+?)\s*\)", term
        )
        if m:
            path, op, lit = m.groups()
            lit = json.loads(lit)
            out = []
            for v in inputs:
                cur = v
                for k in filter(None, path.split(".")):
                    cur = cur.get(k) if isinstance(cur, dict) else None
                ok = {
                    "==": cur == lit, "!=": cur != lit,
                    ">": cur is not None and cur > lit,
                    "<": cur is not None and cur < lit,
                    ">=": cur is not None and cur >= lit,
                    "<=": cur is not None and cur <= lit,
                }[op]
                if ok:
                    out.append(v)
            return out
        # path expression: .a.b[0].c[] ...
        if not re.fullmatch(r"\.(?:[\w]+|\[\d*\])(?:\.?[\w]+|\[\d*\])*|\.", term):
            raise ValueError(f"jq: unsupported program {term!r}")
        out = inputs
        for step in re.findall(r"\.?([\w]+)|\[(\d*)\]", term):
            key, idx = step
            nxt = []
            for v in out:
                if key:
                    nxt.append(v.get(key) if isinstance(v, dict) else None)
                elif idx == "":
                    if isinstance(v, list):
                        nxt.extend(v)
                elif isinstance(v, list) and int(idx) < len(v):
                    nxt.append(v[int(idx)])
            out = nxt
        return out

    results = [data]
    for part in _str(prog).split("|"):
        results = apply(part, results)
    return results
